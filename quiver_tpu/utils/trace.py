"""Tracing, timing, and structured logging.

Capability parity with the reference's observability layer (SURVEY §5):

- ``TRACE_SCOPE`` macros (torch-quiver trace.hpp:6-14) — compiled to no-ops
  unless ``QUIVER_ENABLE_TRACE`` is set — become :func:`trace_scope`, which
  annotates both the host timeline (``jax.profiler.TraceAnnotation``) and the
  XLA program (``jax.named_scope``) and is a no-op unless tracing is enabled
  via the same ``QUIVER_ENABLE_TRACE`` env var or :func:`enable_trace`.
- the RAII wall-clock ``timer`` (timer.hpp:7-28) becomes :class:`Timer`.
- the ad-hoc ``"LOG>>>"`` prints (feature.py:109-111, shard_tensor.py:69-71)
  become a real structured logger under the ``quiver_tpu`` namespace.
- profile *collection* (the stdtracer role, fetch_stdtracer.cmake:11-17) is
  :func:`start_trace`/:func:`stop_trace` over ``jax.profiler`` — the result
  opens in TensorBoard/Perfetto instead of a text dump.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import time

import jax

__all__ = [
    "enable_trace",
    "disable_trace",
    "trace_enabled",
    "trace_scope",
    "Timer",
    "get_logger",
    "info_once",
    "reset_once",
    "warn_once",
    "start_trace",
    "stop_trace",
]

_TRACE_ENV = "QUIVER_ENABLE_TRACE"
_enabled: bool | None = None  # None = consult env var


def trace_enabled() -> bool:
    if _enabled is not None:
        return _enabled
    # env is only the initial default — enable_trace()/disable_trace() are
    # the live switches, and an in-trace read only gates the trace-time
    # profiler annotation (no runtime behavior depends on it)
    # graftlint: disable=env-at-trace -- initial default; enable_trace() is the live switch
    return os.environ.get(_TRACE_ENV, "0") not in ("", "0", "false", "False")


def enable_trace() -> None:
    """Turn trace scopes on for this process (overrides the env var)."""
    global _enabled
    _enabled = True


def disable_trace() -> None:
    global _enabled
    _enabled = False


@contextlib.contextmanager
def trace_scope(name: str):
    """Annotate a region on the host profiler timeline and in the jaxpr.

    No-op (zero overhead beyond one branch) unless tracing is enabled,
    mirroring the reference's compile-time-gated TRACE_SCOPE. Usable around
    both eager host code (shows up as a TraceAnnotation slice) and traced
    code (names the XLA ops for the device timeline).
    """
    if not trace_enabled():
        yield
        return
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


class Timer:
    """RAII wall-clock timer (reference timer.hpp:7-28 parity).

    >>> with Timer("sample") as t:
    ...     out = sampler.sample(seeds)
    prints ``[sample] 12.3 ms`` at scope exit (via the package logger) and
    leaves the duration in ``t.seconds``.

    ``registry=`` feeds the measured duration to an aggregator with an
    ``observe(name, seconds)`` method — an ``obs.StepTimeline`` (or a
    ``MetricsRegistry`` adapter) — so existing ``Timer("sample", sync=...)``
    call sites join the graftscope step timeline instead of only logging;
    ``metric=`` overrides the stage name fed to it.
    """

    def __init__(self, name: str, sync=None, quiet: bool = False,
                 registry=None, metric: str | None = None):
        self.name = name
        self.seconds = 0.0
        self._sync = sync  # optional array/pytree to block_until_ready on exit
        self._quiet = quiet
        self._registry = registry
        self._metric = metric or name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._sync is not None:
            jax.block_until_ready(self._sync)
        self.seconds = time.perf_counter() - self._t0
        if not self._quiet:
            get_logger().info("[%s] %.1f ms", self.name, self.seconds * 1e3)
        if self._registry is not None:
            self._registry.observe(self._metric, self.seconds)
        return False


def get_logger(child: str | None = None) -> logging.Logger:
    """The package logger (replaces the reference's LOG>>> prints).

    Library-friendly by default: a NullHandler with propagation left on, so
    applications route/format quiver_tpu records through their own logging
    config. Set ``QUIVER_LOG_LEVEL`` (e.g. INFO) to opt into a ready-made
    stderr handler for scripts/benchmarks.
    """
    logger = logging.getLogger("quiver_tpu")
    if not logger.handlers:
        # handler bootstrap runs at most once (guarded by logger.handlers);
        # the level is process-lifetime config, not a live switch
        # graftlint: disable=env-at-trace -- one-shot handler bootstrap, not a live switch
        level = os.environ.get("QUIVER_LOG_LEVEL")
        if level:
            try:
                # validate BEFORE mutating the logger: a bogus level (e.g.
                # QUIVER_LOG_LEVEL=bogus) must not crash the process at its
                # first log call — fall back to the library-friendly
                # NullHandler path with a one-line warning instead
                logger.setLevel(level)
            except ValueError:
                # graftlint: disable=per-call-logging-in-jit -- one-shot handler bootstrap (guarded by logger.handlers), not a per-step path
                print(
                    f"quiver_tpu: ignoring invalid QUIVER_LOG_LEVEL="
                    f"{level!r} (use DEBUG/INFO/WARNING/ERROR/CRITICAL "
                    "or an int); logging stays at the library default",
                    file=sys.stderr,
                )
                logger.addHandler(logging.NullHandler())
            else:
                h = logging.StreamHandler()
                h.setFormatter(
                    logging.Formatter(
                        "%(asctime)s %(name)s %(levelname)s %(message)s"
                    )
                )
                logger.addHandler(h)
                logger.propagate = False
        else:
            logger.addHandler(logging.NullHandler())
    return logger.getChild(child) if child else logger


_ONCE_KEYS: set[str] = set()


def info_once(key: str, msg: str, *args, child: str | None = None) -> None:
    """Log ``msg`` at INFO level exactly once per process per ``key``.

    For signals that must reach the user but would spam if repeated —
    e.g. reference-API parity arguments that are accepted but INERT
    (VERDICT r5 weak #7): the first non-default use logs, the per-batch
    call sites stay silent after that.
    """
    if key in _ONCE_KEYS:
        return
    _ONCE_KEYS.add(key)
    get_logger(child).info(msg, *args)


def warn_once(key: str, msg: str, *args, child: str | None = None) -> None:
    """Log ``msg`` at WARNING level exactly once per process per ``key``.

    The fail-safe-degradation companion to :func:`info_once`: shared
    on-disk caches (kernel elections, AOT serving executables) treat any
    corrupt/truncated file as a miss and recompute — that degradation
    must reach the operator ONCE, not once per lookup on a hot path.
    """
    if key in _ONCE_KEYS:
        return
    _ONCE_KEYS.add(key)
    get_logger(child).warning(msg, *args)


def reset_once() -> None:
    """Clear :func:`info_once`/:func:`warn_once`'s once-per-process
    memory.

    For test fixtures: without this, one-shot log state leaks across tests
    in the same process and log-assertion tests become order-dependent
    (the first test to trigger a key swallows it for every later test).
    """
    _ONCE_KEYS.clear()


def start_trace(log_dir: str) -> None:
    """Begin collecting a device+host profile (TensorBoard/Perfetto format)."""
    enable_trace()
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()
