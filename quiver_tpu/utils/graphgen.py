"""Synthetic graph generators for tests and dataset-free benchmarks.

The reference ships a Pareto-degree generator so perf runs need no datasets
(torch-quiver benchmarks/generated_graph/gen_graph.py:21-33); this module
provides the same capability: power-law degree sequence, uniform random
endpoints, returned as COO ``edge_index``.
"""

from __future__ import annotations

import numpy as np

__all__ = ["generate_pareto_graph", "generate_uniform_graph"]


def generate_pareto_graph(
    num_nodes: int,
    avg_degree: float,
    alpha: float = 2.0,
    seed: int = 0,
    max_degree: int | None = None,
) -> np.ndarray:
    """Power-law (Pareto) out-degree graph as (2, E) COO edge_index.

    Degrees are drawn from a Pareto(alpha) scaled to the requested mean, so
    ~30% of nodes own ~75% of edges — matching the skew the reference cites
    for ogbn-products/Reddit (docs/Introduction_en.md:77-80).
    """
    rng = np.random.default_rng(seed)
    # Pareto with mean alpha*m/(alpha-1); scale m so the mean is avg_degree.
    m = avg_degree * (alpha - 1.0) / alpha
    deg = rng.pareto(alpha, num_nodes) * m + 1.0
    if max_degree is None:
        max_degree = max(int(avg_degree * 64), 64)
    deg = np.minimum(deg.astype(np.int64), max_degree)
    total = int(deg.sum())
    row = np.repeat(np.arange(num_nodes, dtype=np.int64), deg)
    col = rng.integers(0, num_nodes, size=total, dtype=np.int64)
    dtype = np.int32 if num_nodes <= np.iinfo(np.int32).max else np.int64
    return np.stack([row.astype(dtype), col.astype(dtype)])


def generate_uniform_graph(num_nodes: int, avg_degree: int, seed: int = 0) -> np.ndarray:
    """Uniform random graph as (2, E) COO edge_index."""
    rng = np.random.default_rng(seed)
    total = num_nodes * avg_degree
    dtype = np.int32 if num_nodes <= np.iinfo(np.int32).max else np.int64
    row = rng.integers(0, num_nodes, size=total, dtype=dtype)
    col = rng.integers(0, num_nodes, size=total, dtype=dtype)
    return np.stack([row, col])
