"""Debug introspection helpers.

``show_tensor_info`` is capability parity with the reference's debug binding
(torch-quiver srcs/cpp/src/quiver/cpu/tensor.cpp:25-96), which prints an
array's dtype/shape/device; here it also reports sharding and committed
memory kind, the TPU-relevant placement facts.
"""

from __future__ import annotations

import numpy as np

import jax

__all__ = ["show_tensor_info", "tensor_info"]


def tensor_info(x) -> str:
    """One-line description of an array's dtype, shape, and placement."""
    if isinstance(x, jax.Array):
        try:
            devs = sorted(str(d) for d in x.devices())
        except RuntimeError:  # deleted/donated buffers
            devs = ["<deleted>"]
        kind = getattr(getattr(x, "sharding", None), "memory_kind", None)
        placement = devs[0] if len(devs) == 1 else f"{len(devs)} devices"
        if kind:
            placement += f", {kind}"
        return f"jax.Array dtype={x.dtype} shape={tuple(x.shape)} [{placement}]"
    x = np.asarray(x)
    return f"numpy dtype={x.dtype} shape={x.shape} [host]"


def show_tensor_info(x) -> str:
    """Print and return :func:`tensor_info` (reference tensor.cpp:74-95)."""
    s = tensor_info(x)
    print(s)
    return s
