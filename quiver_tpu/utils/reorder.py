"""Degree-based feature reordering.

Capability parity with the reference's ``reindex_by_config``/``reindex_feature``
(torch-quiver utils.py:213-231): sort nodes by descending degree so the hot
tier of the feature cache holds high-degree nodes, and shuffle the hot prefix
so sharded placements are statistically load-balanced across devices
(utils.py:219-224). Pure host-side preprocessing — runs once, in numpy.

Invariant (tested, mirrors test_graph_reindex.py:35-70 in the reference):
    original_feature[ids] == new_feature[new_order[ids]]
"""

from __future__ import annotations

import numpy as np

__all__ = ["reorder_by_degree", "reindex_by_config"]


def reorder_by_degree(
    feature: np.ndarray,
    degree: np.ndarray,
    hot_ratio: float,
    seed: int = 0,
    pin_top: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Reorder feature rows hot-first by degree.

    Args:
      feature: (N, F) node features.
      degree: (N,) node degrees (CSRTopo.degree).
      hot_ratio: fraction of rows that will live in the hot tier; this prefix
        of the degree-sorted order is randomly shuffled for shard balance.
      seed: shuffle seed.
      pin_top: keep the top ``pin_top`` rows in strict descending-degree
        order (excluded from the balance shuffle). The replicated super-hot
        tier wants the literal top-β rows — every device holds a full copy,
        so shard balance is meaningless there and shuffling would dilute it
        with merely-warm rows.

    Returns:
      (new_feature, new_order) where new_order maps old node id -> new row,
      i.e. new_feature[new_order[i]] == feature[i].
    """
    n = feature.shape[0]
    if degree.shape != (n,):
        raise ValueError(f"degree shape {degree.shape} != ({n},)")
    hot_ratio = float(np.clip(hot_ratio, 0.0, 1.0))
    # argsort of -degree: stable so equal-degree nodes keep id order
    perm = np.argsort(-degree.astype(np.int64), kind="stable")
    hot = int(n * hot_ratio)
    pin = int(np.clip(pin_top, 0, hot))
    if hot - pin > 1:
        rng = np.random.default_rng(seed)
        rng.shuffle(perm[pin:hot])
    new_feature = feature[perm]
    new_order = np.empty(n, dtype=np.int64)
    new_order[perm] = np.arange(n, dtype=np.int64)
    if n <= np.iinfo(np.int32).max:
        new_order = new_order.astype(np.int32)
    return new_feature, new_order


def reindex_by_config(adj_csr, graph_feature, gpu_portion, seed: int = 0):
    """Reference-signature alias (torch-quiver utils.py:213-224):
    ``reindex_by_config(csr_topo, feature, gpu_portion)`` ->
    (reordered_feature, new_order)."""
    return reorder_by_degree(
        np.asarray(graph_feature), adj_csr.degree, gpu_portion, seed=seed
    )
