"""Checkpoint / resume for training state — atomic, integrity-verified,
topology-portable.

The reference has no model checkpointing at all (SURVEY §5: examples use
torch.save only for preprocessing artifacts, preprocess.py:54-106). The
first cut here wrapped orbax; this store replaces it with a self-contained
format built for the elastic-resume contract the trainer needs:

* **Mesh-agnostic**: leaves are saved as GLOBAL host arrays with a
  manifest of specs (shape/dtype/key path) — no sharding is welded in, so
  a run checkpointed on an F=8 mesh restores onto F=4
  (``DistributedTrainer.resume(mesh=)`` re-places them).
* **Atomic**: everything is written + fsynced into a temp directory, the
  ``COMMIT`` marker lands last, and one ``os.replace`` renames the
  directory into place — a crash mid-save leaves only a skipped temp
  directory, never a half-readable checkpoint that poisons the next
  ``resume()``.
* **Integrity-verified**: the manifest carries per-leaf CRC32 content
  checksums (``resilience/integrity.py``); restore re-derives them, and a
  corrupt or uncommitted directory is quarantined (renamed
  ``quarantine-*``, logged once per directory) with automatic fallback to
  the newest valid checkpoint. ``max_to_keep >= 2`` is enforced while
  integrity is on — a retention window of one would leave nothing to fall
  back to.

>>> ckpt = Checkpointer("/tmp/run1", max_to_keep=3)
>>> ckpt.save(step, {"params": params, "opt_state": opt_state})
>>> state = ckpt.restore()                      # newest VALID, exact tree
>>> state = ckpt.restore(template=state0)       # shape/dtype-checked
"""

from __future__ import annotations

import concurrent.futures
import os
import pickle
import re
import shutil
import time
import zlib

import numpy as np

import jax

from ..resilience.integrity import (
    ARRAYS_NAME,
    COMMIT_NAME,
    MANIFEST_NAME,
    TREEDEF_NAME,
    CorruptCheckpoint,
    array_checksum,
    build_manifest,
    load_manifest,
    quarantine_name,
    verify_checkpoint_dir,
)

__all__ = ["Checkpointer"]

_STEP_RE = re.compile(r"^step-(\d+)$")
_TMP_PREFIX = ".tmp-"


def _resolve_dtype(name: str) -> np.dtype:
    """Manifest dtype string -> numpy dtype; 'bfloat16' resolves through
    ml_dtypes (ships with jax) when numpy alone cannot parse it."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401 — registers bfloat16 & friends

        return np.dtype(name)


def _fsync_dir(path: str) -> None:
    """Flush directory metadata (the rename/commit durability point);
    best-effort on filesystems without directory fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_file(path: str, data: bytes) -> None:
    with open(path, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())


class Checkpointer:
    """Atomic manifest-based checkpoint store for train-state pytrees.

    Args:
      directory: checkpoint root (created if missing; made absolute).
      max_to_keep: retention window (oldest committed checkpoints
        deleted). Must be >= 2 while ``integrity=True``: the corrupt-
        checkpoint fallback needs a previous valid checkpoint to fall
        back TO.
      integrity: verify per-leaf content checksums on restore and
        quarantine failing directories (on by default; ``False`` trusts
        the COMMIT marker alone — the pre-integrity behavior).
      tracer: optional grafttrace :class:`~quiver_tpu.obs.tracing
        .Tracer` — each save lands a ``ckpt.save`` span (subsystem
        ``resilience``) covering the worker-thread write, tagged with
        the causing trace when the caller passes one.
    """

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3,
                 integrity: bool = True, tracer=None):
        self.directory = os.path.abspath(os.fspath(directory))
        self.integrity = bool(integrity)
        self.tracer = tracer
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        if self.integrity and max_to_keep < 2:
            raise ValueError(
                f"max_to_keep must be >= 2 with integrity verification on "
                f"(got {max_to_keep}): a corrupt newest checkpoint needs a "
                f"previous valid one to fall back to; pass integrity=False "
                f"to keep a single-checkpoint window"
            )
        self.max_to_keep = int(max_to_keep)
        os.makedirs(self.directory, exist_ok=True)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="quiver-ckpt"
        )
        self._pending: list[concurrent.futures.Future] = []
        self._inflight: set[int] = set()

    # -- directory scanning --------------------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step-{int(step)}")

    def _committed(self, step: int) -> bool:
        d = self._step_dir(step)
        return os.path.isdir(d) and os.path.exists(
            os.path.join(d, COMMIT_NAME)
        )

    def all_steps(self) -> list[int]:
        """Committed steps, ascending. Uncommitted/partial directories
        (no COMMIT marker, temp names, quarantined) are invisible here —
        a crash mid-save can never surface through this scan."""
        steps = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.exists(
                os.path.join(self.directory, name, COMMIT_NAME)
            ):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> int | None:
        """Newest committed step (marker check only — full checksum
        verification happens on restore / :meth:`latest_valid_step`)."""
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_valid_step(self) -> int | None:
        """Newest step that passes FULL integrity verification.

        Corrupt committed directories encountered on the way are
        quarantined (renamed, one log per directory) so the next scan
        does not re-pay their verification. With ``integrity=False``
        this is :meth:`latest_step`."""
        if not self.integrity:
            return self.latest_step()
        for step in reversed(self.all_steps()):
            try:
                verify_checkpoint_dir(self._step_dir(step))
            except CorruptCheckpoint as e:
                self._quarantine(step, e)
                continue
            return step
        return None

    def verify(self, step: int | None = None) -> dict:
        """Full integrity check of ``step`` (default latest committed);
        returns the manifest or raises :class:`CorruptCheckpoint`."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        return verify_checkpoint_dir(self._step_dir(int(step)))

    def metadata(self, step: int | None = None) -> dict:
        """The writer's ``meta`` dict of ``step`` (default latest
        committed) — mesh shape, logical workers, … (what the trainer's
        elastic resume validates). Empty dict for metadata-less saves."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        manifest = load_manifest(self._step_dir(int(step)))
        return dict(manifest.get("meta") or {})

    def _quarantine(self, step: int, err: CorruptCheckpoint) -> None:
        """Rename a failed directory out of the step namespace (one log
        line per directory — repeated scans stay quiet)."""
        from .trace import info_once

        src = self._step_dir(step)
        dst = os.path.join(
            self.directory,
            quarantine_name(os.path.basename(src), time.time() * 1000),
        )
        try:
            os.replace(src, dst)
            where = dst
        except OSError:
            where = src  # could not rename; the step scan still skips it
        info_once(
            f"checkpoint-quarantine-{os.path.basename(src)}",
            "checkpoint step %d FAILED integrity verification (%s); "
            "quarantined at %s and falling back to the newest valid "
            "checkpoint",
            int(step), str(err), where,
        )

    # -- save ----------------------------------------------------------------

    def save(self, step: int, state, wait: bool = False,
             metadata: dict | None = None,
             trace: str | None = None) -> bool:
        """Save a state pytree at ``step`` (async by default).

        The state is host-materialized and checksummed NOW (the caller
        may mutate or donate buffers right after); file IO + the atomic
        commit run on a background thread. Returns whether the save was
        ACCEPTED — ``False`` (plus a once-per-process log) when ``step``
        is already committed or in flight, so a caller can never believe
        state is durable when nothing will be written.

        ``metadata`` lands in the manifest's ``meta`` field — the
        mesh-agnostic facts a later (possibly differently-shaped) resume
        validates against.
        """
        step = int(step)
        if step in self._inflight or self._committed(step):
            from .trace import info_once

            info_once(
                "checkpoint-save-rejected",
                "Checkpointer.save(step=%d) was REJECTED (the step is "
                "already checkpointed or in flight) — nothing was "
                "written; further rejections in this process stay silent",
                step,
            )
            return False
        # host-materialize + checksum synchronously; the worker only does IO
        paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(
            state
        )
        skeleton = jax.tree_util.tree_unflatten(
            treedef, list(range(len(paths_and_leaves)))
        )
        treedef_bytes = pickle.dumps(skeleton)
        records, chunks, offset = [], [], 0
        for path, leaf in paths_and_leaves:
            # np.asarray, NOT ascontiguousarray: the latter promotes 0-d
            # scalars to (1,) and the manifest must record the true shape
            # (tobytes always emits C-order bytes either way)
            arr = np.asarray(leaf)
            data = arr.tobytes()
            records.append({
                "path": jax.tree_util.keystr(path),
                "shape": list(arr.shape),
                "dtype": arr.dtype.name,
                "offset": offset,
                "nbytes": len(data),
                "crc32": array_checksum(arr),
            })
            chunks.append(data)
            offset += len(data)
        manifest = build_manifest(
            step, records,
            zlib.crc32(treedef_bytes) & 0xFFFFFFFF,
            metadata,
        )
        self._inflight.add(step)
        self._pending.append(self._pool.submit(
            self._write_sync, step, b"".join(chunks), treedef_bytes,
            manifest, trace
        ))
        if wait:
            self.wait_until_finished()
        return True

    def _write_sync(self, step: int, payload: bytes, treedef_bytes: bytes,
                    manifest: dict, trace: str | None = None) -> None:
        """Worker-thread body: temp dir -> payload -> COMMIT -> atomic
        rename -> retention. Runs strictly serialized (one worker)."""
        import json

        t0 = self.tracer.now() if (
            self.tracer is not None and self.tracer.enabled
        ) else None
        tmp = os.path.join(
            self.directory, f"{_TMP_PREFIX}step-{step}-{os.getpid()}"
        )
        try:
            self._sweep_stale_tmp(keep=tmp)
            os.makedirs(tmp, exist_ok=True)
            _write_file(os.path.join(tmp, ARRAYS_NAME), payload)
            _write_file(os.path.join(tmp, TREEDEF_NAME), treedef_bytes)
            _write_file(
                os.path.join(tmp, MANIFEST_NAME),
                json.dumps(manifest, indent=1).encode(),
            )
            # the marker goes in LAST; the rename below is the single
            # atomic commit point either way
            _write_file(os.path.join(tmp, COMMIT_NAME), b"COMMIT\n")
            os.replace(tmp, self._step_dir(step))
            _fsync_dir(self.directory)
            self._enforce_retention()
        finally:
            self._inflight.discard(step)
            shutil.rmtree(tmp, ignore_errors=True)
            if t0 is not None:
                self.tracer.record(
                    "ckpt.save", t0, self.tracer.now() - t0, trace=trace,
                    subsystem="resilience", step=step,
                    nbytes=len(payload),
                )

    def _sweep_stale_tmp(self, keep: str) -> None:
        """Best-effort removal of temp directories a crashed writer left
        behind (they are invisible to every scan, but cost disk)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            full = os.path.join(self.directory, name)
            if name.startswith(_TMP_PREFIX) and full != keep:
                shutil.rmtree(full, ignore_errors=True)

    def _enforce_retention(self) -> None:
        """Delete the oldest committed checkpoints beyond ``max_to_keep``
        (COMMIT marker removed first, so a kill mid-delete leaves an
        uncommitted — skipped — directory, not a corrupt-looking one)."""
        steps = self.all_steps()
        for step in steps[:max(len(steps) - self.max_to_keep, 0)]:
            d = self._step_dir(step)
            try:
                os.remove(os.path.join(d, COMMIT_NAME))
            except OSError:
                pass
            shutil.rmtree(d, ignore_errors=True)

    # -- restore -------------------------------------------------------------

    def restore(self, step: int | None = None, template=None):
        """Restore the state at ``step`` (default: newest VALID).

        With ``step=None``, corrupt/uncommitted directories are
        quarantined and the newest checkpoint that passes verification
        wins — a half-written or bit-flipped newest checkpoint costs one
        log line, not the run. An EXPLICIT step that fails verification
        raises :class:`CorruptCheckpoint` instead (the caller pinned it;
        silently serving a different step would be worse).

        ``template`` (a matching pytree, e.g. the freshly-initialized
        state) restores into the template's exact tree structure after a
        per-leaf shape/dtype check against the manifest; without it the
        pickled skeleton rebuilds the saved structure exactly (tuples
        stay tuples). Leaves come back as host numpy arrays — callers
        re-place them onto their mesh (see ``DistributedTrainer.resume``).
        """
        self.wait_until_finished()
        if step is None:
            step = self.latest_valid_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        step = int(step)
        path = self._step_dir(step)
        if self.integrity:
            manifest = verify_checkpoint_dir(path)
        else:
            if not self._committed(step):
                raise CorruptCheckpoint(
                    f"{path}: no COMMIT marker (uncommitted/partial save)"
                )
            manifest = load_manifest(path)
        with open(os.path.join(path, ARRAYS_NAME), "rb") as fh:
            payload = fh.read()
        leaves = []
        for rec in manifest["leaves"]:
            dtype = _resolve_dtype(rec["dtype"])
            arr = np.frombuffer(
                payload, dtype=dtype,
                count=int(rec["nbytes"]) // max(dtype.itemsize, 1),
                offset=int(rec["offset"]),
            ).reshape(tuple(rec["shape"])).copy()
            leaves.append(arr)
        if template is None:
            with open(os.path.join(path, TREEDEF_NAME), "rb") as fh:
                skeleton = pickle.load(fh)
            order, treedef = jax.tree_util.tree_flatten(skeleton)
            return jax.tree_util.tree_unflatten(
                treedef, [leaves[i] for i in order]
            )
        t_leaves, t_def = jax.tree_util.tree_flatten(template)
        if len(t_leaves) != len(leaves):
            raise ValueError(
                f"template has {len(t_leaves)} leaves, checkpoint step "
                f"{step} has {len(leaves)}"
            )
        for rec, t in zip(manifest["leaves"], t_leaves):
            t_arr = np.asarray(t)
            if (tuple(rec["shape"]) != t_arr.shape
                    or _resolve_dtype(rec["dtype"]) != t_arr.dtype):
                raise ValueError(
                    f"checkpoint leaf {rec['path']!r} is "
                    f"{tuple(rec['shape'])}/{rec['dtype']}, template "
                    f"expects {t_arr.shape}/{t_arr.dtype.name}"
                )
        return jax.tree_util.tree_unflatten(t_def, leaves)

    # -- lifecycle -----------------------------------------------------------

    def wait_until_finished(self) -> None:
        """Block until every in-flight async save has committed (raising
        the first worker failure, if any)."""
        pending, self._pending = self._pending, []
        for fut in pending:
            fut.result()

    def close(self) -> None:
        """Wait for in-flight async saves, then release the worker — a
        close racing an async commit must not lose the tail checkpoint."""
        try:
            self.wait_until_finished()
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
