"""Checkpoint / resume for training state.

The reference has no model checkpointing at all (SURVEY §5: examples use
torch.save only for preprocessing artifacts, preprocess.py:54-106) — this is
roadmap capability the TPU framework ships natively: orbax-backed, async-safe,
multi-host-correct saves of (params, opt_state, step) with retention.

>>> ckpt = Checkpointer("/tmp/run1", max_to_keep=3)
>>> ckpt.save(step, {"params": params, "opt_state": opt_state})
>>> state = ckpt.restore()                      # latest, exact saved tree
>>> state = ckpt.restore(template=state0)       # shape/dtype/sharding-checked
"""

from __future__ import annotations

import os

import orbax.checkpoint as ocp

__all__ = ["Checkpointer"]


class Checkpointer:
    """Thin orbax CheckpointManager wrapper for train-state pytrees.

    Args:
      directory: checkpoint root (created if missing; made absolute —
        orbax requires absolute paths).
      max_to_keep: retention window (oldest checkpoints deleted).
    """

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3):
        self.directory = os.path.abspath(os.fspath(directory))
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state, wait: bool = False) -> bool:
        """Save a state pytree at ``step`` (async by default).

        Returns whether orbax ACCEPTED the save — it returns False when
        the manager's should-save policy rejects it (e.g. a step that is
        already checkpointed). Swallowing that bool means a caller can
        believe state is durable when nothing was written, so a rejection
        is also logged (once per process)."""
        saved = bool(
            self._mngr.save(int(step), args=ocp.args.StandardSave(state))
        )
        if not saved:
            from .trace import info_once

            info_once(
                "checkpoint-save-rejected",
                "Checkpointer.save(step=%d) was REJECTED by orbax (e.g. "
                "the step is already checkpointed) — nothing was written; "
                "further rejections in this process stay silent",
                int(step),
            )
        if wait:
            self._mngr.wait_until_finished()
        return saved

    def restore(self, step: int | None = None, template=None):
        """Restore the state at ``step`` (default: latest).

        ``template`` (a matching pytree, e.g. the freshly-initialized state)
        restores into the template's exact dtypes/shardings; without it the
        tree is restored as saved.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self.directory}")
        args = None if template is None else ocp.args.StandardRestore(template)
        return self._mngr.restore(int(step), args=args)

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        """Wait for in-flight async saves, then release the manager — a
        close racing an async commit must not lose the tail checkpoint."""
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
