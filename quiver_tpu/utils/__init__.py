__all__ = [
    "Checkpointer",
    "show_tensor_info",
    "tensor_info",
    "generate_pareto_graph",
    "reorder_by_degree",
    "Timer",
    "trace_scope",
    "enable_trace",
    "disable_trace",
    "trace_enabled",
    "get_logger",
    "start_trace",
    "stop_trace",
    "honor_forced_platform",
]

_LAZY = {
    "Checkpointer": "checkpoint",
    "show_tensor_info": "debug",
    "tensor_info": "debug",
    "generate_pareto_graph": "graphgen",
    "reorder_by_degree": "reorder",
    "Timer": "trace",
    "trace_scope": "trace",
    "enable_trace": "trace",
    "disable_trace": "trace",
    "trace_enabled": "trace",
    "get_logger": "trace",
    "start_trace": "trace",
    "stop_trace": "trace",
    "honor_forced_platform": "backend",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
