"""Mesh-sharded feature storage with ICI-collective gathers.

TPU-native replacement for the reference's ShardTensor + p2p_clique_replicate
stack (torch-quiver shard_tensor.py:79-241, quiver_feature.cu:56-361,
feature.py:126-166): where the reference partitions hot rows across the GPUs
of an NVLink clique and lets the gather kernel load peer HBM directly,
quiver-tpu shards rows across the mesh's ``feature`` axis and fetches remote
rows with one XLA collective inside ``shard_map``:

    partial[b] = own(id_b) ? local_rows[id_b - offset] : 0
    result     = psum(partial, axis="feature")

The psum lowers to reduce-scatter + all-gather on the ICI ring — the role
NVLink peer loads play in the reference. No IPC handles, no access_book, no
cross-clique Python fallback path (shard_tensor.py:166-208): devices that
share no ICI would sit on different meshes entirely.

``ShardedTensor`` is the generic row-sharded 2-D table (reference
ShardTensor parity); ``ShardedFeature`` layers feature_order translation,
an optional L0 *replicated super-hot tier* (``replicate_budget`` — the
top-degree rows in every chip's HBM, gathered with zero interconnect
lanes), and the cold host tier on top (reference Feature with
device_replicate + p2p_clique_replicate + UVA, as one three-tier store).

When every feature-group member requests its OWN id set (routed mode, the
seed_sharding="all" trainer), requests are routed to their owning shard
over two ``all_to_all`` hops. Buckets are CAPPED by default: capacity
``ceil(alpha * L / F)`` per destination, so each hop moves ``alpha * L``
lanes instead of the exact-safe worst case ``F * L`` — the comm volume no
longer inflates with the feature-axis width. Per-bucket overflow is
detected in-program and served through a psum fallback (never silent,
never wrong), counted, and surfaced so callers and the auto-tuner can grow
the cap across batches. See ``ShardedTensor.routed_gather``.
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.config import CachePolicy, parse_size_bytes
from .feature import (
    KernelChoice,
    _hot_gather_fn,
    _parse_storage_dtype,
    quantize_rows_int8,
    tiered_lookup,
    validate_gather_kernel,
    wrap_dequant_gathers,
)
from ..core.memory import to_pinned_host
from ..core.topology import CSRTopo
from ..obs.registry import ROUTED_OVERFLOW, TIER_HITS, MetricsRegistry
from ..ops.sample import staged_gather
from ..parallel.routing import BucketRoute
from ..utils.trace import get_logger, info_once
from ..parallel.mesh import DATA_AXIS, FEATURE_AXIS, shard_map
from ..utils.reorder import reorder_by_degree

__all__ = ["ShardedTensor", "ShardedFeature"]


class ShardedTensor(KernelChoice):
    """2-D table row-sharded over the mesh's feature axis.

    Rows are padded to a multiple of the axis size; shard d owns rows
    [d*rows_per_shard, (d+1)*rows_per_shard) — the same contiguous-offset
    layout the reference tracks in ``tensor_offset_device``
    (shard_tensor.py:55-76).
    """

    def __init__(self, mesh: Mesh, axis: str = FEATURE_AXIS, kernel: str = "auto",
                 routed_alpha: float = 2.0):
        self.mesh = mesh
        self.axis = axis
        self.num_shards = mesh.shape[axis]
        self._kernel = validate_gather_kernel(kernel)
        # capped-bucket routed gather: per-destination bucket capacity
        # ceil(routed_alpha * L / F). alpha=2 leaves 2x headroom over a
        # uniform owner distribution — degree-ordered hot rows concentrate
        # on shard 0 (reorder_by_degree's partial shuffle spreads them, but
        # skew survives), so 1.0 would overflow routinely. Grown by
        # _maybe_grow_routed_alpha when a batch overflows (fallback-served,
        # never wrong — just slower); alpha >= F means full-length buckets,
        # i.e. the exact-safe uncapped path.
        if routed_alpha <= 0:
            raise ValueError(f"routed_alpha must be > 0, got {routed_alpha}")
        self.routed_alpha = float(routed_alpha)
        # graftscope registry: the overflow count of the last capped routed
        # gather lands here (``last_routed_overflow`` is a thin view). Read
        # lazily — int() forces a sync, so consumers (the auto-tuner,
        # benchmarks, exporters) pull it after the batch.
        self.metrics = MetricsRegistry()
        self.metrics.counter(
            ROUTED_OVERFLOW, unit="lanes",
            doc="fallback-served lanes of the last capped routed gather",
        )
        self.table = None
        self.rows_per_shard = 0
        self.num_rows = 0
        self._gather_cache = {}

    @property
    def last_routed_overflow(self):
        """Fallback-served lane count of the last eager capped routed
        gather (device scalar; ``(steps,)`` after an epoch_scan write;
        None before any). Thin view of the ``feature.routed_overflow``
        registry metric — new consumers should read ``self.metrics``."""
        return self.metrics.value(ROUTED_OVERFLOW)

    @last_routed_overflow.setter
    def last_routed_overflow(self, value):
        self.metrics.set(ROUTED_OVERFLOW, value)

    def from_cpu_tensor(self, tensor: np.ndarray) -> "ShardedTensor":
        n, f = tensor.shape
        rps = -(-n // self.num_shards)  # ceil
        padded = rps * self.num_shards
        if padded != n:
            tensor = np.concatenate(
                [tensor, np.zeros((padded - n, f), tensor.dtype)]
            )
        sharding = NamedSharding(self.mesh, P(self.axis, None))
        self.table = jax.device_put(tensor, sharding)
        self.rows_per_shard = rps
        self.num_rows = n
        return self

    @property
    def shape(self):
        return (self.num_rows, self.table.shape[1])

    def local_gather(self, local_table, ids):
        """Per-device body: serve the ids this shard owns, zeros elsewhere.

        Call inside ``shard_map``; combine across shards with
        ``psum(..., self.axis)``. Requires every member of the feature
        group to request the SAME ids (the psum aligns rows by position).
        """
        my = jax.lax.axis_index(self.axis)
        owner = ids // self.rows_per_shard
        mine = owner == my
        local_idx = jnp.where(mine, ids - my * self.rows_per_shard, 0)
        rows = _hot_gather_fn(local_table, self.kernel)(local_idx)
        return jnp.where(mine[:, None], rows, 0)

    def routed_cap(self, length: int, alpha: float | None = None) -> int:
        """Capped-bucket capacity for a per-device request length ``L``:
        ``cap = ceil(alpha * L / F)``, clamped to [1, L]. ``cap == L``
        degenerates to the exact-safe full-length buckets (no fallback
        machinery is traced then)."""
        a = self.routed_alpha if alpha is None else float(alpha)
        if a <= 0:
            raise ValueError(f"alpha must be > 0, got {a}")
        cap = math.ceil(a * length / max(self.num_shards, 1))
        # graftlint: disable=host-op-on-tracer -- L is the static lane width
        return max(1, min(int(cap), int(length)))

    # graftlint: eager -- between-batch tuner; under trace int() raises and
    def _maybe_grow_routed_alpha(self) -> None:  # the except returns early
        """Auto-tuner step for eager capped gathers: if the PREVIOUS capped
        batch overflowed its buckets, double ``routed_alpha`` (capped at F
        — full-length buckets) before planning this batch's cap. Reading
        the stashed count is cheap: the batch that produced it has long
        since completed."""
        ov = self.last_routed_overflow
        if ov is None:
            return
        self.last_routed_overflow = None
        try:
            count = int(ov)
        except Exception:  # noqa: BLE001 — a deleted/donated buffer must
            return  # not break the next gather
        if count <= 0:
            return
        old = self.routed_alpha
        self.routed_alpha = min(old * 2.0, float(self.num_shards))
        if self.routed_alpha != old:
            get_logger("feature").info(
                "routed gather: %d lanes overflowed their buckets "
                "(fallback-served); growing alpha %.2f -> %.2f",
                count, old, self.routed_alpha,
            )

    def routed_gather(self, local_table, ids, cap: int | None = None,
                      with_overflow: bool = False):
        """Per-device body: serve a DIFFERENT id set per feature-group
        member by routing requests to their owning shard and rows back —
        two ``all_to_all`` hops over the feature axis.

        This is the true analogue of the reference's NVLink-clique gather
        (shard_tensor.cu.hpp:16-58: every GPU runs its own batch and loads
        peer HBM directly): with it, the feature axis no longer forces
        redundant sampling/model work across the group — each device can be
        a full data worker over its own seed block while the table stays
        sharded (see docs/Introduction.md "Cost of redundant sampling").

        Comm model (L = per-device request length, F = feature-axis size):

        * ``cap=None`` — exact-safe full-length buckets: every destination
          bucket is padded to L (worst case all ids on one shard), so each
          hop moves ``F x L`` row lanes regardless of actual traffic.
        * ``cap=c`` (capped-bucket mode, ``c = ceil(alpha*L/F)`` from
          :meth:`routed_cap`) — each hop moves ``F x c ~= alpha*L`` lanes.
          Per-bucket overflow (more than ``c`` of my requests owned by one
          shard) is DETECTED in-program, never silent: overflowed lanes
          are served through a psum fallback (all_gather the <= L-c
          overflow ids over the feature axis, each shard contributes the
          rows it owns, psum returns them everywhere) gated behind a
          ``lax.cond`` whose predicate is the feature-group psum of the
          overflow count — uniform across the participants, so the
          collective-inside-cond is deadlock-free, and a non-overflowing
          batch pays ZERO fallback comm. The total overflow across all
          buckets is <= L - c (at most L valid lanes, each overflowing
          bucket keeps c of them), so the (L-c,) fallback buffer is
          exact-safe.

        Results are bit-identical between the two modes: capped routing
        moves the same table rows, just in smaller buckets, and fallback
        lanes receive exactly the rows the uncapped path would have
        fetched. Use psum ``local_gather`` instead when the feature group
        shares one id set.

        ``ids`` may contain invalid lanes as any negative value; their rows
        return zero. With ``with_overflow=True`` returns ``(rows, count)``
        where ``count`` is the feature-group total of fallback-served lanes
        (an int32 scalar, identical on every member; always 0 when
        ``cap=None``).
        """
        F = self.num_shards
        L = ids.shape[0]
        if cap is not None:
            cap = int(cap)
            if cap < 1:
                raise ValueError(f"cap must be >= 1, got {cap}")
            if cap >= L:
                cap = None  # full-length buckets ARE the uncapped path
        valid = ids >= 0
        safe = jnp.where(valid, ids, 0)

        # one audited code path for both comm modes and both consumers
        # (feature gather here, neighbor sampling in sampling/dist.py):
        # parallel.routing.BucketRoute owns the sort-by-owner bucketing,
        # the two all_to_all hops, and the cond-gated psum fallback
        my = jax.lax.axis_index(self.axis)
        rps = self.rows_per_shard
        gather_rows = _hot_gather_fn(local_table, self.kernel)

        def serve(req_ids):
            # ownership-masked local gather: zero for dead (-1) lanes and
            # for ids another shard owns — required by the psum fallback,
            # harmless on the main hop (routing guarantees ownership there)
            mine = (req_ids >= 0) & (req_ids // rps == my)
            lidx = jnp.where(mine, req_ids - my * rps, 0)
            rows = gather_rows(lidx)
            return jnp.where(mine[:, None], rows, 0)

        route = BucketRoute(
            safe, valid, safe // rps, axis=self.axis,
            num_shards=self.num_shards, cap=cap,
        )
        rows = route.exchange(serve)
        if with_overflow:
            return rows, route.overflow
        return rows

    def _gather_fn(self, padded_len: int, dtype, routed: bool = False,
                   cap: int | None = None):
        """Memoized jitted shard_map gather (a fresh wrapper per call would
        re-trace on every eager batch).

        ``routed=False``: ids shard over the data axes, remote rows arrive
        by psum. ``routed=True``: ids shard over EVERY mesh axis and each
        device routes its own slice to the owning shards (routed_gather),
        so per-device gather work is 1/num_devices of the request instead
        of 1/data_size; ``cap`` selects the capped-bucket comm mode and
        the routed program returns ``(rows, overflow_count)`` with the
        count psum'd over the whole mesh (replicated).
        """
        cache_key = (padded_len, np.dtype(dtype).name, routed, cap)
        if cache_key in self._gather_cache:
            return self._gather_cache[cache_key]

        if routed:
            ids_axes = tuple(self.mesh.axis_names)
            other_axes = tuple(
                a for a in self.mesh.axis_names if a != self.axis
            )

            def body(local_table, local_ids):
                rows, ov = self.routed_gather(
                    local_table, local_ids, cap=cap, with_overflow=True
                )
                if other_axes:  # feature-psum'd already; replicate mesh-wide
                    ov = jax.lax.psum(ov, other_axes)
                return rows, ov

            out_specs = (P(ids_axes, None), P())
        else:
            ids_axes = tuple(
                a for a in self.mesh.axis_names if a != self.axis
            )

            def body(local_table, local_ids):
                part = self.local_gather(local_table, local_ids)
                return jax.lax.psum(part, self.axis)

            out_specs = P(ids_axes, None)

        f = jax.jit(
            shard_map(
                body,
                mesh=self.mesh,
                in_specs=(P(self.axis, None), P(ids_axes)),
                out_specs=out_specs,
                check_vma=False,
            )
        )
        self._gather_cache[cache_key] = f
        return f

    def delete(self) -> None:
        """Free the sharded buffers now (reference ``shard_tensor.delete``,
        SURVEY §2.5). The object is unusable after."""
        if self.table is not None:
            self.table.delete()
        self.table = None
        self.last_routed_overflow = None
        self._gather_cache.clear()

    def gather(self, ids, routed: bool = False, routed_cap="auto"):
        """Standalone sharded gather.

        ``routed=False``: ids shard over the data axes (replicated across
        the feature axis); remote rows arrive by psum. ``routed=True``: ids
        shard over EVERY axis and each device routes its slice to the
        owning shards (two all_to_alls) — per-device work drops by the
        feature-axis width; see routed_gather. Same results either way
        (bit-identical).

        ``routed_cap`` picks the routed comm mode (see routed_gather's comm
        model): ``"auto"`` (default) caps destination buckets at
        ``ceil(routed_alpha * L / F)`` lanes — ``alpha*L`` moved per hop
        instead of ``F*L`` — and auto-grows ``routed_alpha`` on the next
        call after a batch overflows (the overflowed lanes themselves are
        fallback-served, so results stay exact). ``None`` forces the
        uncapped full-length buckets; an int is an explicit per-bucket
        capacity. After a routed call ``last_routed_overflow`` holds the
        batch's fallback-served lane count (device scalar).
        """
        mult = 1
        for a in self.mesh.axis_names:
            if routed or a != self.axis:
                mult *= self.mesh.shape[a]
        n = ids.shape[0]
        pad = (-n) % mult
        if pad:
            # -1 = the documented invalid-lane sentinel. Padded lanes are
            # zeroed in the output — correct output, not skipped work.
            # (psum-path local_gather treats any non-owned id as zeros and
            # the routed paths never fetch them, so -1 is safe everywhere.)
            ids = jnp.concatenate([ids, jnp.full(pad, -1, ids.dtype)])
        if not routed:
            out = self._gather_fn(ids.shape[0], ids.dtype, False)(
                self.table, ids
            )
            return out[:n] if pad else out
        local_len = ids.shape[0] // mult
        if routed_cap == "auto":
            self._maybe_grow_routed_alpha()
            cap = self.routed_cap(local_len)
        elif routed_cap is None:
            cap = None
        else:
            cap = min(int(routed_cap), local_len)
        if cap is not None and cap >= local_len:
            cap = None  # full-length buckets: share the uncapped program
        out, ov = self._gather_fn(ids.shape[0], ids.dtype, True, cap)(
            self.table, ids
        )
        if not isinstance(ov, jax.core.Tracer):
            # eager call: stash the device scalar for the auto-tuner /
            # benchmarks. Under an outer jit trace ov is a tracer — storing
            # it would leak; in-program callers use routed_gather's
            # with_overflow return instead.
            self.last_routed_overflow = ov
        return out[:n] if pad else out

    def __getitem__(self, ids):
        """Standalone sharded gather (psum flavor); see :meth:`gather`."""
        return self.gather(ids)


class ShardedFeature(KernelChoice):
    """Feature store with a three-tier memory hierarchy over the mesh:

    * **L0 replicated super-hot** (``replicate_budget`` bytes/device): the
      top-β rows by degree, a full copy in EVERY chip's HBM, served by a
      pure local gather — zero interconnect lanes. The reference's
      ``device_replicate`` policy, scoped to only the rows hot enough to
      earn F× the HBM.
    * **L1 mesh-sharded hot** (``device_cache_size`` bytes/device): the
      MESH_SHARD realization of ``p2p_clique_replicate``
      (feature.py:126-166) — rows sharded over the feature axis, gathers
      ride ICI collectives (psum or owner-routed all_to_all).
    * **cold**: pinned-host rows with staged host-compute gathers (the UVA
      zero-copy role).

    Both budgets are *per device*, matching the reference's per-GPU
    ``device_cache_size``; total L1 rows = budget × feature-axis size,
    while an L0 row costs its bytes on every device.

    Per-tier hit counts of the last eager gather land in
    ``last_tier_hits`` (int32 ``(3,)`` device vector,
    ``[replicated, sharded, cold]``) — the measured hit distribution the
    control plane uses to move the L0/L1 boundary between batches.
    ``auto_split=True`` is a compat shim over a default
    :class:`~quiver_tpu.control.CacheController` (see
    :meth:`_maybe_auto_split`); attach a shared controller for measured
    re-tiering (:meth:`repin`) across training AND serving traffic.
    """

    def __init__(
        self,
        mesh: Mesh,
        device_cache_size: int | str = 0,
        csr_topo: CSRTopo | None = None,
        axis: str = FEATURE_AXIS,
        hot_shuffle_seed: int = 0,
        kernel: str = "auto",
        dtype=None,
        routed_alpha: float = 2.0,
        replicate_budget: int | str = 0,
        auto_split: bool = False,
    ):
        self.mesh = mesh
        self.axis = axis
        self._kernel = validate_gather_kernel(kernel)
        if routed_alpha <= 0:
            raise ValueError(f"routed_alpha must be > 0, got {routed_alpha}")
        self.routed_alpha = float(routed_alpha)
        self.storage_dtype = _parse_storage_dtype(dtype)
        self.cache_policy = CachePolicy.MESH_SHARD
        self.cache_budget = parse_size_bytes(device_cache_size)
        self.replicate_budget = parse_size_bytes(replicate_budget)
        self.auto_split = bool(auto_split)
        self.csr_topo = csr_topo
        self.hot_shuffle_seed = hot_shuffle_seed
        self.rep = None  # L0: (rep_rows, F) mesh-replicated block
        self.hot: ShardedTensor | None = None
        self.cold = None
        self._cold_is_host = False
        self.feature_order = None
        self.scale = None  # (N,) dequant scales (int8 storage only)
        self.rep_rows = 0
        self.hot_rows = 0
        self.shape = None
        # graftscope registry: per-tier hit counts [replicated, sharded,
        # cold] of the last eager gather land here (``last_tier_hits`` is a
        # thin view; device int32 (3,), None before any). Trainers
        # overwrite it with their psum'd batch totals so the split tuner
        # sees the fused path's traffic too.
        self.metrics = MetricsRegistry()
        self.metrics.gauge(
            TIER_HITS, shape=(3,), unit="hits",
            doc="per-tier feature hits [replicated, sharded, cold] of the "
                "last gather",
        )
        # host copy of the device region (rows [0, rep_rows + hot_rows) in
        # storage dtype) kept iff the L0/L1 boundary may move after
        # placement (auto_split or a nonzero replicate budget) — resplit
        # rebuilds both tiers from it without touching the cold tier
        self._region_host = None
        self._rep_ceiling_rows = 0  # auto_split never grows L0 past this
        # streaming-mutation version: bumped ONCE per published
        # apply_row_updates transaction. Consumers that captured tier
        # buffers (the fused trainer's mesh-wide cold copy) compare their
        # bound version against this and raise instead of serving stale
        # rows (quiver_tpu.streaming's invalidation contract).
        self.version = 0
        # quiver-ctl seam: the attached CacheController (None = standalone).
        # auto_split=True lazily creates a default one on first tuner call;
        # DistributedTrainer(controller=...) attaches a shared one. The
        # split decision itself lives in control/controller.py — this class
        # only measures (tier hits) and actuates (resplit/repin).
        self._controller = None
        self._resplit_from_tuner = False

    def _plan_split(self, n: int, f: int, itemsize: int, quantized: bool,
                    num_shards: int) -> tuple[int, int]:
        """(rep_rows, hot_rows) from the two per-device byte budgets."""
        if quantized:
            # the (N,) f32 scale array is replicated on EVERY device (all
            # tiers dequantize on device) — charge its 4N bytes against the
            # budgets before spending on 1-byte-element rows. Sharded budget
            # pays first (the scale is its dequant state even cold-only);
            # any shortfall eats into the replicate budget.
            scale_bytes = 4 * n
            combined = self.cache_budget + self.replicate_budget
            if 0 < combined < scale_bytes:
                # budget-edge: cannot even hold the dequant scales — degrade
                # to cold-only (exact, host-served) instead of crashing or
                # silently mis-splitting
                info_once(
                    "sharded-int8-budget-below-scale",
                    "ShardedFeature(int8): combined cache budget %d B is "
                    "smaller than the replicated dequant-scale array "
                    "(4 B x %d rows = %d B); degrading to a cold-only "
                    "store (exact, host-served). Grow device_cache_size "
                    "past 4*n bytes to enable device tiers.",
                    combined, n, scale_bytes, child="feature",
                )
                return 0, 0
            c_budget = self.cache_budget - scale_bytes
            r_budget = self.replicate_budget
            if c_budget < 0:
                r_budget = max(r_budget + c_budget, 0)
                c_budget = 0
            rep_rows = min(n, r_budget // f)
            hot_rows = min(n - rep_rows, (c_budget // f) * num_shards)
            return rep_rows, hot_rows
        row_bytes = f * itemsize
        rep_rows = min(n, self.replicate_budget // row_bytes)
        hot_rows = min(
            n - rep_rows, (self.cache_budget // row_bytes) * num_shards
        )
        return rep_rows, hot_rows

    def _place_region(self, region: np.ndarray, rep_rows: int) -> None:
        """(Re)build the L0 + L1 device tiers from the device-region rows.

        ``region`` holds rows [0, rep_rows + hot_rows) of the translated
        row space in storage dtype; the boundary at ``rep_rows`` decides
        which prefix is replicated."""
        old_rep, old_hot = self.rep, self.hot
        total = region.shape[0]
        rep_rows = max(0, min(int(rep_rows), total))
        if rep_rows > 0:
            self.rep = jax.device_put(
                region[:rep_rows], NamedSharding(self.mesh, P())
            )
        else:
            self.rep = None
        if total - rep_rows > 0:
            self.hot = ShardedTensor(
                self.mesh, self.axis, kernel=self._kernel,
                routed_alpha=self.routed_alpha,
            ).from_cpu_tensor(region[rep_rows:])
        else:
            self.hot = None
        self.rep_rows = rep_rows
        self.hot_rows = total - rep_rows
        if old_rep is not None and hasattr(old_rep, "delete"):
            old_rep.delete()
        if old_hot is not None:
            old_hot.delete()

    def from_cpu_tensor(self, tensor: np.ndarray) -> "ShardedFeature":
        tensor = np.asarray(tensor)
        quantized = (
            self.storage_dtype is not None
            and self.storage_dtype == np.dtype(np.int8)
        )
        if (
            self.storage_dtype is not None
            and not quantized
            and tensor.dtype != self.storage_dtype
        ):
            tensor = tensor.astype(self.storage_dtype)
        n, f = tensor.shape
        num_shards = self.mesh.shape[self.axis]
        rep_rows, hot_rows = self._plan_split(
            n, f, tensor.dtype.itemsize, quantized, num_shards
        )
        device_rows = rep_rows + hot_rows

        # degree order matters whenever a tier boundary cuts [0, n): the
        # L0 prefix wants the literal top-degree rows (pinned, unshuffled —
        # replication needs no shard balance), the sharded span keeps the
        # balance shuffle
        if self.csr_topo is not None and 0 < device_rows and (
            device_rows < n or 0 < rep_rows < n
        ):
            tensor, order = reorder_by_degree(
                tensor,
                self.csr_topo.degree,
                device_rows / n,
                seed=self.hot_shuffle_seed,
                pin_top=rep_rows,
            )
            self.csr_topo.feature_order = order
            self.feature_order = jnp.asarray(order)

        if quantized:
            tensor, scale = quantize_rows_int8(tensor)  # AFTER the reorder
            self.scale = jnp.asarray(scale)

        self.shape = (n, f)
        self.dtype = tensor.dtype
        self._rep_ceiling_rows = rep_rows
        if device_rows > 0:
            region = tensor[:device_rows]
            if self.auto_split or self.replicate_budget > 0:
                self._region_host = np.ascontiguousarray(region)
            self._place_region(region, rep_rows)
        if device_rows < n:
            self.cold, self._cold_is_host = to_pinned_host(
                tensor[device_rows:], mesh=self.mesh
            )
        # placement report (reference shard_tensor.py:153-162 LOG>>> parity)
        get_logger("feature").info(
            "feature tiers: %d/%d rows replicated (L0, %.1f MB/device), "
            "%d sharded over %d devices on mesh axis '%s' (%.1f MB/device); "
            "cold tier: %s",
            rep_rows,
            n,
            rep_rows * f * tensor.dtype.itemsize / 2**20,
            hot_rows,
            num_shards,
            self.axis,
            hot_rows * f * tensor.dtype.itemsize / num_shards / 2**20,
            "pinned host" if self._cold_is_host
            else ("none" if device_rows == n else "device"),
        )
        return self

    @property
    def last_tier_hits(self):
        """Per-tier hit counts of the last eager gather (thin view of the
        ``feature.tier_hits`` registry metric — new consumers should read
        ``self.metrics``)."""
        return self.metrics.value(TIER_HITS)

    @last_tier_hits.setter
    def last_tier_hits(self, value):
        self.metrics.set(TIER_HITS, value)

    @property
    def cache_ratio(self) -> float:
        """Fraction of rows resident in device HBM (both L0 and L1)."""
        if not self.shape:
            return 0.0
        return (self.rep_rows + self.hot_rows) / self.shape[0]

    @property
    def replicated_ratio(self) -> float:
        return self.rep_rows / self.shape[0] if self.shape else 0.0

    def resplit(self, rep_rows: int) -> None:
        """Move the L0/L1 boundary to ``rep_rows`` (eager, between batches).

        Tier membership in the translated row space is untouched — the
        first ``rep_rows`` device rows become the replicated block, the
        rest the sharded table — so gathers stay bit-identical; only the
        comm path serving each row changes. Requires the retained host
        region (``auto_split=True`` or ``replicate_budget > 0`` at
        construction). Compiled consumers retrace on the new table shapes.
        """
        if self._region_host is None:
            if max(0, int(rep_rows)) == self.rep_rows:
                return  # no-op split (e.g. a trainer passing budget 0)
            raise ValueError(
                "resplit needs the retained host region: construct "
                "ShardedFeature with replicate_budget > 0 or auto_split=True"
            )
        total = self._region_host.shape[0]
        rep_rows = max(0, min(int(rep_rows), total))
        if rep_rows == self.rep_rows:
            return
        self._place_region(self._region_host, rep_rows)
        # stale hits describe the OLD boundary; the tuner must not act on
        # them against the new one
        self.last_tier_hits = None
        if self._controller is not None and not self._resplit_from_tuner:
            # a MANUAL move invalidates the tuner's direction history (its
            # own moves keep it — that history IS the reversal dead-band)
            self._controller.split_tuner.reset()

    def replan(self, mesh: Mesh) -> "ShardedFeature":
        """Re-place the three-tier store onto a DIFFERENT mesh shape
        (elastic resume: a run checkpointed at F=8 continuing at F=4).

        The translated row space is reused verbatim — ``feature_order``,
        the per-row dequant ``scale``, and every row's bytes are
        unchanged; only the tier boundaries are re-planned for the new
        feature-axis size (the same per-device byte budgets buy fewer
        total sharded rows on a smaller mesh, so rows spill from L1 to
        the cold tier) and the tiers are re-placed. Gathers therefore
        stay bit-identical: the same rows come back, possibly over a
        different comm path — the same exactness contract as
        :meth:`resplit`. Compiled consumers must rebuild (their mesh
        changed, not just their shapes).
        """
        if self.shape is None:
            raise ValueError("replan() before from_cpu_tensor()")
        n, f = self.shape
        num_shards = int(mesh.shape[self.axis])
        quantized = (
            self.storage_dtype is not None
            and self.storage_dtype == np.dtype(np.int8)
        )
        # reassemble the full translated row space on host: device region
        # (retained host copy when available, else read back) + cold rows
        if self._region_host is not None:
            region = self._region_host
        else:
            parts = []
            if self.rep is not None:
                parts.append(np.asarray(self.rep))
            if self.hot is not None:
                parts.append(np.asarray(self.hot.table)[: self.hot_rows])
            region = (
                np.concatenate(parts) if len(parts) > 1
                else parts[0] if parts
                else np.zeros((0, f), self.dtype)
            )
        if self.cold is not None:
            full = np.concatenate([region, np.asarray(self.cold)])
        else:
            full = region
        rep_rows, hot_rows = self._plan_split(
            n, f, np.dtype(self.dtype).itemsize, quantized, num_shards
        )
        device_rows = rep_rows + hot_rows
        old_shards = self.mesh.shape[self.axis]
        self.mesh = mesh
        if self.cold is not None and hasattr(self.cold, "delete"):
            self.cold.delete()
        self.cold = None
        self._cold_is_host = False
        self._rep_ceiling_rows = rep_rows
        self._place_region(full[:device_rows], rep_rows)
        if device_rows < n:
            self.cold, self._cold_is_host = to_pinned_host(
                full[device_rows:], mesh=mesh
            )
        self._region_host = (
            np.ascontiguousarray(full[:device_rows])
            if (self.auto_split or self.replicate_budget > 0)
            else None
        )
        # stale hits describe the OLD mesh's tiers
        self.last_tier_hits = None
        get_logger("feature").info(
            "feature replan: %d -> %d shards on mesh axis '%s'; tiers now "
            "%d replicated / %d sharded / %d cold rows (same translated "
            "order — gathers stay bit-identical)",
            old_shards, num_shards, self.axis,
            rep_rows, self.hot_rows, n - device_rows,
        )
        return self

    def resplit_budget(self, replicate_budget: int | str) -> None:
        """:meth:`resplit` with the boundary given in bytes/device (same
        parser as ``device_cache_size``). Raises the L0 ceiling the
        ``auto_split`` tuner honors."""
        budget = parse_size_bytes(replicate_budget)
        row_bytes = self.shape[1] * np.dtype(self.dtype).itemsize
        rows = budget // max(row_bytes, 1)
        self._rep_ceiling_rows = max(self._rep_ceiling_rows, rows)
        self.resplit(rows)

    def repin(self, rows) -> None:
        """Re-tier the store so ``rows`` (ORIGINAL node ids, hottest
        first) occupy the FRONT of the translated row space — a
        measured-hottest set becomes the L0 prefix, spilling into L1 when
        longer than ``rep_rows``. This is the quiver-ctl actuation seam:
        the initial placement can only pin a degree-order prefix
        (``reorder_by_degree``), whereas ``repin`` accepts ARBITRARY hot
        sets (heat measured under real traffic need not correlate with
        degree).

        Tier SIZES are untouched; rows move WITH their bytes and dequant
        scales, and ``feature_order`` is re-composed with the inverse
        permutation, so every gather stays bitwise-identical — only the
        comm path serving each row changes (the exactness contract of
        :meth:`resplit`/:meth:`replan`). Duplicate ids keep their first
        (hottest) occurrence; ids beyond ``rep_rows + hot_rows`` rows are
        ignored (nothing to pin them into). Bumps ``version`` — compiled
        consumers (the fused trainer's captured cold copy) must
        ``refresh()``; :class:`~quiver_tpu.control.CacheController`
        does this for its trainer automatically.
        """
        if self.shape is None:
            raise ValueError("repin() before from_cpu_tensor()")
        n, f = self.shape
        device_rows = self.rep_rows + self.hot_rows
        if device_rows == 0:
            return  # cold-only store: no device tier to pin into
        ids = np.asarray(rows).reshape(-1).astype(np.int64)
        if ids.size == 0:
            return
        if ids.min() < 0 or ids.max() >= n:
            raise ValueError(
                f"repin ids must be in [0, {n}); got range "
                f"[{ids.min()}, {ids.max()}]"
            )
        _, first = np.unique(ids, return_index=True)
        ids = ids[np.sort(first)][:device_rows]
        if self.feature_order is not None:
            old_order = np.asarray(self.feature_order).astype(np.int64)
            t = old_order[ids]
        else:
            old_order = None
            t = ids
        # permutation of the translated space: the pinned set first (in
        # priority order), every other row keeping its relative order
        mask = np.ones(n, bool)
        mask[t] = False
        perm = np.concatenate([t, np.nonzero(mask)[0]])
        # reassemble the full translated table on host (replan's pattern:
        # retained host region when available, else device read-back)
        if self._region_host is not None:
            region = self._region_host
        else:
            parts = []
            if self.rep is not None:
                parts.append(np.asarray(self.rep))
            if self.hot is not None:
                parts.append(np.asarray(self.hot.table)[: self.hot_rows])
            region = (
                np.concatenate(parts) if len(parts) > 1
                else parts[0] if parts
                else np.zeros((0, f), self.dtype)
            )
        full = (
            np.concatenate([region, np.asarray(self.cold)])
            if self.cold is not None else region
        )
        new_full = full[perm]
        new_pos = np.empty(n, np.int64)
        new_pos[perm] = np.arange(n, dtype=np.int64)
        # compose: node id -> old translated row -> new translated row
        new_order = new_pos if old_order is None else new_pos[old_order]
        new_scale = (
            None if self.scale is None else np.asarray(self.scale)[perm]
        )
        # --- publish: host state + ONE version bump, then re-place the
        # device tiers from it (apply_row_updates' transaction shape) ---
        self.version += 1
        order_dtype = (
            old_order.dtype if old_order is not None
            else np.int32 if n <= np.iinfo(np.int32).max else np.int64
        )
        new_order = new_order.astype(order_dtype, copy=False)
        self.feature_order = jnp.asarray(new_order)
        if self.csr_topo is not None:
            self.csr_topo.feature_order = new_order
        if new_scale is not None:
            self.scale = jnp.asarray(new_scale)
        self._place_region(new_full[:device_rows], self.rep_rows)
        if self.cold is not None:
            old_cold = self.cold
            self.cold, self._cold_is_host = to_pinned_host(
                new_full[device_rows:], mesh=self.mesh
            )
            if hasattr(old_cold, "delete"):
                old_cold.delete()
        if self._region_host is not None:
            self._region_host = np.ascontiguousarray(
                new_full[:device_rows]
            )
        # pre-repin telemetry describes the OLD row order
        self.last_tier_hits = None
        get_logger("feature").info(
            "repin v%d: %d measured-hot rows pinned to the front of the "
            "device region (%d replicated / %d sharded rows; same bytes, "
            "recomposed order — gathers stay bit-identical)",
            self.version, ids.shape[0], self.rep_rows, self.hot_rows,
        )

    # -- streaming mutation (transactional row updates) ----------------------

    def apply_row_updates(self, ids, rows) -> None:
        """Transactionally update feature rows across ALL THREE tiers.

        ``ids`` are ORIGINAL node ids (translated through
        ``feature_order`` — the same id space gathers use); ``rows`` is
        the matching ``(U, feature_dim)`` block in the logical (float)
        dtype. The update is all-or-nothing: every patched host array
        (device region, cold rows, dequant scales for int8 storage) is
        built and validated ASIDE, then published together with ONE
        version bump; a validation failure leaves the store bit-identical.

        Both device tiers re-place from the patched region, so an updated
        row pinned in L0 serves the new value on EVERY chip and its L1
        shard agrees — no stale L0 serve (the streaming layer's
        invalidation contract). Consumers that captured tier buffers (the
        fused trainer's mesh-wide cold copy) detect the bumped
        ``version`` and must refresh instead of reading stale rows.
        Quantized (int8) stores re-quantize the updated rows per-row and
        patch their scales in the same transaction.
        """
        if self.shape is None:
            raise ValueError("apply_row_updates() before from_cpu_tensor()")
        n, f = self.shape
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape != (ids.shape[0], f):
            raise ValueError(
                f"rows must be ({ids.shape[0]}, {f}) to match ids/the "
                f"store's feature dim, got {rows.shape}"
            )
        if ids.size == 0:
            return
        if ids.min() < 0 or ids.max() >= n:
            raise ValueError(
                f"update ids must be in [0, {n}); got range "
                f"[{ids.min()}, {ids.max()}]"
            )
        if np.unique(ids).shape[0] != ids.shape[0]:
            raise ValueError(
                "duplicate ids in one row-update transaction are ambiguous "
                "(which value wins?); collapse duplicates upstream — the "
                "streaming layer's duplicate policy does this at admission"
            )
        if np.issubdtype(rows.dtype, np.floating) and not np.isfinite(
                rows).all():
            raise ValueError(
                "row updates contain non-finite values; a poisoned row "
                "must be rejected at the boundary, not cached"
            )
        quantized = (
            self.storage_dtype is not None
            and self.storage_dtype == np.dtype(np.int8)
        )
        # --- build every patched array ASIDE (pure numpy, no mutation) ---
        if self.feature_order is not None:
            t = np.asarray(self.feature_order).astype(np.int64)[ids]
        else:
            t = ids
        if quantized:
            new_rows, row_scale = quantize_rows_int8(
                rows.astype(np.float32, copy=False)
            )
            new_scale = np.asarray(self.scale).copy()
            new_scale[t] = row_scale
        else:
            new_rows = rows.astype(self.dtype, copy=False)
            new_scale = None
        device_rows = self.rep_rows + self.hot_rows
        in_region = t < device_rows
        new_region = None
        if device_rows > 0 and bool(in_region.any()):
            if self._region_host is not None:
                new_region = self._region_host.copy()
            else:
                parts = []
                if self.rep is not None:
                    parts.append(np.asarray(self.rep))
                if self.hot is not None:
                    parts.append(np.asarray(self.hot.table)[: self.hot_rows])
                new_region = (
                    np.concatenate(parts) if len(parts) > 1 else
                    parts[0].copy()
                )
            new_region[t[in_region]] = new_rows[in_region]
        new_cold = None
        if bool((~in_region).any()):
            new_cold = np.asarray(self.cold).copy()
            new_cold[t[~in_region] - device_rows] = new_rows[~in_region]
        # --- publish: host state + ONE version bump, then re-place the
        # device tiers from it (placements derive from the committed host
        # arrays, so a placement retry reproduces the same state) ---
        self.version += 1
        if new_scale is not None:
            self.scale = jnp.asarray(new_scale)
        if new_region is not None:
            if self._region_host is not None:
                self._region_host = new_region
            self._place_region(new_region, self.rep_rows)
        if new_cold is not None:
            old_cold = self.cold
            self.cold, self._cold_is_host = to_pinned_host(
                new_cold, mesh=self.mesh
            )
            if old_cold is not None and hasattr(old_cold, "delete"):
                old_cold.delete()
        # pre-update telemetry describes rows that no longer exist
        self.last_tier_hits = None
        get_logger("feature").info(
            "feature row update v%d: %d rows (%d device-region, %d cold)%s",
            self.version, ids.shape[0], int(in_region.sum()),
            int((~in_region).sum()),
            " + requantized scales" if quantized else "",
        )

    def note_degree_update(self, degree) -> None:
        """Feed post-mutation degrees to the existing split tuner so
        re-tiering follows mutation (ROADMAP item 3).

        A committed topology mutation changes the degree distribution the
        original L0/L1 boundary was planned from. This hands the NEW
        per-node degrees to the SAME grow/shrink/dead-band tuner that
        consumes measured tier hits (:meth:`_maybe_auto_split`), as a
        synthetic per-tier "hit mass" vector — degree-as-heat, the
        proxy the store's initial placement used. One boundary move per
        commit, at most; measured traffic keeps tuning afterwards.
        No-op unless ``auto_split=True`` (the tuner's own opt-in).

        With a :class:`~quiver_tpu.control.CacheController` attached the
        new degrees additionally seed its frequency sketch as a PRIOR
        (low weight — measured heat quickly dominates), so post-mutation
        re-tiering and measured-traffic re-tiering share one state."""
        if self.shape is None:
            return
        if self._controller is not None:
            prior = np.asarray(degree).reshape(-1)
            if prior.shape[0] == self.shape[0]:
                self._controller.observe_prior(prior)
        if not self.auto_split or self._region_host is None:
            return
        n, _ = self.shape
        degree = np.asarray(degree).reshape(-1)
        if degree.shape[0] != n:
            raise ValueError(
                f"degree must have {n} entries, got {degree.shape[0]}"
            )
        if self.feature_order is not None:
            # feature_order maps node id -> translated row; scatter the
            # new degrees into translated row order
            deg_t = np.zeros(n, dtype=np.int64)
            deg_t[np.asarray(self.feature_order).astype(np.int64)] = degree
        else:
            deg_t = degree.astype(np.int64)
        device_rows = self.rep_rows + self.hot_rows
        self.last_tier_hits = np.array(
            [deg_t[: self.rep_rows].sum(),
             deg_t[self.rep_rows: device_rows].sum(),
             deg_t[device_rows:].sum()],
        )
        self._maybe_auto_split()

    # graftlint: eager -- between-batch split tuner; under trace the hits
    def _maybe_auto_split(self) -> None:  # int() raises and except returns
        """Compat shim: feed the measured hit distribution to the
        attached :class:`~quiver_tpu.control.CacheController`'s
        :class:`~quiver_tpu.control.SplitTuner` and actuate its L0/L1
        boundary decision (``auto_split=True`` lazily creates a default
        controller on first call — the legacy opt-in keeps working with
        no code change).

        Consumes ``last_tier_hits`` (the previous eager batch — long
        completed, so the read is cheap). The tuner's signals are the
        rules this method used to hard-code — grow (double ``rep_rows``,
        up to the budget ceiling) when the hit mass sits just beyond the
        boundary, shrink (halve) when L0 is not earning its F× HBM —
        plus a reversal dead-band so a noisy batch at the ceiling cannot
        oscillate the boundary (see ``control/controller.py``).
        """
        hits = self.last_tier_hits
        if hits is None or self._region_host is None:
            return
        ctl = self._controller
        if ctl is None:
            if not self.auto_split:
                return
            from ..control import CacheController  # lazy: no import cycle
            ctl = CacheController.for_store(self)
        self.last_tier_hits = None
        try:
            h0, h1, _hc = (int(v) for v in np.asarray(hits))
        except Exception:  # noqa: BLE001 — a deleted/donated buffer must
            return  # not break the next gather
        total = self._region_host.shape[0]
        ceiling = min(self._rep_ceiling_rows, total)
        new = ctl.decide_split(h0, h1, self.rep_rows, ceiling)
        if new is None or new == self.rep_rows:
            return
        get_logger("feature").info(
            "auto-split: L0 %d vs sharded %d hits; moving "
            "replicated/sharded boundary %d -> %d rows",
            h0, h1, self.rep_rows, new,
        )
        self._resplit_from_tuner = True
        try:
            self.resplit(new)
        finally:
            self._resplit_from_tuner = False

    def delete(self) -> None:
        """Free all tier buffers now (reference ``shard_tensor.delete``)."""
        if self.hot is not None:
            self.hot.delete()
        for buf in (self.rep, self.cold, self.feature_order, self.scale):
            if buf is not None and hasattr(buf, "delete"):
                buf.delete()
        self.rep = self.hot = self.cold = None
        self.feature_order = self.scale = None
        self.rep_rows = self.hot_rows = 0
        self.last_tier_hits = None
        self._region_host = None

    def __getitem__(self, n_id):
        """Gather rows for data-axis-sharded (or replicated) node ids."""
        return self.gather(n_id)

    @property
    def last_routed_overflow(self):
        """Fallback-served lane count of the hot tier's last capped routed
        gather (device scalar; None before any routed call)."""
        return None if self.hot is None else self.hot.last_routed_overflow

    def gather(self, n_id, routed: bool = False, routed_cap="auto"):
        """Three-tier gather (replicated L0 / sharded L1 / host cold);
        ``routed=True`` uses the owner-routed L1 flavor (ids sharded over
        every mesh axis — see ShardedTensor.gather) instead of the psum
        flavor. ``routed_cap`` selects the routed comm mode ("auto" =
        capped buckets at ``ceil(routed_alpha*L/F)`` with auto-grow on
        overflow, None = uncapped full-length buckets, int = explicit
        capacity); overflow is fallback-served and counted in
        ``last_routed_overflow``.

        L0 and cold lanes enter the L1 gather as -1 (its invalid-lane
        sentinel), so they occupy zero routed-bucket capacity and
        contribute zero psum lanes — an L0 hit really does cost no
        interconnect. After an eager call ``last_tier_hits`` holds the
        batch's per-tier hit counts (int32 (3,)); with ``auto_split=True``
        the measured distribution moves the L0/L1 boundary before the next
        batch (:meth:`_maybe_auto_split`)."""
        if self.auto_split or self._controller is not None:
            self._maybe_auto_split()
        rep_gather = (
            None if self.rep is None
            else _hot_gather_fn(self.rep, self.kernel)
        )
        hot_gather = (
            None if self.hot is None
            else lambda ids: self.hot.gather(
                ids, routed=routed, routed_cap=routed_cap
            )
        )
        cold_gather = (
            None
            if self.cold is None
            else lambda ids: staged_gather(self.cold, ids, self._cold_is_host)
        )
        # int8 tiers dequantize after the (local, psum'd, or routed)
        # gather; only one shard contributes non-zero int8 rows so the
        # reduction is overflow-free
        rep_gather, hot_gather, cold_gather = wrap_dequant_gathers(
            self.scale, self.hot_rows, hot_gather, cold_gather,
            rep_gather, self.rep_rows,
        )
        out, hits = tiered_lookup(
            n_id, self.feature_order, self.hot_rows, hot_gather, cold_gather,
            rep_rows=self.rep_rows, rep_gather=rep_gather, hot_miss_id=-1,
            with_hits=True,
        )
        if not isinstance(hits, jax.core.Tracer):
            # eager call: stash for the split tuner / benchmarks (an outer
            # jit's tracer must not leak; in-program callers use
            # tiered_lookup's with_hits return directly)
            self.last_tier_hits = hits
        return out
