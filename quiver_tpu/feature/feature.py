"""Tiered feature store: HBM hot tier + host-memory cold tier.

Capability parity with the reference's ``quiver.Feature``
(torch-quiver feature.py:29-308): byte-budget hot/cold split, optional
degree-based reorder so high-degree (hot) nodes fill the cache
(feature.py:112-116), ``feature_order`` id translation on lookup
(feature.py:184-195), and two placement policies. TPU redesign:

* ``device_replicate`` → hot rows replicated in each device's HBM (same
  policy, feature.py:120-124).
* ``p2p_clique_replicate`` → hot rows *sharded over the mesh* with gathers
  riding ICI collectives (see feature/shard.py) — ICI plays NVLink's role
  (feature.py:126-166, quiver_feature.cu gather over ``dev_ptrs``).
* UVA zero-copy cold tier → pinned-host-resident cold shard with staged
  host-compute gathers (feature.py:169-182; TPU kernels cannot dereference
  host pointers, SURVEY §2.3 mapping (3)).

No IPC machinery (share_ipc/lazy rebuild, feature.py:234-308): one process
controls the mesh. The methods exist as no-op parity shims.

Cold-lane trick: every lookup gathers both tiers at full batch width (static
shapes), but lanes belonging to the other tier are pointed at row 0, so the
host-side cost collapses to the true cold-miss count's bandwidth (repeated
row 0 stays in cache) rather than the batch width.

``tiered_lookup`` is the shared tier-merge: up to three contiguous tiers in
the translated row space (replicated super-hot / hot / cold — see
feature/shard.py for the three-tier ShardedFeature) with optional
in-program per-tier hit counting.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.config import CachePolicy, parse_size_bytes
from ..core.memory import to_pinned_host
from ..core.topology import CSRTopo
from ..ops.election import KernelElection, validate_kernel_arg
from ..ops.sample import staged_gather
from ..utils.reorder import reorder_by_degree
from ..utils.trace import get_logger, info_once, trace_scope

__all__ = ["Feature", "HeteroFeature", "tiered_lookup", "resolve_gather_kernel"]


def _parse_storage_dtype(dtype):
    """None (keep input dtype) or a numpy dtype; "bf16"/"bfloat16" resolve
    through ml_dtypes (numpy has no native bfloat16; ml_dtypes ships with
    jax). int8 means per-row absmax quantization (scales kept alongside);
    other integer dtypes are rejected — a plain astype would truncate float
    features to garbage silently."""
    if dtype is None:
        return None
    if str(dtype) in ("bf16", "bfloat16"):
        from ml_dtypes import bfloat16

        return np.dtype(bfloat16)
    dt = np.dtype(dtype)
    if dt == np.dtype(np.int8):
        return dt
    if dt.kind != "f":
        raise ValueError(
            f"storage dtype must be a float dtype, 'bfloat16', or 'int8' "
            f"(quantized); got {dtype!r}"
        )
    return dt


def quantize_rows_int8(tensor: np.ndarray):
    """Per-row symmetric absmax int8 quantization.

    Returns (q (N, F) int8, scale (N,) float32) with
    ``row ~= q * scale[:, None]``; all-zero rows get scale 0 (and dequantize
    to exact zeros). Worst-case per-element error is scale/2 — bounded by
    0.4% of the row's absmax.
    """
    absmax = np.abs(tensor).max(axis=1).astype(np.float32)
    scale = absmax / 127.0
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(
        np.round(tensor / safe[:, None]), -127, 127
    ).astype(np.int8)
    return q, scale


def _dequant_fn(gather, scale_for):
    """Wrap an int8 row gather with on-device dequantization."""
    return lambda ids: gather(ids).astype(jnp.float32) * scale_for(ids)[:, None]


def wrap_dequant_gathers(scale, hot_rows: int, hot_gather, cold_gather,
                         rep_gather=None, rep_rows: int = 0):
    """Shared int8-dequant wrapping for the feature stores' tiered gathers.

    Scale ids live in the translated (reordered) global row space; each
    tier's gather receives ids local to its own table, so the scale lookup
    re-offsets them: replicated rows sit at [0, rep_rows), sharded-hot rows
    at [rep_rows, rep_rows + hot_rows), cold rows above. No-op when
    ``scale`` is None (unquantized storage).

    Returns ``(rep_gather, hot_gather, cold_gather)``.
    """
    if scale is None:
        return rep_gather, hot_gather, cold_gather
    if rep_gather is not None:
        rep_gather = _dequant_fn(rep_gather, lambda ids: scale[ids])
    if hot_gather is not None:
        hot_gather = _dequant_fn(
            hot_gather, lambda ids: scale[ids + rep_rows]
        )
    if cold_gather is not None:
        cold_gather = _dequant_fn(
            cold_gather, lambda ids: scale[ids + rep_rows + hot_rows]
        )
    return rep_gather, hot_gather, cold_gather


def validate_gather_kernel(kernel: str) -> str:
    """Eager argument check only — MUST NOT touch the JAX backend (object
    construction must stay cheap and never initialize/lock backend choice)."""
    return validate_kernel_arg(kernel)


def resolve_gather_kernel(kernel: str) -> str:
    """Resolve the hot-tier gather kernel choice. Touches the backend, so
    callers defer this to first use (never the constructor).

    ``"auto"`` on TPU ELECTS BY MEASURED THROUGHPUT between the Pallas
    row-DMA kernel (ops/pallas/gather.py — the ``quiver_tensor_gather``
    analogue, shard_tensor.cu.hpp:16-58) and the stock XLA take, via the
    shared ``ops.election.KernelElection`` machinery: a correctness smoke
    gates Pallas (a regression degrades auto to xla with a warning), then
    a 2-candidate fused-scan micro-bench picks the faster kernel — "it
    compiled and returned right rows" is not evidence it is fast (VERDICT
    r3 item 4). The election is cached per process and on disk (the shared
    ``QUIVER_ELECTION_CACHE`` file, keyed by device kind), and
    ``QUIVER_GATHER_KERNEL=pallas|xla`` overrides it. Off-TPU auto is xla
    (the Pallas CPU path is correct but slow). An explicit
    ``kernel="pallas"`` bypasses everything (fail loudly on request).
    Env-before-first-use: both knobs (the force and the cache path) are
    resolved ONCE per process at the first auto resolution — set them
    before the first gather; flipping them afterwards is inert
    (tests/test_kernel_election.py pins this).
    """
    return GATHER_ELECTION.resolve_request(kernel)


_PALLAS_GATHER_OK: bool | None = None


def _pallas_gather_usable() -> bool:
    """One-time compiled smoke of the Pallas gather (fail-safe for auto)."""
    global _PALLAS_GATHER_OK
    if _PALLAS_GATHER_OK is None:
        try:
            from ..ops.pallas.gather import gather_rows

            table = jnp.arange(32 * 128, dtype=jnp.float32).reshape(32, 128)
            ids = jnp.asarray([3, 0, 31, 7], dtype=jnp.int32)
            out = np.asarray(jax.block_until_ready(gather_rows(table, ids)))
            _PALLAS_GATHER_OK = bool(
                np.array_equal(out, np.asarray(table)[np.asarray(ids)])
            )
            if not _PALLAS_GATHER_OK:
                get_logger("feature").warning(
                    "pallas gather smoke returned wrong rows; kernel=auto "
                    "degrades to xla"
                )
        except Exception as e:  # noqa: BLE001 — any compile failure degrades
            get_logger("feature").warning(
                "pallas gather smoke failed (%s: %s); kernel=auto degrades "
                "to xla",
                type(e).__name__,
                str(e)[:200],
            )
            _PALLAS_GATHER_OK = False
    return _PALLAS_GATHER_OK


def _measure_gather_gbps(kernel: str, rows: int = 65536, dim: int = 128,
                         batch: int = 8192, reps: int = 16) -> float:
    """Median GB/s of one gather kernel over a fused id-scan.

    Dispatch-clean by construction (the round-3 lesson: per-call loops over
    a tunneled link measure the link): ONE program scans ``reps`` distinct
    id batches — distinct so XLA cannot hoist the gather out of the scan —
    with a checksum carry keeping every gathered column live, and one
    scalar readback ends the clock.
    """
    import time

    from jax import lax

    table = jnp.arange(rows * dim, dtype=jnp.float32).reshape(rows, dim)
    ids_mat = jax.random.randint(
        jax.random.PRNGKey(0), (reps, batch), 0, rows, dtype=jnp.int32
    )
    gather = _hot_gather_fn(table, kernel)

    @jax.jit
    def run(ids_all):
        def step(carry, ids):
            return carry + jnp.sum(gather(ids)), None
        total, _ = lax.scan(step, jnp.float32(0), ids_all)
        return total

    jax.block_until_ready(run(ids_mat))  # compile
    times = []
    for _ in range(3):
        t0 = time.time()
        jax.block_until_ready(run(ids_mat))
        times.append(time.time() - t0)
    nbytes = reps * batch * dim * 4
    return nbytes / sorted(times)[1] / 1e9


# GB/s election between the Pallas row-DMA gather and the XLA take. The
# rev is bumped when either gather kernel's implementation changes: the
# disk cache is keyed on rev + jax version + device kind, so a kernel or
# toolchain change forces re-election instead of trusting stale numbers.
# The smoke/measure callables defer module-global lookup so tests can
# monkeypatch feature._pallas_gather_usable / _measure_gather_gbps.
GATHER_ELECTION = KernelElection(
    "gather", env_var="QUIVER_GATHER_KERNEL", rev=1,
    smoke=lambda: _pallas_gather_usable(),  # noqa: PLW0108 — late binding
    measure=lambda kernel: _measure_gather_gbps(kernel),
    unit="GB/s", log_child="feature",
)


def _hot_gather_fn(table, kernel: str):
    """(ids) -> rows gather over the HBM-resident hot tier."""
    if kernel == "pallas":
        from ..ops.pallas.gather import gather_rows

        return lambda ids: gather_rows(table, ids.astype(jnp.int32))
    return lambda ids: table[ids]


class KernelChoice:
    """Lazy, retrace-stable gather-kernel choice for the feature stores.

    ``self._kernel`` holds the constructor request verbatim (it rides in
    pytree aux_data, so it must NEVER change — mutating it after a jit call
    would silently invalidate the jit cache and force a retrace); the
    resolved choice is cached separately. Resolution touches the backend,
    so it happens on first use, never in a constructor.
    """

    _kernel: str

    @property
    def kernel(self) -> str:
        resolved = getattr(self, "_kernel_resolved", None)
        if resolved is None:
            resolved = resolve_gather_kernel(self._kernel)
            self._kernel_resolved = resolved
        return resolved


def tiered_lookup(n_id, feature_order, hot_rows: int, hot_gather, cold_gather,
                  rep_rows: int = 0, rep_gather=None, hot_miss_id: int = 0,
                  with_hits: bool = False):
    """Shared tier-merge used by Feature and ShardedFeature.

    Three contiguous tiers in the translated (reordered) row space:

    * replicated super-hot ``[0, rep_rows)`` — ``rep_gather`` (zero-comm
      local gather, every device holds the full block);
    * hot ``[rep_rows, rep_rows + hot_rows)`` — ``hot_gather`` (HBM; sharded
      stores serve it with a psum or routed collective);
    * cold ``[rep_rows + hot_rows, n)`` — ``cold_gather`` (host-staged).

    Each gather is a callable (tier-local ids) -> rows; any may be None
    (its boundary range is then empty or covered by a neighbor). Invalid
    lanes (-1) return zero rows; lanes belonging to another tier are pointed
    at row 0 so their bandwidth collapses to one cached row — except the
    hot tier's, which carry ``hot_miss_id`` (pass -1 for the sharded
    gathers: their documented invalid-lane sentinel keeps other-tier lanes
    out of the routed buckets and the psum, so they cost zero collective
    lanes instead of a redundant row-0 fetch).

    ``with_hits=True`` additionally returns an int32 ``(3,)`` vector of
    VALID lanes per tier boundary ``[replicated, hot, cold]`` — the local
    per-tier hit counts (callers inside ``shard_map`` psum them).
    """
    n_id = jnp.asarray(n_id)
    valid = n_id >= 0
    ids = jnp.where(valid, n_id, 0)
    if feature_order is not None:
        ids = feature_order[ids]
    hot_end = rep_rows + hot_rows
    have_rep = rep_gather is not None and rep_rows > 0
    # (mask, gather, row offset into the tier's table, other-tier miss id);
    # masks partition the valid id range, in tier order
    tiers = []
    if have_rep:
        tiers.append((ids < rep_rows, rep_gather, 0, 0))
    if hot_gather is not None:
        m = ids < hot_end
        if have_rep:
            m = m & (ids >= rep_rows)
        tiers.append((m, hot_gather, rep_rows, hot_miss_id))
    if cold_gather is not None:
        tiers.append((ids >= hot_end, cold_gather, hot_end, 0))
    if len(tiers) == 1:
        _, gather, off, _ = tiers[0]
        out = gather(ids - off if off else ids)
    else:
        out = None
        for mask, gather, off, miss in tiers:
            part = gather(jnp.where(mask, ids - off, miss))
            out = part if out is None else jnp.where(mask[:, None], part, out)
    out = jnp.where(valid[:, None], out, 0)
    if not with_hits:
        return out
    hits = jnp.stack([
        jnp.sum((valid & (ids < rep_rows)).astype(jnp.int32)),
        jnp.sum((valid & (ids >= rep_rows) & (ids < hot_end)).astype(jnp.int32)),
        jnp.sum((valid & (ids >= hot_end)).astype(jnp.int32)),
    ])
    return out, hits


@jax.tree_util.register_pytree_node_class
class Feature(KernelChoice):
    """Tiered node-feature table with jit-compatible lookup.

    Args mirror the reference's constructor (feature.py:29-44):
      rank, device_list: accepted-and-INERT parity slots. The reference
        pins one CUDA device per process rank; under single-controller
        SPMD the mesh owns placement, so these only survive as attributes
        for call-site compatibility — nothing reads them.
      device_cache_size: hot-tier byte budget ("0.9M", "3GB", int bytes).
      cache_policy: "device_replicate" | "p2p_clique_replicate"/"mesh_shard".
      csr_topo: enables degree-based hot ordering; sets csr_topo.feature_order.
      replicate_budget: L0 super-hot byte budget (same parser). Under
        device_replicate the whole hot tier is ALREADY a zero-comm
        per-device replica, so the L0/L1 distinction collapses: the bytes
        are folded into ``device_cache_size`` (one-shot INFO log). The
        argument exists so policy configs port unchanged between Feature
        and ShardedFeature, where L0 is a real third tier.
    """

    def __init__(
        self,
        rank: int = 0,
        device_list=None,
        device_cache_size: int | str = 0,
        cache_policy: str | CachePolicy = CachePolicy.DEVICE_REPLICATE,
        csr_topo: CSRTopo | None = None,
        hot_shuffle_seed: int = 0,
        kernel: str = "auto",
        dtype=None,
        replicate_budget: int | str = 0,
    ):
        self.rank = rank
        self.device_list = device_list or [0]
        if rank != 0 or (device_list is not None and list(device_list) != [0]):
            # reference-ported code gets a runtime signal that its device
            # pinning did nothing (VERDICT r5 weak #7)
            info_once(
                "feature-inert-parity-args",
                "Feature(rank=%r, device_list=%r) accepted for reference "
                "API parity but INERT: under single-controller SPMD the "
                "mesh owns placement; nothing reads these arguments",
                rank, device_list, child="feature",
            )
        self.cache_budget = parse_size_bytes(device_cache_size)
        self.replicate_budget = parse_size_bytes(replicate_budget)
        if self.replicate_budget:
            # device_replicate's hot tier is already replicated per device —
            # there is no cheaper tier to promote rows into, so the L0
            # budget simply buys more hot rows
            info_once(
                "feature-replicate-budget-folded",
                "Feature(device_replicate) already replicates its hot tier "
                "per device; replicate_budget=%d B folded into "
                "device_cache_size (one zero-comm tier)",
                self.replicate_budget, child="feature",
            )
            self.cache_budget += self.replicate_budget
        self.cache_policy = CachePolicy.parse(cache_policy)
        self.csr_topo = csr_topo
        self.hot_shuffle_seed = hot_shuffle_seed
        self._kernel = validate_gather_kernel(kernel)
        # storage dtype override: "bfloat16" halves the byte budget per row
        # (so ~2x rows fit the same HBM cache and every gather moves half
        # the bytes) — the TPU-first answer to the reference's hardcoded
        # float32 ShardTensor (quiver_feature.cu:65-74). None keeps the
        # input dtype.
        self.storage_dtype = _parse_storage_dtype(dtype)
        # populated by from_cpu_tensor
        self.hot = None
        self.cold = None
        self.feature_order = None
        self.scale = None  # (N,) per-row dequant scales (int8 storage only)
        self.hot_rows = 0
        self.shape = None
        self.dtype = None
        self._cold_is_host = False

    # -- construction -------------------------------------------------------

    def from_cpu_tensor(self, tensor) -> "Feature":
        """Split, (optionally) reorder, and place the feature table."""
        if self.cache_policy is CachePolicy.MESH_SHARD:
            raise NotImplementedError(
                "mesh_shard placement lives in quiver_tpu.feature.shard."
                "ShardedFeature; plain Feature supports device_replicate only"
            )
        tensor = np.asarray(tensor)
        quantized = (
            self.storage_dtype is not None
            and self.storage_dtype == np.dtype(np.int8)
        )
        if (
            self.storage_dtype is not None
            and not quantized
            and tensor.dtype != self.storage_dtype
        ):
            tensor = tensor.astype(self.storage_dtype)
        n, f = tensor.shape
        if quantized:
            # the (N,) float32 dequant-scale array lives in HBM for BOTH
            # tiers (cold gathers dequantize on device too) — charge all
            # N*4 scale bytes to the budget up front, then spend the rest
            # on 1-byte-per-element hot rows
            row_bytes = f
            hot_rows = min(n, max(self.cache_budget - 4 * n, 0) // row_bytes)
        else:
            row_bytes = f * tensor.dtype.itemsize
            hot_rows = min(n, self.cache_budget // row_bytes)

        if self.csr_topo is not None and hot_rows < n:
            hot_ratio = hot_rows / n
            tensor, order = reorder_by_degree(
                tensor, self.csr_topo.degree, hot_ratio, seed=self.hot_shuffle_seed
            )
            self.csr_topo.feature_order = order
            self.feature_order = jnp.asarray(order)

        scale = None
        if quantized:
            tensor, scale = quantize_rows_int8(tensor)  # AFTER the reorder
            self.scale = jnp.asarray(scale)  # (N,) stays in HBM (4B/row)

        self.shape = (n, f)
        self.dtype = tensor.dtype
        self.hot_rows = int(hot_rows)
        if hot_rows > 0:
            self.hot = jnp.asarray(tensor[:hot_rows])
        if hot_rows < n:
            self.cold, self._cold_is_host = to_pinned_host(tensor[hot_rows:])
        # placement report (the reference's LOG>>> cache-% print, feature.py:109-111)
        get_logger("feature").info(
            "%.2f%% of feature (%d/%d rows, %.1f MB) cached in HBM "
            "(device_replicate); cold tier: %s",
            100.0 * hot_rows / max(n, 1),
            hot_rows,
            n,
            hot_rows * row_bytes / 2**20,
            "pinned host" if self._cold_is_host else ("none" if hot_rows == n else "device"),
        )
        return self

    @classmethod
    def from_numpy(cls, tensor, **kwargs) -> "Feature":
        return cls(**kwargs).from_cpu_tensor(tensor)

    # -- lookup -------------------------------------------------------------

    def __getitem__(self, n_id):
        """Gather rows for (possibly padded, -1 sentinel) node ids.

        Jit-composable; invalid lanes return zero rows.
        """
        hot_gather = None if self.hot is None else _hot_gather_fn(self.hot, self.kernel)
        cold_gather = (
            None
            if self.cold is None
            else lambda ids: staged_gather(self.cold, ids, self._cold_is_host)
        )
        _, hot_gather, cold_gather = wrap_dequant_gathers(
            self.scale, self.hot_rows, hot_gather, cold_gather
        )
        with trace_scope("feature_gather"):
            return tiered_lookup(
                n_id, self.feature_order, self.hot_rows, hot_gather, cold_gather
            )

    def size(self, dim: int) -> int:
        return self.shape[dim]

    @property
    def cache_ratio(self) -> float:
        return self.hot_rows / self.shape[0] if self.shape else 0.0

    # -- pytree (so Feature can be closed over / passed into jit) ----------

    def tree_flatten(self):
        children = (self.hot, self.cold, self.feature_order, self.scale)
        aux = (
            self.rank,
            tuple(self.device_list),
            self.cache_budget,
            self.cache_policy,
            self.hot_rows,
            self.shape,
            self.dtype,
            self._cold_is_host,
            self.hot_shuffle_seed,
            self._kernel,
            self.storage_dtype,
            self.replicate_budget,
        )
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = cls.__new__(cls)
        obj.hot, obj.cold, obj.feature_order, obj.scale = children
        (
            obj.rank,
            device_list,
            obj.cache_budget,
            obj.cache_policy,
            obj.hot_rows,
            obj.shape,
            obj.dtype,
            obj._cold_is_host,
            obj.hot_shuffle_seed,
            obj._kernel,
            obj.storage_dtype,
            obj.replicate_budget,
        ) = aux
        obj.device_list = list(device_list)
        obj.csr_topo = None
        return obj

    def delete(self) -> None:
        """Free the device/host buffers now (reference ``shard_tensor.delete``,
        SURVEY §2.5 — planned there, real here). The object is unusable after."""
        for buf in (self.hot, self.cold, self.feature_order, self.scale):
            if buf is not None and hasattr(buf, "delete"):
                buf.delete()
        self.hot = self.cold = self.feature_order = self.scale = None
        self.hot_rows = 0

    # -- reference API shims (IPC is a no-op under single-controller SPMD) --

    def share_ipc(self):
        return self

    @classmethod
    def new_from_ipc_handle(cls, rank, handle):
        return handle

    @classmethod
    def lazy_from_ipc_handle(cls, handle):
        return handle


class HeteroFeature:
    """Per-node-type feature tables for heterogeneous graphs.

    A thin dict-of-Feature: ``__getitem__`` takes the sampler's ``n_id``
    dict and returns {type: rows} — each type's table keeps its own tiering
    policy (hot/cold budget, reorder) independently.
    """

    def __init__(self, features: dict):
        self.features = dict(features)

    @classmethod
    def from_cpu_tensors(cls, tensors: dict, **feature_kwargs) -> "HeteroFeature":
        return cls({
            t: Feature(**feature_kwargs).from_cpu_tensor(arr)
            for t, arr in tensors.items()
        })

    def __getitem__(self, n_id_dict: dict) -> dict:
        return {t: self.features[t][ids] for t, ids in n_id_dict.items()}

    def size(self, node_type: str, dim: int) -> int:
        return self.features[node_type].size(dim)
