"""Dataset ingestion: real-graph loaders + a synthetic acceptance benchmark.

The reference's examples train on real Reddit/OGB graphs loaded through
torch_geometric / the ogb package (examples/pyg/reddit_quiver.py:20-34,
benchmarks/ogbn-papers100M/preprocess.py:54-106). This module provides the
same ingestion capability without those libraries: it reads the datasets'
standard on-disk layouts directly —

* ``load_ogb_raw``: the OGB raw CSV layout (``raw/edge.csv.gz``,
  ``node-feat.csv.gz``, ``node-label.csv.gz``, ``split/<scheme>/*.csv.gz``)
  that every ogbn-* download unpacks to.
* ``load_reddit``: the PyG Reddit layout (``reddit_data.npz`` +
  ``reddit_graph.npz`` scipy-sparse adjacency).
* ``planted_partition``: a stochastic-block-model graph with noisy one-hot
  features and a *computable Bayes accuracy* — the acceptance benchmark for
  environments where dataset downloads are impossible. A correct
  sampler+feature+model stack must recover well above feature-only Bayes
  (graph structure carries the class signal); a broken one cannot.

All loaders return a :class:`GraphDataset`: ``CSRTopo`` + features + labels
+ canonical splits — everything the reference's training scripts pull out of
``PygNodePropPredDataset``/``Reddit`` (dist_sampling_ogb_products_quiver.py:
139-151).
"""

from __future__ import annotations

import os
import types
from typing import NamedTuple

import numpy as np

from .core.topology import CSRTopo

__all__ = [
    "GraphDataset",
    "load_dataset",
    "load_ogb_raw",
    "load_reddit",
    "planted_partition",
]


class GraphDataset(NamedTuple):
    """Everything a training script needs, in quiver-tpu's native types."""

    name: str
    topo: CSRTopo
    features: np.ndarray  # (N, F) float32
    labels: np.ndarray  # (N,) int32, -1 where unlabeled
    train_idx: np.ndarray
    val_idx: np.ndarray
    test_idx: np.ndarray
    num_classes: int
    # immutable default: a plain {} here would be shared across every
    # instance built without meta, so one caller's mutation would leak
    meta: dict = types.MappingProxyType({})

    @property
    def node_count(self) -> int:
        return self.topo.node_count

    @property
    def feature_dim(self) -> int:
        return self.features.shape[1]


def load_dataset(name: str, root: str | None = None, **kwargs) -> GraphDataset:
    """Dispatch by name: "reddit", "ogbn-*", or "planted[:n[:classes]]"."""
    if name.startswith("planted"):
        parts = name.split(":")
        n = int(parts[1]) if len(parts) > 1 else 10_000
        classes = int(parts[2]) if len(parts) > 2 else 8
        return planted_partition(n=n, num_classes=classes, **kwargs)
    if root is None:
        raise ValueError(
            f"dataset {name!r} needs root= pointing at its on-disk copy "
            "(downloads are not performed)"
        )
    if name == "reddit":
        return load_reddit(root)
    if name.startswith("ogbn-"):
        return load_ogb_raw(name, root, **kwargs)
    raise ValueError(f"unknown dataset {name!r}")


def _read_csv_gz(path, dtype):
    import pandas as pd

    return pd.read_csv(path, header=None).to_numpy(dtype=dtype)


def load_ogb_raw(
    name: str, root: str, split_scheme: str | None = None, undirected: bool = True
) -> GraphDataset:
    """Load an ogbn-* dataset from its raw CSV layout.

    ``root`` is the directory containing ``raw/`` and ``split/`` (i.e. what
    the ogb package unpacks, e.g. ``<root>/ogbn_products``). No ogb
    dependency: plain pandas reads. ``undirected=True`` symmetrizes the edge
    list, matching PyG/ogb's ToUndirected for products.
    """
    base = root
    if not os.path.isdir(os.path.join(base, "raw")):
        cand = os.path.join(root, name.replace("-", "_"))
        if os.path.isdir(os.path.join(cand, "raw")):
            base = cand
        else:
            raise FileNotFoundError(
                f"no raw/ under {root} (or {cand}) — point root at the "
                "unpacked ogb dataset directory"
            )
    raw = os.path.join(base, "raw")
    edges = _read_csv_gz(os.path.join(raw, "edge.csv.gz"), np.int64).T  # (2, E)
    feat = _read_csv_gz(os.path.join(raw, "node-feat.csv.gz"), np.float32)
    labels = _read_csv_gz(os.path.join(raw, "node-label.csv.gz"), np.int64).ravel()
    if undirected:
        edges = np.concatenate([edges, edges[::-1]], axis=1)

    split_dir = os.path.join(base, "split")
    if split_scheme is None:
        schemes = sorted(os.listdir(split_dir)) if os.path.isdir(split_dir) else []
        if not schemes:
            raise FileNotFoundError(f"no split/ under {base}")
        split_scheme = schemes[0]
    sdir = os.path.join(split_dir, split_scheme)
    train_idx = _read_csv_gz(os.path.join(sdir, "train.csv.gz"), np.int64).ravel()
    val_idx = _read_csv_gz(os.path.join(sdir, "valid.csv.gz"), np.int64).ravel()
    test_idx = _read_csv_gz(os.path.join(sdir, "test.csv.gz"), np.int64).ravel()

    topo = CSRTopo(edge_index=edges)
    return GraphDataset(
        name=name,
        topo=topo,
        features=feat,
        labels=labels.astype(np.int32),
        train_idx=train_idx,
        val_idx=val_idx,
        test_idx=test_idx,
        num_classes=int(labels.max()) + 1,
        meta={"split_scheme": split_scheme, "undirected": undirected},
    )


def load_reddit(root: str) -> GraphDataset:
    """Load Reddit from the PyG raw layout: ``reddit_data.npz`` (feature,
    label, node_types: 1=train, 2=val, 3=test) + ``reddit_graph.npz``
    (scipy sparse adjacency)."""
    import scipy.sparse as sp

    data = np.load(os.path.join(root, "reddit_data.npz"))
    adj = sp.load_npz(os.path.join(root, "reddit_graph.npz")).tocsr()
    types = data["node_types"]
    labels = data["label"].astype(np.int32)
    topo = CSRTopo(indptr=adj.indptr.astype(np.int64),
                   indices=adj.indices.astype(np.int64))
    return GraphDataset(
        name="reddit",
        topo=topo,
        features=data["feature"].astype(np.float32),
        labels=labels,
        train_idx=np.where(types == 1)[0],
        val_idx=np.where(types == 2)[0],
        test_idx=np.where(types == 3)[0],
        num_classes=int(labels.max()) + 1,
    )


def planted_partition(
    n: int = 10_000,
    num_classes: int = 8,
    avg_degree: float = 12.0,
    homophily: float = 0.9,
    feature_noise: float = 2.0,
    feature_dim: int | None = None,
    train_frac: float = 0.5,
    val_frac: float = 0.1,
    seed: int = 0,
) -> GraphDataset:
    """Stochastic-block-model graph with noisy one-hot features.

    Each node gets a uniform class; edges pick their endpoint's class with
    probability ``homophily`` (else a uniform random class) — so neighbors
    agree with the node's class w.p. homophily + (1-homophily)/C. Features
    are ``onehot(label) + N(0, feature_noise)``: individually weak, so a
    model must aggregate neighborhoods to do well — exactly the signal a
    sampling+gather stack has to preserve.

    The feature-only Bayes accuracy is computable (see
    :func:`feature_bayes_accuracy`); a correct GraphSAGE pipeline beats it
    by a wide margin, a subtly-broken sampler or gather falls to it (or
    below). ``meta["feature_bayes_acc"]`` carries the Monte-Carlo estimate.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    feature_dim = feature_dim or num_classes

    # SBM edges: for each directed edge slot, draw the target from the
    # source's class w.p. homophily, else from anywhere
    m = int(n * avg_degree)
    src = rng.integers(0, n, m)
    same = rng.random(m) < homophily
    # targets of the same class: index into the per-class node pools
    class_pool = [np.where(labels == c)[0] for c in range(num_classes)]
    dst = np.empty(m, dtype=np.int64)
    for c in range(num_classes):
        lane = same & (labels[src] == c)
        pool = class_pool[c]
        dst[lane] = pool[rng.integers(0, len(pool), int(lane.sum()))]
    rand_lane = ~same
    dst[rand_lane] = rng.integers(0, n, int(rand_lane.sum()))
    ei = np.stack([src, dst])
    ei = np.concatenate([ei, ei[::-1]], axis=1)  # undirected

    feat = np.zeros((n, feature_dim), np.float32)
    feat[np.arange(n), labels % feature_dim] = 1.0
    feat += rng.normal(scale=feature_noise, size=(n, feature_dim)).astype(
        np.float32
    )

    perm = rng.permutation(n)
    n_train = int(n * train_frac)
    n_val = int(n * val_frac)
    bayes = feature_bayes_accuracy(num_classes, feature_noise, seed=seed + 1)
    return GraphDataset(
        name=f"planted:{n}:{num_classes}",
        topo=CSRTopo(edge_index=ei),
        features=feat,
        labels=labels,
        train_idx=perm[:n_train],
        val_idx=perm[n_train:n_train + n_val],
        test_idx=perm[n_train + n_val:],
        num_classes=num_classes,
        meta={
            "homophily": homophily,
            "feature_noise": feature_noise,
            "feature_bayes_acc": bayes,
        },
    )


def feature_bayes_accuracy(
    num_classes: int, noise: float, trials: int = 200_000, seed: int = 0
) -> float:
    """Monte-Carlo Bayes accuracy of the *feature-only* classifier for the
    planted-partition generative model (argmax over onehot + N(0, noise) —
    the optimal rule given one node's features and no graph)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(scale=noise, size=(trials, num_classes))
    x[:, 0] += 1.0  # true class 0 by symmetry
    return float((np.argmax(x, axis=1) == 0).mean())
