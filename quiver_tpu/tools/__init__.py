"""Developer tooling shipped with the package.

``tools.lint`` is graftlint — the trace-safety / collective-consistency
static analyzer (``python -m quiver_tpu.tools.lint``). Tools here are
stdlib-only at analysis time: they parse source with ``ast`` and never
execute or import the code under analysis.
"""
