"""Shared SARIF 2.1.0 plumbing for the repo's static-analysis tools.

graftlint (``tools/lint``, AST-level) and graftaudit (``tools/audit``,
jaxpr/HLO-level) report through one schema so CI can upload a single
merged ``analysis.sarif`` artifact and reviewers get one annotation
stream. Each tool supplies its rule registry (name -> doc/family) and its
findings; ``merge_sarif`` concatenates per-tool runs into one document
(the SARIF shape for multi-tool results — one ``runs`` entry per driver).
"""

from __future__ import annotations

__all__ = ["build_sarif_doc", "merge_sarif", "merge_sarif_files"]

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _result(f, suppressed: bool) -> dict:
    res = {
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path.replace("\\", "/")},
                "region": {"startLine": int(f.line),
                           "startColumn": int(f.col) + 1},
            },
        }],
    }
    if suppressed:
        res["suppressions"] = [{"kind": "inSource",
                                "justification": "reasoned inline "
                                                 "suppression"}]
    return res


def build_sarif_doc(tool_name: str, rule_docs: dict, family_of,
                    findings, suppressed) -> dict:
    """One-run SARIF document for one tool.

    Args:
      tool_name: ``tool.driver.name`` (``graftlint`` / ``graftaudit``).
      rule_docs: rule name -> docstring (first line becomes the short
        description).
      family_of: rule name -> family string (driver rule property).
      findings: active findings (``rule``/``path``/``line``/``col``/
        ``message`` attributes — both tools' Finding shapes qualify).
      suppressed: findings silenced by a reasoned waiver/suppression.
    """
    rules = [
        {
            "id": name,
            "shortDescription": {
                "text": (doc.splitlines()[0] if doc else name)},
            "fullDescription": {"text": doc},
            "properties": {"family": family_of(name)},
        }
        for name, doc in rule_docs.items()
    ]
    results = [_result(f, False) for f in findings]
    results += [_result(f, True) for f in suppressed]
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "informationUri":
                    "https://github.com/quiver-tpu/quiver-tpu",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def merge_sarif(docs) -> dict:
    """Concatenate the ``runs`` of several SARIF documents into one."""
    runs = []
    for doc in docs:
        runs.extend(doc.get("runs", []))
    return {"$schema": SARIF_SCHEMA, "version": "2.1.0", "runs": runs}


def merge_sarif_files(in_paths, out_path) -> None:
    """CLI-facing merge: ``python -c "from quiver_tpu.tools.sarif import
    merge_sarif_files; merge_sarif_files(['lint.sarif', 'audit.sarif'],
    'analysis.sarif')"``. Missing inputs are skipped so a partially
    failed CI matrix still uploads what it has."""
    import json
    import os

    docs = []
    for p in in_paths:
        if os.path.exists(p):
            with open(p) as fh:
                docs.append(json.load(fh))
    # atomic publish: the merged artifact is uploaded/read by other steps,
    # so a crash mid-write must leave an invisible temp, never a torn file
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as fh:
        json.dump(merge_sarif(docs), fh, indent=2)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp_path, out_path)
