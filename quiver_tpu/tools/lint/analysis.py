"""Trace-reachability analysis over the linted source tree.

The core question every graftlint rule asks is *"does this code run during
a jax trace?"* — the QUIVER_COUNTS bug (PR 3) was exactly an ``os.environ``
read that LOOKED live but executed once at first trace. Answering it
statically needs a conservative call-graph walk:

1. **Entry points**: functions decorated with ``jit``/``pmap`` (directly or
   via ``partial``), functions/lambdas passed into trace wrappers
   (``jit``, ``shard_map``, ``vmap``, ``grad``, ``lax.scan``/``cond``/
   ``while_loop``/``fori_loop``/``switch``/``associative_scan``, ...), and
   every method of a ``flax`` ``nn.Module`` subclass (flax traces them by
   construction).
2. **Propagation**: from a traced function, a call by name marks the callee
   traced. Name calls resolve lexically (params/locals shadow globals);
   attribute calls (``self.routed_gather(...)``) resolve by terminal name
   against every named function in the analyzed file set — conservative:
   homonyms all get marked. Class instantiation marks ``__init__``;
   property *access* from traced code marks the property body (that is how
   ``KernelChoice.kernel`` runs at trace time); local functions/lambdas
   passed as arguments or returned from traced code are marked (closure
   callbacks like ``BucketRoute.exchange``'s ``serve``).
3. **Barriers**: a *resolve-once* function — ``global X`` + an
   ``if X is [not] None`` guard + an assignment to ``X`` — runs its slow
   path once per process, not once per trace. The walk neither flags nor
   descends into it: this is the sanctioned pattern
   (``models/layers.resolve_counts_strategy``) the env-at-trace rule points
   users at.

Everything here is stdlib ``ast``; the analyzed code is never imported.
"""

from __future__ import annotations

import ast
import dataclasses
import re

__all__ = [
    "FuncInfo",
    "SourceFile",
    "Project",
    "analyze",
    "terminal_name",
    "iter_owned",
    "is_env_read",
]

# terminal callable name -> positional indices holding traced functions
TRACE_WRAPPERS: dict[str, tuple[int, ...]] = {
    "jit": (0,),
    "pjit": (0,),
    "pmap": (0,),
    "vmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "jacfwd": (0,),
    "jacrev": (0,),
    "hessian": (0,),
    "linearize": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "named_call": (0,),
    "shard_map": (0,),
    "scan": (0,),
    "associative_scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "custom_jvp": (0,),
    "custom_vjp": (0,),
}

# decorator names that make the decorated function a trace entry
_JIT_DECORATORS = {"jit", "pjit", "pmap"}

# attribute accesses that read STATIC array metadata, not traced values
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                "weak_type", "itemsize"}

# attribute-call names that are overwhelmingly builtin container/array
# methods: linking them by terminal name to same-named project functions
# produces wrong call-graph edges (e.g. ``tiers.append(...)`` marking a
# project-level ``def append`` traced)
_BUILTIN_METHOD_NAMES = frozenset(
    n for t in (list, dict, str, set, tuple, bytes, frozenset)
    for n in dir(t) if not n.startswith("_")
) | {"astype", "reshape", "item", "view", "tolist", "block_until_ready",
     "at", "set", "add", "max", "min", "sum", "mean", "all", "any"}

# callables whose function-valued arguments run on the HOST (outside the
# trace): passing a function here must not mark it traced
_HOST_CALLBACK_WRAPPERS = {"callback", "io_callback", "pure_callback",
                           "debug_callback"}

# a registry metric name: dotted lowercase segments, each starting with a
# letter ("feature.routed_overflow") — version strings like "1.0" do not
# match
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")


def terminal_name(expr: ast.AST) -> str | None:
    """The rightmost name of a call target: ``jax.lax.psum`` -> ``psum``."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def iter_owned(func_node: ast.AST):
    """Yield the AST nodes lexically owned by one function — its body minus
    the bodies of nested function/class definitions (those have their own
    FuncInfo / are analyzed separately). Nested defs themselves are
    yielded (the ``def`` executes in this scope) but never descended
    into — including when they sit directly in the body (a module's
    top-level functions must not leak their statements into the module
    pseudo-function)."""
    defs = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
    if isinstance(func_node, ast.Lambda):
        roots = [func_node.body]
    else:
        roots = list(func_node.body)
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, defs):
            continue
        for child in ast.iter_child_nodes(node):
            if isinstance(child, defs):
                stack.append(child)  # yield the def, not its body
                continue
            stack.append(child)


def is_env_read(node: ast.AST) -> str | None:
    """Return a short description when ``node`` reads the environment:
    ``os.environ.get(...)``, ``os.environ[...]``, ``os.getenv(...)`` (plus
    the bare-``environ`` spellings a ``from os import environ`` leaves)."""
    if isinstance(node, ast.Call):
        t = terminal_name(node.func)
        if t == "getenv":
            return "os.getenv(...)"
        if t == "get" and isinstance(node.func, ast.Attribute):
            if terminal_name(node.func.value) == "environ":
                return "os.environ.get(...)"
    elif isinstance(node, ast.Subscript):
        if terminal_name(node.value) == "environ":
            return "os.environ[...]"
    return None


@dataclasses.dataclass
class FuncInfo:
    """Per-function facts collected in one parse pass."""

    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda | Module
    path: str
    name: str | None  # None for lambdas and the module pseudo-function
    qualname: str
    parent: "FuncInfo | None"
    class_name: str | None = None
    params: list[str] = dataclasses.field(default_factory=list)
    # positional parameters WITHOUT defaults, minus self/cls: the arguments
    # that plausibly carry tracers (keyword-only / defaulted args are
    # config by convention in this codebase)
    taint_params: list[str] = dataclasses.field(default_factory=list)
    local_names: set[str] = dataclasses.field(default_factory=set)
    imported_names: set[str] = dataclasses.field(default_factory=set)
    local_funcs: dict[str, list["FuncInfo"]] = dataclasses.field(
        default_factory=dict)
    # (kind, name, node): kind is "name" | "attr" | "class"
    calls: list[tuple[str, str, ast.AST]] = dataclasses.field(
        default_factory=list)
    # local functions/lambdas referenced as call arguments or returned
    passed_local_funcs: list["FuncInfo"] = dataclasses.field(
        default_factory=list)
    attr_loads: set[str] = dataclasses.field(default_factory=set)
    is_property: bool = False
    is_resolve_once: bool = False
    # pinned eager by annotation: ``# graftlint: eager -- <reason>`` on (or
    # directly above) the def line — for functions that are lexically
    # reachable from traced code but eager-only by contract (e.g. the
    # between-batches auto-tuners, which no-op under trace)
    is_eager_pinned: bool = False
    is_module: bool = False
    traced: bool = False
    trace_reason: str | None = None
    trace_chain: tuple[str, ...] = ()

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)


@dataclasses.dataclass
class SourceFile:
    path: str  # display path (relative where possible)
    text: str
    tree: ast.Module
    module_info: FuncInfo = None  # set by analyze()
    funcs: list[FuncInfo] = dataclasses.field(default_factory=list)
    # def-line -> reason, from ``# graftlint: eager -- <reason>`` comments
    eager_lines: dict[int, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Project:
    files: list[SourceFile]
    funcs: list[FuncInfo] = dataclasses.field(default_factory=list)
    # simple name -> named functions/methods anywhere in the file set
    index: dict[str, list[FuncInfo]] = dataclasses.field(default_factory=dict)
    class_index: dict[str, list[FuncInfo]] = dataclasses.field(
        default_factory=dict)  # class name -> [__init__ FuncInfo]
    property_index: dict[str, list[FuncInfo]] = dataclasses.field(
        default_factory=dict)
    declared_axes: dict[str, str] = dataclasses.field(
        default_factory=dict)  # constant name -> axis string
    # metric-name constants (obs/registry.py discipline): ALL_CAPS module
    # constants whose value is a dotted lowercase metric name
    declared_metrics: dict[str, str] = dataclasses.field(
        default_factory=dict)
    node_func: dict[int, FuncInfo] = dataclasses.field(default_factory=dict)
    # id(func node) -> CFG, filled lazily by tools.lint.cfg.cfg_of
    cfg_cache: dict = dataclasses.field(default_factory=dict)

    def owner_of(self, node: ast.AST) -> FuncInfo | None:
        return self.node_func.get(id(node))


# -- per-file collection ------------------------------------------------------


def _decorator_names(dec: ast.AST) -> set[str]:
    """Terminal names reachable in a decorator expression, unwrapping
    ``partial(jax.jit, ...)``."""
    names = set()
    t = terminal_name(dec)
    if t:
        names.add(t)
    if isinstance(dec, ast.Call):
        ft = terminal_name(dec.func)
        if ft:
            names.add(ft)
        if ft == "partial" and dec.args:
            inner = terminal_name(dec.args[0])
            if inner:
                names.add(inner)
    return names


def _collect_params(node: ast.AST) -> tuple[list[str], list[str]]:
    """(all param names, taint params: positional-without-default minus
    self/cls)."""
    if isinstance(node, ast.Module):
        return [], []
    a = node.args
    allp = [p.arg for p in
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        allp.append(a.vararg.arg)
    if a.kwarg:
        allp.append(a.kwarg.arg)
    pos = list(a.posonlyargs) + list(a.args)
    n_default = len(a.defaults)
    no_default = pos[: len(pos) - n_default] if n_default else pos
    taint = [p.arg for p in no_default if p.arg not in ("self", "cls")]
    return allp, taint


def _detect_resolve_once(info: FuncInfo) -> bool:
    """The sanctioned memoization idiom: ``global X`` + ``if X is [not]
    None`` + an assignment to X. Such a function's slow path runs once per
    process — a barrier for the traced-reachability walk."""
    if isinstance(info.node, (ast.Lambda, ast.Module)):
        return False
    globals_declared: set[str] = set()
    guarded: set[str] = set()
    assigned: set[str] = set()
    for node in iter_owned(info.node):
        if isinstance(node, ast.Global):
            globals_declared.update(node.names)
        elif isinstance(node, ast.If) and isinstance(node.test, ast.Compare):
            cmp = node.test
            if (isinstance(cmp.left, ast.Name)
                    and len(cmp.ops) == 1
                    and isinstance(cmp.ops[0], (ast.Is, ast.IsNot))
                    and len(cmp.comparators) == 1
                    and isinstance(cmp.comparators[0], ast.Constant)
                    and cmp.comparators[0].value is None):
                guarded.add(cmp.left.id)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigned.add(t.id)
    return bool(globals_declared & guarded & assigned)


class _Collector(ast.NodeVisitor):
    """One pass over a file: build FuncInfos, local scopes, call edges."""

    def __init__(self, src: SourceFile, project: Project):
        self.src = src
        self.project = project
        module = FuncInfo(node=src.tree, path=src.path, name=None,
                          qualname="<module>", parent=None, is_module=True)
        src.module_info = module
        self.stack: list[FuncInfo] = [module]
        self.class_stack: list[str] = []
        self._register(module)

    # -- helpers --

    def _register(self, info: FuncInfo):
        self.src.funcs.append(info)
        self.project.funcs.append(info)

    def _own(self, node: ast.AST):
        self.project.node_func[id(node)] = self.stack[-1]

    def _bind_local(self, name: str):
        self.stack[-1].local_names.add(name)

    def _bind_func(self, name: str, info: FuncInfo):
        self.stack[-1].local_funcs.setdefault(name, []).append(info)

    # -- defs --

    def _enter_func(self, node, name: str | None):
        parent = self.stack[-1]
        qual = (parent.qualname + "." if not parent.is_module else "") + (
            name or "<lambda>")
        cls = self.class_stack[-1] if self.class_stack else None
        allp, taint = _collect_params(node)
        info = FuncInfo(node=node, path=self.src.path, name=name,
                        qualname=qual, parent=parent, class_name=cls,
                        params=allp, taint_params=taint)
        info.local_names.update(allp)
        self._register(info)
        return info

    def visit_FunctionDef(self, node):
        self._visit_funcdef(node)

    def visit_AsyncFunctionDef(self, node):
        self._visit_funcdef(node)

    def _visit_funcdef(self, node):
        info = self._enter_func(node, node.name)
        info.is_eager_pinned = node.lineno in self.src.eager_lines
        dec_names = set()
        for dec in node.decorator_list:
            dec_names |= _decorator_names(dec)
            # decorator expressions evaluate in the ENCLOSING scope
            self.visit(dec)
        info.is_property = "property" in dec_names or "cached_property" in dec_names
        if dec_names & _JIT_DECORATORS and not info.is_eager_pinned:
            info.traced = True
            info.trace_reason = (
                f"decorated with {sorted(dec_names & _JIT_DECORATORS)[0]}")
        # the def binds its name in the enclosing scope; methods bind in
        # the class namespace, which plain calls cannot see lexically
        directly_in_class = bool(self.class_stack) and self.stack[-1].is_module
        if not directly_in_class:
            self._bind_func(node.name, info)
            self._bind_local(node.name)
        # index every named function by simple name (conservative linking)
        self.project.index.setdefault(node.name, []).append(info)
        if info.is_property:
            self.project.property_index.setdefault(
                node.name, []).append(info)
        if directly_in_class and node.name == "__init__":
            self.project.class_index.setdefault(
                self.class_stack[-1], []).append(info)
        self.stack.append(info)
        for stmt in node.body:
            self.visit(stmt)
        self.stack.pop()
        info.is_resolve_once = _detect_resolve_once(info)

    def visit_Lambda(self, node):
        info = self._enter_func(node, None)
        self.stack.append(info)
        self.visit(node.body)
        self.stack.pop()

    def visit_ClassDef(self, node):
        self._own(node)
        self._bind_local(node.name)
        base_names = {terminal_name(b) for b in node.bases}
        is_flax = "Module" in base_names
        self.class_stack.append(node.name)
        # remember which FuncInfos the class body defines so flax methods
        # can be marked as entries after visiting
        before = len(self.src.funcs)
        for stmt in node.body:
            self.visit(stmt)
        new_funcs = self.src.funcs[before:]
        self.class_stack.pop()
        if is_flax:
            for f in new_funcs:
                if (f.class_name == node.name and f.name
                        and not f.traced and not f.is_eager_pinned):
                    f.traced = True
                    f.trace_reason = (
                        f"method of flax Module '{node.name}' "
                        "(flax traces module methods)")

    # -- scope bindings --

    def visit_Global(self, node):
        self._own(node)

    def visit_Import(self, node):
        self._own(node)
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.stack[-1].imported_names.add(name)

    def visit_ImportFrom(self, node):
        self._own(node)
        for alias in node.names:
            self.stack[-1].imported_names.add(alias.asname or alias.name)

    def _bind_target(self, target):
        if isinstance(target, ast.Name):
            self._bind_local(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value)

    def visit_Assign(self, node):
        self._own(node)
        # a name bound to a lambda behaves like a local def
        if isinstance(node.value, ast.Lambda):
            before = len(self.src.funcs)
            self.visit(node.value)
            lam = self.src.funcs[before]  # the outermost lambda just visited
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._bind_func(t.id, lam)
                    lam.qualname = (self.stack[-1].qualname + "." + t.id
                                    + ".<lambda>")
        else:
            self.visit(node.value)
        for t in node.targets:
            self._bind_target(t)
            self.visit(t)
        # module-level axis-name constants: NAME_AXIS = "literal"; and
        # metric-name constants: ALL_CAPS = "dotted.lowercase"
        if (self.stack[-1].is_module
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id.endswith("_AXIS"):
                    self.project.declared_axes[t.id] = node.value.value
                elif (t.id.isupper()
                      and _METRIC_NAME_RE.match(node.value.value)):
                    self.project.declared_metrics[t.id] = node.value.value

    def visit_AnnAssign(self, node):
        self._own(node)
        if node.value is not None:
            self.visit(node.value)
        self._bind_target(node.target)

    def visit_AugAssign(self, node):
        self._own(node)
        self.visit(node.value)
        self._bind_target(node.target)

    def visit_For(self, node):
        self._own(node)
        self._bind_target(node.target)
        self.generic_visit(node)

    def visit_AsyncFor(self, node):
        self.visit_For(node)

    def visit_With(self, node):
        self._own(node)
        for item in node.items:
            if item.optional_vars is not None:
                self._bind_target(item.optional_vars)
        self.generic_visit(node)

    def visit_AsyncWith(self, node):
        self.visit_With(node)

    def visit_ExceptHandler(self, node):
        self._own(node)
        if node.name:
            self._bind_local(node.name)
        self.generic_visit(node)

    def visit_NamedExpr(self, node):
        self._own(node)
        self._bind_target(node.target)
        self.visit(node.value)

    def visit_comprehension(self, node):
        self._bind_target(node.target)
        self.generic_visit(node)

    # -- uses --

    def visit_Attribute(self, node):
        self._own(node)
        if isinstance(node.ctx, ast.Load):
            self.stack[-1].attr_loads.add(node.attr)
        self.generic_visit(node)

    def visit_Return(self, node):
        self._own(node)
        if node.value is not None:
            self._note_passed(node.value)
        self.generic_visit(node)

    def _note_passed(self, expr):
        """A local function referenced as a value (argument / return) from
        traced code will almost certainly be invoked during the trace.
        Module-level functions passed by name are excluded: those are
        usually host callbacks (``jax.debug.callback`` targets)."""
        names = []
        if isinstance(expr, ast.Name):
            names = [expr.id]
        elif isinstance(expr, (ast.Tuple, ast.List)):
            names = [e.id for e in expr.elts if isinstance(e, ast.Name)]
        here = self.stack[-1]
        for n in names:
            scope = here
            while scope is not None and not scope.is_module:
                if n in scope.local_funcs:
                    here.passed_local_funcs.extend(scope.local_funcs[n])
                    break
                if n in scope.local_names or n in scope.imported_names:
                    break
                scope = scope.parent

    def visit_Call(self, node):
        self._own(node)
        here = self.stack[-1]
        t = terminal_name(node.func)
        if t is not None:
            kind = "name" if isinstance(node.func, ast.Name) else "attr"
            here.calls.append((kind, t, node))
        if t not in _HOST_CALLBACK_WRAPPERS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                self._note_passed(arg)
        self.generic_visit(node)

    def generic_visit(self, node):
        self._own(node)
        super().generic_visit(node)


# -- entry marking + propagation ---------------------------------------------


def _func_candidates(expr: ast.AST, scope: FuncInfo,
                     project: Project) -> list[FuncInfo]:
    """Resolve an expression in trace-wrapper argument position to the
    functions it may denote."""
    if isinstance(expr, ast.Lambda):
        owner = project.owner_of(expr.body)
        return [owner] if owner is not None else []
    if isinstance(expr, ast.Call):  # partial(f, ...) and friends
        if terminal_name(expr.func) == "partial" and expr.args:
            return _func_candidates(expr.args[0], scope, project)
        return []
    if isinstance(expr, ast.Name):
        s = scope
        while s is not None:
            if expr.id in s.local_funcs:
                return list(s.local_funcs[expr.id])
            if expr.id in s.local_names:
                return []  # shadowed by a plain local — unresolvable
            if expr.id in s.imported_names:
                return list(project.index.get(expr.id, []))
            s = s.parent
        return list(project.index.get(expr.id, []))
    if isinstance(expr, ast.Attribute):
        return list(project.index.get(expr.attr, []))
    if isinstance(expr, (ast.Tuple, ast.List)):  # lax.switch branch lists
        out = []
        for e in expr.elts:
            out.extend(_func_candidates(e, scope, project))
        return out
    return []


def _mark(info: FuncInfo, reason: str, chain: tuple[str, ...],
          work: list[FuncInfo]):
    if (info.traced or info.is_resolve_once or info.is_eager_pinned
            or info.is_module):
        return
    info.traced = True
    info.trace_reason = reason
    info.trace_chain = chain
    work.append(info)


def analyze(files: list[SourceFile]) -> Project:
    project = Project(files=files)
    for src in files:
        _Collector(src, project).visit(src.tree)

    # pass 2: trace-wrapper call sites anywhere in any file
    work: list[FuncInfo] = []
    for src in files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            t = terminal_name(node.func)
            positions = TRACE_WRAPPERS.get(t)
            scope = project.owner_of(node) or src.module_info
            cands: list[tuple[FuncInfo, str]] = []
            if positions is not None:
                for pos in positions:
                    if pos < len(node.args):
                        for c in _func_candidates(node.args[pos], scope,
                                                  project):
                            cands.append(
                                (c, f"passed to {t} at "
                                    f"{src.path}:{node.lineno}"))
            elif t == "switch" and len(node.args) >= 2:
                for c in _func_candidates(node.args[1], scope, project):
                    cands.append((c, f"passed to switch at "
                                     f"{src.path}:{node.lineno}"))
            for info, reason in cands:
                _mark(info, reason, (), work)

    # decorator / flax entries found during collection seed the worklist too
    for f in project.funcs:
        if f.traced:
            work.append(f)

    # pass 3: propagate over the call graph
    seen_edges: set[tuple[int, int]] = set()
    while work:
        f = work.pop()
        chain = f.trace_chain + (f.qualname,)
        short_chain = chain[-4:]
        via = f"called from {f.qualname} ({f.path}:{f.line})"
        for kind, name, node in f.calls:
            targets: list[FuncInfo] = []
            if kind == "name":
                s = f
                resolved = None
                while s is not None:
                    if name in s.local_funcs:
                        resolved = list(s.local_funcs[name])
                        break
                    if name in s.local_names and not s.is_module:
                        resolved = []  # a plain local variable — opaque
                        break
                    if name in s.imported_names:
                        resolved = list(project.index.get(name, []))
                        break
                    s = s.parent
                targets = (resolved if resolved is not None
                           else list(project.index.get(name, [])))
                # instantiation of a known class runs its __init__ at trace
                targets += project.class_index.get(name, [])
            else:  # attribute call: conservative terminal-name linking,
                # except names that are overwhelmingly builtin methods
                if name in _BUILTIN_METHOD_NAMES:
                    targets = []
                else:
                    targets = list(project.index.get(name, []))
            for g in targets:
                edge = (id(f), id(g))
                if edge in seen_edges:
                    continue
                seen_edges.add(edge)
                _mark(g, via, short_chain, work)
        for g in f.passed_local_funcs:
            edge = (id(f), id(g))
            if edge not in seen_edges:
                seen_edges.add(edge)
                _mark(g, f"closure passed from {f.qualname}", short_chain,
                      work)
        for attr in f.attr_loads:
            for g in project.property_index.get(attr, []):
                edge = (id(f), id(g))
                if edge not in seen_edges:
                    seen_edges.add(edge)
                    _mark(g, f"property read from {f.qualname}", short_chain,
                          work)
    return project
