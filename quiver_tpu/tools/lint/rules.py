"""graftlint rules — each distilled from a bug this repo actually shipped.

Every rule is a function ``(project) -> list[Finding]`` registered in
``RULES``. Rule names are the stable identifiers used by inline
suppressions (``# graftlint: disable=<rule> -- <reason>``) and ``--select``
/ ``--ignore``.
"""

from __future__ import annotations

import ast
import copy
import dataclasses
import os
import re

from .analysis import (
    Project,
    FuncInfo,
    STATIC_ATTRS,
    _METRIC_NAME_RE,
    is_env_read,
    iter_owned,
    terminal_name,
)
from .cfg import cfg_of, propagate_guard_establishers

__all__ = ["Finding", "FAMILIES", "RULES", "family_of", "rule_docs"]


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "suppressed": self.suppressed}


def _finding(rule, info_or_path, node, message) -> Finding:
    path = (info_or_path if isinstance(info_or_path, str)
            else info_or_path.path)
    return Finding(rule=rule, path=path,
                   line=getattr(node, "lineno", 1),
                   col=getattr(node, "col_offset", 0), message=message)


def _chain(info: FuncInfo) -> str:
    hops = " -> ".join(info.trace_chain + (info.qualname,))
    return f"{hops} [{info.trace_reason}]"


# -- rule 1: env-at-trace -----------------------------------------------------

def check_env_at_trace(project: Project) -> list[Finding]:
    """``os.environ`` reads reachable from jit/shard_map/lax-control-flow
    bodies. The env var silently freezes at first trace while looking like
    a live switch (the QUIVER_COUNTS bug, fixed by hand in PR 3). Route the
    read through a module-cached resolve-once helper instead
    (``models/layers.resolve_counts_strategy`` over
    ``core/config.resolve_platform_strategy``) and document the
    env-before-first-use contract."""
    out = []
    for f in project.funcs:
        if not f.traced or f.is_module:
            continue
        for node in iter_owned(f.node):
            how = is_env_read(node)
            if how:
                out.append(_finding(
                    "env-at-trace", f, node,
                    f"{how} read inside traced code ({_chain(f)}); the "
                    "value freezes at first trace while looking live — "
                    "resolve it ONCE per process via a module-cached "
                    "helper (cf. models/layers.resolve_counts_strategy) "
                    "and document env-before-first-use",
                ))
    return out


# -- rule 2: axis-name-consistency -------------------------------------------

# collective -> index of the positional axis argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "all_to_all": 1, "psum_scatter": 1, "ppermute": 1, "pshuffle": 1,
    "axis_index": 0, "axis_size": 0,
}
_SPEC_CALLS = {"PartitionSpec", "P"}


def _axis_literals(arg: ast.AST):
    """String constants in an axis-argument expression (handles tuples)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        yield arg
    elif isinstance(arg, (ast.Tuple, ast.List)):
        for e in arg.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                yield e


def check_axis_name_consistency(project: Project) -> list[Finding]:
    """Axis names in collective calls / PartitionSpecs / ``mesh.shape[...]``
    must come from the shared ``*_AXIS`` constants (``parallel/mesh.py``
    declares ``DATA_AXIS``/``FEATURE_AXIS``); a string literal in axis
    position is drift waiting to happen, and a literal matching NO declared
    axis is drift that already happened."""
    declared = project.declared_axes
    by_value = {v: k for k, v in declared.items()}
    if not declared:
        return []  # nothing declared in the analyzed set — nothing to check

    def msg_for(lit: str) -> str:
        if lit in by_value:
            return (f"hardcoded axis name {lit!r}; use the shared constant "
                    f"{by_value[lit]} (quiver_tpu.parallel.mesh) so axis "
                    "renames cannot drift")
        known = ", ".join(sorted(f"{v!r} ({k})" for k, v in declared.items()))
        return (f"axis name {lit!r} matches no declared mesh axis "
                f"(declared: {known}) — string drift in a collective is a "
                "silent wrong-group reduction")

    out = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                t = terminal_name(node.func)
                axis_args = []
                if t in _COLLECTIVES:
                    pos = _COLLECTIVES[t]
                    if pos < len(node.args):
                        axis_args.append(node.args[pos])
                    for kw in node.keywords:
                        if kw.arg in ("axis_name", "axis"):
                            axis_args.append(kw.value)
                elif t in _SPEC_CALLS:
                    axis_args.extend(node.args)
                for arg in axis_args:
                    for lit in _axis_literals(arg):
                        out.append(_finding("axis-name-consistency", src.path,
                                            lit, msg_for(lit.value)))
            elif isinstance(node, ast.Subscript):
                # mesh.shape["data"] — flag only literals that ARE declared
                # axes (unknown strings here are ordinary dict keys)
                if (isinstance(node.value, ast.Attribute)
                        and node.value.attr == "shape"
                        and isinstance(node.slice, ast.Constant)
                        and isinstance(node.slice.value, str)
                        and node.slice.value in by_value):
                    out.append(_finding("axis-name-consistency", src.path,
                                        node.slice,
                                        msg_for(node.slice.value)))
    return out


# -- rule 3: cond-branch-parity ----------------------------------------------

def _return_arities(expr: ast.AST, scope: FuncInfo | None,
                    project: Project) -> set:
    """Possible return shapes of a cond branch: int = tuple arity,
    "scalar" = a single non-tuple value. Empty set = not statically
    analyzable (stay silent)."""
    def expr_arity(e):
        if isinstance(e, ast.Tuple):
            return len(e.elts)
        if e is None:
            return 0
        return "scalar"

    if isinstance(expr, ast.Lambda):
        return {expr_arity(expr.body)}
    target = None
    if isinstance(expr, ast.Name) and scope is not None:
        s = scope
        while s is not None:
            if expr.id in s.local_funcs:
                cands = s.local_funcs[expr.id]
                target = cands[0] if len(cands) == 1 else None
                break
            if expr.id in s.local_names and not s.is_module:
                break
            s = s.parent
        if target is None:
            cands = project.index.get(expr.id, [])
            target = cands[0] if len(cands) == 1 else None
    if target is None or isinstance(target.node, ast.Lambda):
        return set()
    arities = set()
    for node in iter_owned(target.node):
        if isinstance(node, ast.Return):
            arities.add(expr_arity(node.value))
    return arities


def check_cond_branch_parity(project: Project) -> list[Finding]:
    """``lax.cond`` branches returning mismatched tuple arity — the
    psum-fallback pattern (``parallel/routing.py``, ``feature/shard.py``)
    duplicates a two-branch cond; editing one branch's return without the
    other fails only at trace time, deep inside a shard_map stack."""
    out = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) != "cond" or len(node.args) < 3:
                continue
            scope = project.owner_of(node)
            a_true = _return_arities(node.args[1], scope, project)
            a_false = _return_arities(node.args[2], scope, project)
            if a_true and a_false and not (a_true & a_false):
                def show(s):
                    return "/".join(str(x) for x in sorted(s, key=str))
                out.append(_finding(
                    "cond-branch-parity", src.path, node,
                    f"lax.cond branches return mismatched structures "
                    f"(true branch: {show(a_true)} value(s), false branch: "
                    f"{show(a_false)}); both branches must return the same "
                    "pytree structure or the cond fails at trace time",
                ))
    return out


# -- rule 4: host-op-on-tracer -----------------------------------------------

class _TaintWalk(ast.NodeVisitor):
    """Minimal forward taint pass over one traced function's owned nodes."""

    def __init__(self, func: FuncInfo):
        self.func = func
        self.tainted: set[str] = set(func.taint_params)
        self.findings: list[Finding] = []

    def _tainted(self, expr) -> bool:
        if expr is None:
            return False
        # static metadata never carries a tracer
        clean = _strip_static(expr)
        for node in ast.walk(clean) if clean is not None else ():
            if isinstance(node, ast.Name) and node.id in self.tainted:
                return True
        return False

    def run(self):
        # iter_owned yields in traversal (not source) order, and loops can
        # carry taint backwards — iterate the assignment scan to a
        # fixpoint before checking call sites
        nodes = sorted(
            iter_owned(self.func.node),
            key=lambda n: (getattr(n, "lineno", 0),
                           getattr(n, "col_offset", 0)),
        )
        assigns = [n for n in nodes if isinstance(n, ast.Assign)]
        changed = True
        while changed:
            changed = False
            for node in assigns:
                if self._tainted(node.value):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if (isinstance(n, ast.Name)
                                    and n.id not in self.tainted):
                                self.tainted.add(n.id)
                                changed = True
        for node in nodes:
            if isinstance(node, ast.Call):
                self._check_call(node)
        return self.findings

    def _check_call(self, node: ast.Call):
        t = terminal_name(node.func)
        f = self.func
        if t in ("int", "float", "bool", "complex") and node.args:
            if self._tainted(node.args[0]):
                self.findings.append(_finding(
                    "host-op-on-tracer", f, node,
                    f"{t}() on a value flowing from traced parameter(s) "
                    f"of {f.qualname} ({_chain(f)}); forcing a Python "
                    "scalar inside traced code blocks on device sync or "
                    "raises TracerConversionError — keep it a jnp value "
                    "or move the readback outside the traced body",
                ))
        elif t == "item" and isinstance(node.func, ast.Attribute):
            if self._tainted(node.func.value):
                self.findings.append(_finding(
                    "host-op-on-tracer", f, node,
                    f".item() on a value flowing from traced parameter(s) "
                    f"of {f.qualname} ({_chain(f)}); device->host readback "
                    "inside traced code — return the value instead",
                ))
        elif t == "range" and node.args:
            a0 = node.args[0]
            if (isinstance(a0, ast.Call) and terminal_name(a0.func) == "len"
                    and a0.args and self._tainted(a0.args[0])):
                self.findings.append(_finding(
                    "host-op-on-tracer", f, node,
                    f"range(len(...)) over a traced parameter of "
                    f"{f.qualname} ({_chain(f)}): the Python loop unrolls "
                    "one program copy per element at trace time — use "
                    "lax.scan / lax.fori_loop",
                ))


def _strip_static(expr: ast.AST):
    """Return the expr for taint walking, or None when the whole expr is a
    static-metadata access. Names under ``.shape``-like attributes and
    inside ``len(...)`` do not carry tracers at runtime."""

    class _T(ast.NodeTransformer):
        def visit_Attribute(self, node):
            if node.attr in STATIC_ATTRS:
                return ast.copy_location(ast.Constant(value=None), node)
            return self.generic_visit(node)

        def visit_Call(self, node):
            if terminal_name(node.func) == "len":
                return ast.copy_location(ast.Constant(value=None), node)
            return self.generic_visit(node)

    return _T().visit(copy.deepcopy(expr))


def check_host_op_on_tracer(project: Project) -> list[Finding]:
    """``int()``/``float()``/``.item()``/``range(len())`` on values that
    flow from the parameters of a traced function: a host scalar readback
    (or a trace-time unroll) hiding inside device code. Static metadata
    (``x.shape[0]``, ``x.ndim``, ``len(x)`` alone) is exempt."""
    out = []
    for f in project.funcs:
        if not f.traced or f.is_module or not f.taint_params:
            continue
        out.extend(_TaintWalk(f).run())
    return out


# -- rule 5: per-call-logging-in-jit -----------------------------------------

_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}


def _is_logger_receiver(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        return terminal_name(expr.func) in ("get_logger", "getLogger",
                                            "getChild")
    t = terminal_name(expr)
    if t is None:
        return False
    tl = t.lower()
    return tl in ("warnings",) or "log" in tl


def check_per_call_logging_in_jit(project: Project) -> list[Finding]:
    """Logging calls inside traced bodies run once per TRACE, not once per
    step — they look like per-batch telemetry and silently aren't, and
    each retrace re-emits them. Use the one-shot ``info_once`` idiom for
    trace-time signals, or ``jax.debug.print``/``jax.debug.callback`` for
    genuine in-program output."""
    out = []
    for f in project.funcs:
        if not f.traced or f.is_module:
            continue
        if f.name and f.name.endswith("once"):
            continue  # the one-shot idiom's own implementation
        for node in iter_owned(f.node):
            if not isinstance(node, ast.Call):
                continue
            t = terminal_name(node.func)
            if isinstance(node.func, ast.Name) and t == "print":
                out.append(_finding(
                    "per-call-logging-in-jit", f, node,
                    f"print() inside traced code ({_chain(f)}) runs at "
                    "trace time, not per step; use jax.debug.print for "
                    "in-program output or info_once for one-shot signals",
                ))
            elif (isinstance(node.func, ast.Attribute)
                  and t in _LOG_METHODS
                  and _is_logger_receiver(node.func.value)):
                out.append(_finding(
                    "per-call-logging-in-jit", f, node,
                    f"logger .{t}() inside traced code ({_chain(f)}) fires "
                    "once per trace and again on every retrace — use the "
                    "one-shot info_once idiom (utils/trace.py) or "
                    "jax.debug.callback",
                ))
    return out


# -- rule 6: export-doc-drift -------------------------------------------------

def _module_all(tree: ast.Module) -> list[tuple[str, ast.AST]]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        return [
                            (e.value, e) for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        ]
    return []


def check_export_doc_drift(project: Project) -> list[Finding]:
    """Names in a top-level package ``__init__.py.__all__`` missing from
    ``docs/API.md`` — the generated index (``scripts/gen_api_md.py``) went
    stale. Applies to any analyzed ``__init__.py`` whose grandparent
    directory carries ``docs/API.md`` (i.e. the package root)."""
    out = []
    for src in project.files:
        if os.path.basename(src.path) != "__init__.py":
            continue
        pkg_dir = os.path.dirname(os.path.abspath(src.path))
        api_md = os.path.join(os.path.dirname(pkg_dir), "docs", "API.md")
        if not os.path.isfile(api_md):
            continue
        exports = _module_all(src.tree)
        if not exports:
            continue
        try:
            with open(api_md, encoding="utf-8") as fh:
                documented = set(re.findall(r"`([^`\n]+)`", fh.read()))
        except OSError:
            continue
        rel_md = os.path.relpath(api_md)
        for name, node in exports:
            if name not in documented:
                out.append(_finding(
                    "export-doc-drift", src.path, node,
                    f"__all__ export {name!r} is missing from {rel_md}; "
                    "regenerate it (JAX_PLATFORMS=cpu python "
                    "scripts/gen_api_md.py)",
                ))
    return out


# ===== graftlint v2 — dataflow rule families ================================
#
# The rules below run on the CFG/dominator engine (tools/lint/cfg.py):
# they do not ask "is there a guard somewhere" but "does the guard
# DOMINATE the operation" — every path from entry must pass through it.
# Each family is distilled from a discipline a shipped PR established by
# hand: staleness from PR 8's version-guarded reads, transaction from
# PR 7's atomic checkpoint store (and CSRTopo.save), concurrency from the
# executor/lock/metric-constant lifecycles of PRs 2-8.


def _direct_methods(project: Project) -> dict[tuple[str, str],
                                              list[FuncInfo]]:
    """(path, class name) -> methods defined directly in the class body
    (nested closures inside a method carry class_name too but have a
    non-module parent)."""
    out: dict[tuple[str, str], list[FuncInfo]] = {}
    for f in project.funcs:
        if (f.class_name and f.name and not f.is_module
                and f.parent is not None and f.parent.is_module):
            out.setdefault((f.path, f.class_name), []).append(f)
    return out


def _self_attr_assigns(m: FuncInfo) -> set[str]:
    """Names of ``self.<attr>`` targets assigned anywhere in a method."""
    out: set[str] = set()
    for node in iter_owned(m.node):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        else:
            continue
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for tt in elts:
                if (isinstance(tt, ast.Attribute)
                        and isinstance(tt.value, ast.Name)
                        and tt.value.id == "self"):
                    out.add(tt.attr)
    return out


def _self_method_calls(m: FuncInfo) -> set[str]:
    """Names called as ``self.<name>(...)`` in a method."""
    out: set[str] = set()
    for node in iter_owned(m.node):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            out.add(node.func.attr)
    return out


# -- rule 7: stale-version-read (family: staleness) ---------------------------

def check_stale_version_read(project: Project) -> list[Finding]:
    """Public methods of a version-guarded class reading version-bound
    state without a *dominating* version check. PR 8 made every mutable
    placement (sampler device topology, trainer captured operands) carry
    the version it was built from and raise ``VersionMismatchError``
    instead of silently serving pre-commit data — but only on the entry
    points that remember to call the guard. This rule machine-checks the
    discipline: in any class that owns a version guard (a method raising
    ``VersionMismatchError``, directly or via a callee) and a rebind seam
    (a method re-assigning a ``*version*`` attribute — ``refresh()``,
    ``replan()``), every public method reading the state those seams
    re-capture must be dominated by a guard or rebind call (guard facts
    propagate interprocedurally: a callee that guards on every exit
    counts). A guard in one branch, or after the read, does not."""
    seeds: set[str] = set()
    for f in project.funcs:
        if f.is_module or not f.name:
            continue
        for node in iter_owned(f.node):
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                t = (terminal_name(exc.func) if isinstance(exc, ast.Call)
                     else terminal_name(exc))
                if t == "VersionMismatchError":
                    seeds.add(f.name)
    if not seeds:
        return []
    guard_names = propagate_guard_establishers(project, seeds)
    out = []
    for (_path, cls), methods in sorted(_direct_methods(project).items()):
        if not any(m.name in guard_names for m in methods):
            continue
        rebind: set[str] = set()
        rebind_sets: list[set[str]] = []
        for m in methods:
            if m.name == "__init__":
                continue
            attrs = _self_attr_assigns(m)
            if any("version" in a.lower() for a in attrs):
                rebind.add(m.name)
                rebind_sets.append({a for a in attrs
                                    if "version" not in a.lower()})
        # the version-bound state is what EVERY rebind seam re-captures:
        # refresh() and _replan() both rebuild the captured operands and
        # programs, but only _replan touches elastic-mesh state like
        # self.mesh — the intersection separates the two
        stale_attrs = (set.intersection(*rebind_sets)
                       if rebind_sets else set())
        if not stale_attrs:
            continue
        ok_calls = guard_names | rebind
        guards_shown = sorted(
            m.name for m in methods if m.name in seeds) or sorted(
            m.name for m in methods if m.name in guard_names)
        for m in methods:
            if (m.name.startswith("_") or m.name in rebind
                    or m.name in seeds):
                continue
            cfg = None
            for node in iter_owned(m.node):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr in stale_attrs
                        and isinstance(node.ctx, ast.Load)):
                    continue
                if cfg is None:
                    cfg = cfg_of(project, m)
                if cfg.calls_dominating(node) & ok_calls:
                    continue
                out.append(_finding(
                    "stale-version-read", m, node,
                    f"{cls}.{m.name} reads version-bound state "
                    f"self.{node.attr} (re-captured by "
                    f"{'/'.join(sorted(rebind))}) without a dominating "
                    f"version check; after a streaming commit this read "
                    f"silently serves the pre-commit placement — call "
                    f"{'/'.join(guards_shown)} on every path before the "
                    "read (cf. GraphSageSampler.sample, "
                    "DistributedTrainer.step)",
                ))
    return out


# -- rules 8-10: transaction family ------------------------------------------

_TXN_PATH_RE = re.compile(r"checkpoint|topology|streaming|integrity")
_TMPISH = ("tmp", "temp")
_NP_RECEIVERS = {"np", "numpy", "jnp"}
_NP_WRITERS = {"save", "savez", "savez_compressed"}


def _is_os_replace(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "replace"
            and terminal_name(node.func.value) == "os")


def _module_calls_replace(src) -> bool:
    return any(isinstance(n, ast.Call) and _is_os_replace(n)
               for n in ast.walk(src.tree))


def _txn_scoped(src) -> bool:
    """Transactional modules: save-path modules by name, plus any module
    that performs an atomic ``os.replace`` publish itself (doing it
    somewhere obliges every write in the module to be honest about it)."""
    path = src.path.replace(os.sep, "/")
    return bool(_TXN_PATH_RE.search(path)) or _module_calls_replace(src)


def _func_env(f: FuncInfo) -> dict[str, ast.AST]:
    """name -> RHS expression for simple local bindings (assignments and
    ``with open(...) as fh`` items)."""
    env: dict[str, ast.AST] = {}
    for node in iter_owned(f.node):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            env.setdefault(node.targets[0].id, node.value)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.optional_vars, ast.Name):
                    env.setdefault(item.optional_vars.id,
                                   item.context_expr)
    return env


def _tempish(s: str) -> bool:
    low = s.lower()
    return any(t in low for t in _TMPISH)


def _temp_derived(expr, env, params, _seen=None):
    """Classify a write-target path expression: True (derives from a
    temp-dir/temp-name source), ``"param:<name>"`` (a bare parameter —
    the enclosing function is a write *helper*; its call sites carry the
    obligation), or False (a published/unknown path)."""
    if expr is None:
        return False
    if _seen is None:
        _seen = set()
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, str) and _tempish(expr.value)
    if isinstance(expr, ast.Name):
        if _tempish(expr.id):
            return True
        if expr.id in _seen:
            return False
        _seen.add(expr.id)
        bound = env.get(expr.id)
        if bound is not None:
            r = _temp_derived(bound, env, params, _seen)
            if r:
                return r
        if expr.id in params:
            return f"param:{expr.id}"
        return False
    if isinstance(expr, ast.JoinedStr):
        return any(
            _temp_derived(
                v.value if isinstance(v, ast.FormattedValue) else v,
                env, params, _seen) is True
            for v in expr.values)
    if isinstance(expr, ast.BinOp):
        return (_temp_derived(expr.left, env, params, _seen) is True
                or _temp_derived(expr.right, env, params, _seen) is True)
    if isinstance(expr, ast.Call):
        t = terminal_name(expr.func)
        if t in ("mkdtemp", "mkstemp", "NamedTemporaryFile",
                 "TemporaryDirectory", "gettempdir", "mktemp"):
            return True
        if t in ("join", "joinpath", "fspath", "abspath", "str"):
            return any(_temp_derived(a, env, params, _seen) is True
                       for a in expr.args)
        if t == "open" and expr.args:
            return _temp_derived(expr.args[0], env, params, _seen)
        return False
    if isinstance(expr, ast.Attribute):
        # fh.name on a NamedTemporaryFile, tmp_path / ... — unknown
        return _temp_derived(expr.value, env, params, _seen) is True
    return False


def _open_write_target(call: ast.Call):
    """The path argument of an ``open(...)`` that writes (mode contains
    w/x); append-mode streams (JSONL ledgers) are a different idiom and
    exempt."""
    if terminal_name(call.func) != "open" or not call.args:
        return None
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and ("w" in mode or "x" in mode):
        return call.args[0]
    return None


def _write_helper_map(project: Project) -> dict[str, int]:
    """Functions whose open-for-write target is a bare parameter
    (``Checkpointer._write_file``): name -> parameter position. Their
    call sites are the write events to audit."""
    helpers: dict[str, int] = {}
    for f in project.funcs:
        if not f.name or f.is_module:
            continue
        env = _func_env(f)
        params = set(f.params)
        for node in iter_owned(f.node):
            if not isinstance(node, ast.Call):
                continue
            tgt = _open_write_target(node)
            if tgt is None:
                continue
            r = _temp_derived(tgt, env, params)
            if isinstance(r, str):
                pname = r.split(":", 1)[1]
                pos = [p for p in f.params if p not in ("self", "cls")]
                if pname in pos:
                    helpers[f.name] = pos.index(pname)
    return helpers


def _write_events(f: FuncInfo, env, helpers):
    """Yield (call node, target path expr) for every byte-writing call in
    one function: open-for-write, ``np.save*``, and calls to known write
    helpers."""
    for node in iter_owned(f.node):
        if not isinstance(node, ast.Call):
            continue
        tgt = _open_write_target(node)
        if tgt is not None:
            yield node, tgt
            continue
        t = terminal_name(node.func)
        if (t in _NP_WRITERS and isinstance(node.func, ast.Attribute)
                and terminal_name(node.func.value) in _NP_RECEIVERS
                and node.args):
            tgt = node.args[0]
            bound = env.get(tgt.id) if isinstance(tgt, ast.Name) else None
            if (isinstance(bound, ast.Call)
                    and terminal_name(bound.func) == "open"
                    and bound.args):
                tgt = bound.args[0]  # handle from `with open(p) as fh`
            yield node, tgt
            continue
        if (t in helpers and isinstance(node.func, (ast.Name,
                                                    ast.Attribute))):
            pos = helpers[t]
            if pos < len(node.args):
                yield node, node.args[pos]


def check_non_atomic_publish(project: Project) -> list[Finding]:
    """Bare writes to published paths in transactional modules. The
    checkpoint/topology save discipline (PR 7, ``utils/checkpoint.py``,
    ``CSRTopo.save``) is: write into a temp name, fsync, publish with ONE
    ``os.replace`` (COMMIT marker last) — a crash mid-save must leave an
    invisible temp, never a torn file a reader can load. In modules on
    that save path (path matches checkpoint/topology/streaming/integrity,
    or the module performs ``os.replace`` itself), ``open(final_path,
    "w")`` / ``np.savez(final_path)`` whose target does not derive from a
    temp source is a finding; write *helpers* taking the path as a
    parameter are audited at their call sites. Append-mode streams (JSONL
    ledgers) are exempt — appending is a different idiom."""
    helpers = _write_helper_map(project)
    out = []
    for src in project.files:
        if not _txn_scoped(src):
            continue
        for f in src.funcs:
            env = _func_env(f)
            params = set(f.params)
            for call, tgt in _write_events(f, env, helpers):
                r = _temp_derived(tgt, env, params)
                if r is True or isinstance(r, str):
                    continue  # temp-derived, or this IS a write helper
                out.append(_finding(
                    "non-atomic-publish", f, call,
                    "write to a published path in a transactional module "
                    "without the atomic-publish pattern; a crash mid-"
                    "write leaves a torn file the next reader trusts — "
                    "write into a temp name, fsync, then publish with "
                    "one os.replace (cf. utils/checkpoint.py, "
                    "CSRTopo.save)",
                ))
    return out


def check_commit_marker_order(project: Project) -> list[Finding]:
    """COMMIT markers written before the payload. The marker's entire
    meaning (``utils/checkpoint.py``, ``resilience/integrity.py``) is
    "every byte before me is durable" — ``_write_sync`` writes arrays,
    treedef and manifest first and the marker LAST. A function that
    writes a COMMIT-named file before other writes re-introduces the
    torn-checkpoint window the marker exists to close."""
    helpers = _write_helper_map(project)

    def mentions_commit(expr) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                if "COMMIT" in n.value:
                    return True
            elif isinstance(n, (ast.Name, ast.Attribute)):
                t = terminal_name(n)
                if t and "COMMIT" in t:
                    return True
        return False

    out = []
    for src in project.files:
        if not _txn_scoped(src):
            continue
        for f in src.funcs:
            env = _func_env(f)
            events = list(_write_events(f, env, helpers))
            if len(events) < 2:
                continue
            for call, tgt in events:
                if not mentions_commit(tgt):
                    continue
                later = [c for c, t2 in events
                         if c.lineno > call.lineno
                         and not mentions_commit(t2)]
                if later:
                    out.append(_finding(
                        "commit-marker-order", f, call,
                        f"COMMIT marker written before {len(later)} later "
                        "write(s); the marker asserts every byte before "
                        "it is durable, so it must be the LAST write "
                        "before the os.replace publish "
                        "(cf. Checkpointer._write_sync)",
                    ))
    return out


def check_replace_without_fsync(project: Project) -> list[Finding]:
    """``os.replace`` publishes without an fsync of the payload. The
    rename is atomic in the namespace, not in the page cache: publishing
    bytes that were never flushed can surface a zero-length or torn file
    at the FINAL name after a crash — exactly the torn state the temp-
    then-rename dance exists to prevent. Any function (tree-wide) that
    both writes bytes and calls ``os.replace`` must fsync, directly or
    via a callee (``_write_file`` fsyncs for ``_write_sync``). Pure
    renames (quarantine moves) write nothing and are exempt."""
    helpers = _write_helper_map(project)
    # functions that fsync DIRECTLY; one level of callee credit (the
    # ``_write_sync -> _write_file`` shape) — a transitive closure over
    # terminal names would let common names like ``save`` launder the
    # credit across the whole tree
    fsyncers: set[str] = set()
    call_names = {}
    for f in project.funcs:
        if not f.name or f.is_module:
            continue
        names = {name for _k, name, _n in f.calls}
        call_names[id(f)] = names
        if "fsync" in names:
            fsyncers.add(f.name)
    out = []
    for src in project.files:
        for f in src.funcs:
            replaces = [n for n in iter_owned(f.node)
                        if isinstance(n, ast.Call) and _is_os_replace(n)]
            if not replaces:
                continue
            env = _func_env(f)
            if not any(True for _ in _write_events(f, env, helpers)):
                continue  # pure rename (quarantine move), no payload
            names = call_names.get(id(f), set())
            if "fsync" in names or names & fsyncers:
                continue
            out.append(_finding(
                "replace-without-fsync", f, replaces[0],
                f"{f.qualname} writes bytes and publishes them with "
                "os.replace but never fsyncs; after a crash the FINAL "
                "name can hold a zero-length or torn file — fsync the "
                "payload (and ideally the directory) before the rename "
                "(cf. CSRTopo.save)",
            ))
    return out


# -- rules 11-13: concurrency/lifecycle family -------------------------------

_EXECUTOR_NAMES = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_CLOSER_NAMES = {"close", "shutdown", "stop", "join", "terminate",
                 "__exit__", "__del__"}


def check_executor_lifecycle(project: Project) -> list[Finding]:
    """Executors without a shutdown path. A ``ThreadPoolExecutor`` owned
    by an object (``Checkpointer._pool``) must have ``shutdown()``
    reachable from a lifecycle method (``close``/``__exit__``/...):
    otherwise worker threads outlive the object and an in-flight task can
    fire against torn-down state — the exact close-races-async-save bug
    PR 6 fixed. A function-local executor must be shut down in the same
    function (``with`` block, or an explicit ``shutdown()`` — the
    Prefetcher's ``finally: pool.shutdown(wait=False)``), unless
    ownership is transferred (returned / stored on self)."""
    out = []
    # class-owned executors
    for (_path, cls), methods in sorted(_direct_methods(project).items()):
        owned: dict[str, tuple[FuncInfo, ast.AST]] = {}
        shutdown_sites: dict[str, set[str]] = {}
        self_calls: dict[str, set[str]] = {}
        for m in methods:
            self_calls[m.name] = _self_method_calls(m)
            for node in iter_owned(m.node):
                if isinstance(node, ast.Assign):
                    v = node.value
                    if (isinstance(v, ast.Call)
                            and terminal_name(v.func) in _EXECUTOR_NAMES):
                        for t in node.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                owned.setdefault(t.attr, (m, v))
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr == "shutdown"):
                    recv = node.func.value
                    if (isinstance(recv, ast.Attribute)
                            and isinstance(recv.value, ast.Name)
                            and recv.value.id == "self"):
                        shutdown_sites.setdefault(recv.attr,
                                                  set()).add(m.name)
        if owned:
            method_names = {m.name for m in methods}
            reach = set(method_names & _CLOSER_NAMES)
            work = list(reach)
            while work:
                cur = work.pop()
                for nxt in self_calls.get(cur, ()):
                    if nxt in method_names and nxt not in reach:
                        reach.add(nxt)
                        work.append(nxt)
            for attr, (m, v) in sorted(owned.items()):
                if not (shutdown_sites.get(attr, set()) & reach):
                    out.append(_finding(
                        "executor-lifecycle", m, v,
                        f"{cls}.{attr} owns a "
                        f"{terminal_name(v.func)} with no shutdown() "
                        "reachable from a lifecycle method "
                        f"({sorted(_CLOSER_NAMES)[:3]}...); worker "
                        "threads outlive the object and queued tasks can "
                        "fire against torn-down state — add a close() "
                        f"that calls self.{attr}.shutdown() "
                        "(cf. Checkpointer.close)",
                    ))
    # function-local executors
    for f in project.funcs:
        if f.is_module:
            continue
        locals_exec: dict[str, ast.AST] = {}
        shut: set[str] = set()
        transferred: set[str] = set()
        with_used: set[str] = set()
        for node in iter_owned(f.node):
            if isinstance(node, ast.Assign):
                v = node.value
                if (isinstance(v, ast.Call)
                        and terminal_name(v.func) in _EXECUTOR_NAMES):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            locals_exec.setdefault(t.id, v)
                        elif isinstance(t, ast.Attribute):
                            pass  # self-attr case handled above
                elif isinstance(v, ast.Name):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute):
                            transferred.add(v.id)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Name):
                        with_used.add(ce.id)
            elif isinstance(node, ast.Return) and node.value is not None:
                # ownership transfer = returning the executor ITSELF (or
                # a tuple holding it), not any expression that mentions it
                v = node.value
                vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
                for n in vals:
                    if isinstance(n, ast.Name):
                        transferred.add(n.id)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "shutdown"
                  and isinstance(node.func.value, ast.Name)):
                shut.add(node.func.value.id)
        for name, v in sorted(locals_exec.items()):
            if name in shut or name in with_used or name in transferred:
                continue
            out.append(_finding(
                "executor-lifecycle", f, v,
                f"local {terminal_name(v.func)} {name!r} in "
                f"{f.qualname} is never shut down; its worker threads "
                "outlive the call — use a with block or "
                f"try/finally: {name}.shutdown() "
                "(cf. Prefetcher.run)",
            ))
    return out


def check_lock_held_across_call(project: Project) -> list[Finding]:
    """Holding a non-reentrant lock across a call that can re-acquire it.
    ``with self._lock:`` around a call to a method that itself takes
    ``self._lock`` deadlocks the owner thread (``threading.Lock`` is not
    reentrant) — the classic lifecycle bug of close() paths that lock and
    then call a locked helper. Acquisition propagates through same-class
    ``self.`` calls, so an indirect re-entry two calls deep is still
    caught. RLock-backed attributes are exempt (reentrancy is their
    point)."""
    out = []
    for (_path, cls), methods in sorted(_direct_methods(project).items()):
        locks: dict[str, str] = {}
        for m in methods:
            for node in iter_owned(m.node):
                if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call):
                    t = terminal_name(node.value.func)
                    if t in ("Lock", "RLock"):
                        for tt in node.targets:
                            if (isinstance(tt, ast.Attribute)
                                    and isinstance(tt.value, ast.Name)
                                    and tt.value.id == "self"):
                                locks[tt.attr] = t
        nonreentrant = {a for a, k in locks.items() if k == "Lock"}
        if not nonreentrant:
            continue

        def acquired_attrs(node) -> set[str]:
            got: set[str] = set()
            if isinstance(node, (ast.With, ast.AsyncWith)):
                exprs = [i.context_expr for i in node.items]
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "acquire"):
                exprs = [node.func.value]
            else:
                return got
            for e in exprs:
                if (isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self"
                        and e.attr in nonreentrant):
                    got.add(e.attr)
            return got

        direct: dict[str, set[str]] = {}
        self_calls: dict[str, set[str]] = {}
        for m in methods:
            self_calls[m.name] = _self_method_calls(m)
            got: set[str] = set()
            for node in iter_owned(m.node):
                got |= acquired_attrs(node)
            direct[m.name] = got
        may = {name: set(v) for name, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for name, callees in self_calls.items():
                for c in callees:
                    extra = may.get(c, set()) - may[name]
                    if extra:
                        may[name] |= extra
                        changed = True
        for m in methods:
            for node in iter_owned(m.node):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                held = acquired_attrs(node)
                if not held:
                    continue
                for stmt in node.body:
                    for n in ast.walk(stmt):
                        if (isinstance(n, ast.Call)
                                and isinstance(n.func, ast.Attribute)
                                and isinstance(n.func.value, ast.Name)
                                and n.func.value.id == "self"):
                            callee = n.func.attr
                            re_acq = may.get(callee, set()) & held
                            if re_acq:
                                attr = sorted(re_acq)[0]
                                out.append(_finding(
                                    "lock-held-across-call", m, n,
                                    f"{cls}.{m.name} calls "
                                    f"self.{callee}() while holding "
                                    f"self.{attr}, and {callee} "
                                    f"(re)acquires self.{attr} — "
                                    "threading.Lock is not reentrant, "
                                    "this deadlocks; release first, or "
                                    "split a _locked variant of the "
                                    "callee",
                                ))
    return out


# -- rule 13: metric-name-constant (family: concurrency) ---------------------

_TAPE_METHODS = frozenset({"add", "set"})
_REGISTRY_METHODS = frozenset({"counter", "gauge", "set", "add", "value",
                               "snapshot", "spec", "clear"})


def check_metric_name_constant(project: Project) -> list[Finding]:
    """Registry metric names must come from the ``obs/registry.py``
    constants, mirroring the axis-name rule: a string literal in a
    ``tape.add``/``registry.counter``/``metrics.set`` name position is
    producer/consumer spelling drift waiting to happen (the constants
    exist precisely because the three pre-graftscope telemetry streams
    drifted by hand), and a literal matching NO declared constant is
    drift that already happened."""
    declared = project.declared_metrics
    if not declared:
        return []
    by_value = {v: k for k, v in declared.items()}
    out = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if (not isinstance(node, ast.Call)
                    or not isinstance(node.func, ast.Attribute)
                    or not node.args):
                continue
            recv = terminal_name(node.func.value)
            if recv is None:
                continue
            recv_l = recv.lower().lstrip("_")
            meth = node.func.attr
            if recv_l.endswith("tape"):
                if meth not in _TAPE_METHODS:
                    continue
            elif recv_l.endswith(("metrics", "registry")):
                if meth not in _REGISTRY_METHODS:
                    continue
            else:
                continue
            a0 = node.args[0]
            if not (isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)):
                continue
            s = a0.value
            if s in by_value:
                out.append(_finding(
                    "metric-name-constant", src.path, a0,
                    f"hardcoded metric name {s!r}; use the shared "
                    f"constant {by_value[s]} (quiver_tpu.obs.registry) "
                    "so producer and consumer spelling cannot drift",
                ))
            elif _METRIC_NAME_RE.match(s):
                out.append(_finding(
                    "metric-name-constant", src.path, a0,
                    f"metric name {s!r} matches no declared registry "
                    f"constant (declared: {sorted(by_value)}) — declare "
                    "it in obs/registry.py first; an undeclared literal "
                    "is spelling drift a consumer cannot catch",
                ))
    return out


RULES = {
    "env-at-trace": check_env_at_trace,
    "axis-name-consistency": check_axis_name_consistency,
    "cond-branch-parity": check_cond_branch_parity,
    "host-op-on-tracer": check_host_op_on_tracer,
    "per-call-logging-in-jit": check_per_call_logging_in_jit,
    "export-doc-drift": check_export_doc_drift,
    "stale-version-read": check_stale_version_read,
    "non-atomic-publish": check_non_atomic_publish,
    "commit-marker-order": check_commit_marker_order,
    "replace-without-fsync": check_replace_without_fsync,
    "executor-lifecycle": check_executor_lifecycle,
    "lock-held-across-call": check_lock_held_across_call,
    "metric-name-constant": check_metric_name_constant,
}

# rule families: ``--select``/``--ignore`` accept family names and expand
# them to their member rules
FAMILIES = {
    "trace": ("env-at-trace", "cond-branch-parity", "host-op-on-tracer",
              "per-call-logging-in-jit"),
    "consistency": ("axis-name-consistency", "export-doc-drift"),
    "staleness": ("stale-version-read",),
    "transaction": ("non-atomic-publish", "commit-marker-order",
                    "replace-without-fsync"),
    "concurrency": ("executor-lifecycle", "lock-held-across-call",
                    "metric-name-constant"),
}


def family_of(rule: str) -> str:
    for fam, rules in FAMILIES.items():
        if rule in rules:
            return fam
    return "meta"

# names valid in suppressions but emitted by the runner itself
META_RULES = ("bad-suppression", "parse-error")


def rule_docs() -> dict[str, str]:
    return {name: (fn.__doc__ or "").strip() for name, fn in RULES.items()}
