"""graftlint rules — each distilled from a bug this repo actually shipped.

Every rule is a function ``(project) -> list[Finding]`` registered in
``RULES``. Rule names are the stable identifiers used by inline
suppressions (``# graftlint: disable=<rule> -- <reason>``) and ``--select``
/ ``--ignore``.
"""

from __future__ import annotations

import ast
import copy
import dataclasses
import os
import re

from .analysis import (
    Project,
    FuncInfo,
    STATIC_ATTRS,
    is_env_read,
    iter_owned,
    terminal_name,
)

__all__ = ["Finding", "RULES", "rule_docs"]


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "suppressed": self.suppressed}


def _finding(rule, info_or_path, node, message) -> Finding:
    path = (info_or_path if isinstance(info_or_path, str)
            else info_or_path.path)
    return Finding(rule=rule, path=path,
                   line=getattr(node, "lineno", 1),
                   col=getattr(node, "col_offset", 0), message=message)


def _chain(info: FuncInfo) -> str:
    hops = " -> ".join(info.trace_chain + (info.qualname,))
    return f"{hops} [{info.trace_reason}]"


# -- rule 1: env-at-trace -----------------------------------------------------

def check_env_at_trace(project: Project) -> list[Finding]:
    """``os.environ`` reads reachable from jit/shard_map/lax-control-flow
    bodies. The env var silently freezes at first trace while looking like
    a live switch (the QUIVER_COUNTS bug, fixed by hand in PR 3). Route the
    read through a module-cached resolve-once helper instead
    (``models/layers.resolve_counts_strategy`` over
    ``core/config.resolve_platform_strategy``) and document the
    env-before-first-use contract."""
    out = []
    for f in project.funcs:
        if not f.traced or f.is_module:
            continue
        for node in iter_owned(f.node):
            how = is_env_read(node)
            if how:
                out.append(_finding(
                    "env-at-trace", f, node,
                    f"{how} read inside traced code ({_chain(f)}); the "
                    "value freezes at first trace while looking live — "
                    "resolve it ONCE per process via a module-cached "
                    "helper (cf. models/layers.resolve_counts_strategy) "
                    "and document env-before-first-use",
                ))
    return out


# -- rule 2: axis-name-consistency -------------------------------------------

# collective -> index of the positional axis argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "all_to_all": 1, "psum_scatter": 1, "ppermute": 1, "pshuffle": 1,
    "axis_index": 0, "axis_size": 0,
}
_SPEC_CALLS = {"PartitionSpec", "P"}


def _axis_literals(arg: ast.AST):
    """String constants in an axis-argument expression (handles tuples)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        yield arg
    elif isinstance(arg, (ast.Tuple, ast.List)):
        for e in arg.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                yield e


def check_axis_name_consistency(project: Project) -> list[Finding]:
    """Axis names in collective calls / PartitionSpecs / ``mesh.shape[...]``
    must come from the shared ``*_AXIS`` constants (``parallel/mesh.py``
    declares ``DATA_AXIS``/``FEATURE_AXIS``); a string literal in axis
    position is drift waiting to happen, and a literal matching NO declared
    axis is drift that already happened."""
    declared = project.declared_axes
    by_value = {v: k for k, v in declared.items()}
    if not declared:
        return []  # nothing declared in the analyzed set — nothing to check

    def msg_for(lit: str) -> str:
        if lit in by_value:
            return (f"hardcoded axis name {lit!r}; use the shared constant "
                    f"{by_value[lit]} (quiver_tpu.parallel.mesh) so axis "
                    "renames cannot drift")
        known = ", ".join(sorted(f"{v!r} ({k})" for k, v in declared.items()))
        return (f"axis name {lit!r} matches no declared mesh axis "
                f"(declared: {known}) — string drift in a collective is a "
                "silent wrong-group reduction")

    out = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                t = terminal_name(node.func)
                axis_args = []
                if t in _COLLECTIVES:
                    pos = _COLLECTIVES[t]
                    if pos < len(node.args):
                        axis_args.append(node.args[pos])
                    for kw in node.keywords:
                        if kw.arg in ("axis_name", "axis"):
                            axis_args.append(kw.value)
                elif t in _SPEC_CALLS:
                    axis_args.extend(node.args)
                for arg in axis_args:
                    for lit in _axis_literals(arg):
                        out.append(_finding("axis-name-consistency", src.path,
                                            lit, msg_for(lit.value)))
            elif isinstance(node, ast.Subscript):
                # mesh.shape["data"] — flag only literals that ARE declared
                # axes (unknown strings here are ordinary dict keys)
                if (isinstance(node.value, ast.Attribute)
                        and node.value.attr == "shape"
                        and isinstance(node.slice, ast.Constant)
                        and isinstance(node.slice.value, str)
                        and node.slice.value in by_value):
                    out.append(_finding("axis-name-consistency", src.path,
                                        node.slice,
                                        msg_for(node.slice.value)))
    return out


# -- rule 3: cond-branch-parity ----------------------------------------------

def _return_arities(expr: ast.AST, scope: FuncInfo | None,
                    project: Project) -> set:
    """Possible return shapes of a cond branch: int = tuple arity,
    "scalar" = a single non-tuple value. Empty set = not statically
    analyzable (stay silent)."""
    def expr_arity(e):
        if isinstance(e, ast.Tuple):
            return len(e.elts)
        if e is None:
            return 0
        return "scalar"

    if isinstance(expr, ast.Lambda):
        return {expr_arity(expr.body)}
    target = None
    if isinstance(expr, ast.Name) and scope is not None:
        s = scope
        while s is not None:
            if expr.id in s.local_funcs:
                cands = s.local_funcs[expr.id]
                target = cands[0] if len(cands) == 1 else None
                break
            if expr.id in s.local_names and not s.is_module:
                break
            s = s.parent
        if target is None:
            cands = project.index.get(expr.id, [])
            target = cands[0] if len(cands) == 1 else None
    if target is None or isinstance(target.node, ast.Lambda):
        return set()
    arities = set()
    for node in iter_owned(target.node):
        if isinstance(node, ast.Return):
            arities.add(expr_arity(node.value))
    return arities


def check_cond_branch_parity(project: Project) -> list[Finding]:
    """``lax.cond`` branches returning mismatched tuple arity — the
    psum-fallback pattern (``parallel/routing.py``, ``feature/shard.py``)
    duplicates a two-branch cond; editing one branch's return without the
    other fails only at trace time, deep inside a shard_map stack."""
    out = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) != "cond" or len(node.args) < 3:
                continue
            scope = project.owner_of(node)
            a_true = _return_arities(node.args[1], scope, project)
            a_false = _return_arities(node.args[2], scope, project)
            if a_true and a_false and not (a_true & a_false):
                def show(s):
                    return "/".join(str(x) for x in sorted(s, key=str))
                out.append(_finding(
                    "cond-branch-parity", src.path, node,
                    f"lax.cond branches return mismatched structures "
                    f"(true branch: {show(a_true)} value(s), false branch: "
                    f"{show(a_false)}); both branches must return the same "
                    "pytree structure or the cond fails at trace time",
                ))
    return out


# -- rule 4: host-op-on-tracer -----------------------------------------------

class _TaintWalk(ast.NodeVisitor):
    """Minimal forward taint pass over one traced function's owned nodes."""

    def __init__(self, func: FuncInfo):
        self.func = func
        self.tainted: set[str] = set(func.taint_params)
        self.findings: list[Finding] = []

    def _tainted(self, expr) -> bool:
        if expr is None:
            return False
        # static metadata never carries a tracer
        clean = _strip_static(expr)
        for node in ast.walk(clean) if clean is not None else ():
            if isinstance(node, ast.Name) and node.id in self.tainted:
                return True
        return False

    def run(self):
        # iter_owned yields in traversal (not source) order, and loops can
        # carry taint backwards — iterate the assignment scan to a
        # fixpoint before checking call sites
        nodes = sorted(
            iter_owned(self.func.node),
            key=lambda n: (getattr(n, "lineno", 0),
                           getattr(n, "col_offset", 0)),
        )
        assigns = [n for n in nodes if isinstance(n, ast.Assign)]
        changed = True
        while changed:
            changed = False
            for node in assigns:
                if self._tainted(node.value):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if (isinstance(n, ast.Name)
                                    and n.id not in self.tainted):
                                self.tainted.add(n.id)
                                changed = True
        for node in nodes:
            if isinstance(node, ast.Call):
                self._check_call(node)
        return self.findings

    def _check_call(self, node: ast.Call):
        t = terminal_name(node.func)
        f = self.func
        if t in ("int", "float", "bool", "complex") and node.args:
            if self._tainted(node.args[0]):
                self.findings.append(_finding(
                    "host-op-on-tracer", f, node,
                    f"{t}() on a value flowing from traced parameter(s) "
                    f"of {f.qualname} ({_chain(f)}); forcing a Python "
                    "scalar inside traced code blocks on device sync or "
                    "raises TracerConversionError — keep it a jnp value "
                    "or move the readback outside the traced body",
                ))
        elif t == "item" and isinstance(node.func, ast.Attribute):
            if self._tainted(node.func.value):
                self.findings.append(_finding(
                    "host-op-on-tracer", f, node,
                    f".item() on a value flowing from traced parameter(s) "
                    f"of {f.qualname} ({_chain(f)}); device->host readback "
                    "inside traced code — return the value instead",
                ))
        elif t == "range" and node.args:
            a0 = node.args[0]
            if (isinstance(a0, ast.Call) and terminal_name(a0.func) == "len"
                    and a0.args and self._tainted(a0.args[0])):
                self.findings.append(_finding(
                    "host-op-on-tracer", f, node,
                    f"range(len(...)) over a traced parameter of "
                    f"{f.qualname} ({_chain(f)}): the Python loop unrolls "
                    "one program copy per element at trace time — use "
                    "lax.scan / lax.fori_loop",
                ))


def _strip_static(expr: ast.AST):
    """Return the expr for taint walking, or None when the whole expr is a
    static-metadata access. Names under ``.shape``-like attributes and
    inside ``len(...)`` do not carry tracers at runtime."""

    class _T(ast.NodeTransformer):
        def visit_Attribute(self, node):
            if node.attr in STATIC_ATTRS:
                return ast.copy_location(ast.Constant(value=None), node)
            return self.generic_visit(node)

        def visit_Call(self, node):
            if terminal_name(node.func) == "len":
                return ast.copy_location(ast.Constant(value=None), node)
            return self.generic_visit(node)

    return _T().visit(copy.deepcopy(expr))


def check_host_op_on_tracer(project: Project) -> list[Finding]:
    """``int()``/``float()``/``.item()``/``range(len())`` on values that
    flow from the parameters of a traced function: a host scalar readback
    (or a trace-time unroll) hiding inside device code. Static metadata
    (``x.shape[0]``, ``x.ndim``, ``len(x)`` alone) is exempt."""
    out = []
    for f in project.funcs:
        if not f.traced or f.is_module or not f.taint_params:
            continue
        out.extend(_TaintWalk(f).run())
    return out


# -- rule 5: per-call-logging-in-jit -----------------------------------------

_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}


def _is_logger_receiver(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        return terminal_name(expr.func) in ("get_logger", "getLogger",
                                            "getChild")
    t = terminal_name(expr)
    if t is None:
        return False
    tl = t.lower()
    return tl in ("warnings",) or "log" in tl


def check_per_call_logging_in_jit(project: Project) -> list[Finding]:
    """Logging calls inside traced bodies run once per TRACE, not once per
    step — they look like per-batch telemetry and silently aren't, and
    each retrace re-emits them. Use the one-shot ``info_once`` idiom for
    trace-time signals, or ``jax.debug.print``/``jax.debug.callback`` for
    genuine in-program output."""
    out = []
    for f in project.funcs:
        if not f.traced or f.is_module:
            continue
        if f.name and f.name.endswith("once"):
            continue  # the one-shot idiom's own implementation
        for node in iter_owned(f.node):
            if not isinstance(node, ast.Call):
                continue
            t = terminal_name(node.func)
            if isinstance(node.func, ast.Name) and t == "print":
                out.append(_finding(
                    "per-call-logging-in-jit", f, node,
                    f"print() inside traced code ({_chain(f)}) runs at "
                    "trace time, not per step; use jax.debug.print for "
                    "in-program output or info_once for one-shot signals",
                ))
            elif (isinstance(node.func, ast.Attribute)
                  and t in _LOG_METHODS
                  and _is_logger_receiver(node.func.value)):
                out.append(_finding(
                    "per-call-logging-in-jit", f, node,
                    f"logger .{t}() inside traced code ({_chain(f)}) fires "
                    "once per trace and again on every retrace — use the "
                    "one-shot info_once idiom (utils/trace.py) or "
                    "jax.debug.callback",
                ))
    return out


# -- rule 6: export-doc-drift -------------------------------------------------

def _module_all(tree: ast.Module) -> list[tuple[str, ast.AST]]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        return [
                            (e.value, e) for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        ]
    return []


def check_export_doc_drift(project: Project) -> list[Finding]:
    """Names in a top-level package ``__init__.py.__all__`` missing from
    ``docs/API.md`` — the generated index (``scripts/gen_api_md.py``) went
    stale. Applies to any analyzed ``__init__.py`` whose grandparent
    directory carries ``docs/API.md`` (i.e. the package root)."""
    out = []
    for src in project.files:
        if os.path.basename(src.path) != "__init__.py":
            continue
        pkg_dir = os.path.dirname(os.path.abspath(src.path))
        api_md = os.path.join(os.path.dirname(pkg_dir), "docs", "API.md")
        if not os.path.isfile(api_md):
            continue
        exports = _module_all(src.tree)
        if not exports:
            continue
        try:
            with open(api_md, encoding="utf-8") as fh:
                documented = set(re.findall(r"`([^`\n]+)`", fh.read()))
        except OSError:
            continue
        rel_md = os.path.relpath(api_md)
        for name, node in exports:
            if name not in documented:
                out.append(_finding(
                    "export-doc-drift", src.path, node,
                    f"__all__ export {name!r} is missing from {rel_md}; "
                    "regenerate it (JAX_PLATFORMS=cpu python "
                    "scripts/gen_api_md.py)",
                ))
    return out


RULES = {
    "env-at-trace": check_env_at_trace,
    "axis-name-consistency": check_axis_name_consistency,
    "cond-branch-parity": check_cond_branch_parity,
    "host-op-on-tracer": check_host_op_on_tracer,
    "per-call-logging-in-jit": check_per_call_logging_in_jit,
    "export-doc-drift": check_export_doc_drift,
}

# names valid in suppressions but emitted by the runner itself
META_RULES = ("bad-suppression", "parse-error")


def rule_docs() -> dict[str, str]:
    return {name: (fn.__doc__ or "").strip() for name, fn in RULES.items()}
