"""graftlint runner: file collection, suppressions, rule orchestration.

Suppression syntax (reason REQUIRED — a suppression that does not say why
is itself a finding, and cannot be suppressed):

    x = os.environ.get("K")  # graftlint: disable=env-at-trace -- initial default only
    # graftlint: disable=axis-name-consistency -- fixture exercises drift
    psum(x, "data")

A comment alone on its line covers the next line; a trailing comment
covers its own line. Multiple rules: ``disable=rule-a,rule-b -- reason``.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

from .analysis import SourceFile, analyze
from .rules import Finding, META_RULES, RULES

__all__ = ["LintResult", "lint_paths", "collect_files"]

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(.*?))?\s*$"
)
# function-level trace barrier: on (or directly above) a def line, declares
# the function eager-only by contract; reason mandatory like suppressions
_EAGER_RE = re.compile(r"#\s*graftlint:\s*eager(?:\s*--\s*(.*?))?\s*$")


@dataclasses.dataclass
class Suppression:
    line: int  # line the comment sits on
    target_line: int  # line whose findings it covers
    rules: tuple[str, ...]
    reason: str


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # active (unsuppressed), sorted
    suppressed: list[Finding]
    files: list[str]

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_dict(self):
        return {
            "version": 1,
            "files_analyzed": len(self.files),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "counts": _counts(self.findings),
        }


def _counts(findings):
    out: dict[str, int] = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return dict(sorted(out.items()))


def collect_files(paths) -> list[str]:
    """Expand files/directories into a sorted .py file list (skips hidden
    dirs and __pycache__)."""
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if not d.startswith(".") and d != "__pycache__"
                )
                for n in sorted(names):
                    if n.endswith(".py"):
                        out.append(os.path.join(root, n))
        else:
            raise FileNotFoundError(p)
    seen = set()
    uniq = []
    for p in out:
        key = os.path.abspath(p)
        if key not in seen:
            seen.add(key)
            uniq.append(p)
    return uniq


def _display_path(path: str) -> str:
    rel = os.path.relpath(path)
    return rel if not rel.startswith("..") else os.path.abspath(path)


def _parse_suppressions(path: str, text: str):
    """(suppressions, eager-pin lines, bad-suppression findings)."""
    sups: list[Suppression] = []
    eager: dict[int, str] = {}
    bad: list[Finding] = []
    known = set(RULES) | set(META_RULES)
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        comments = [(t.start[0], t.start[1], t.string)
                    for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sups, eager, bad
    lines = text.splitlines()
    for lineno, col, comment in comments:
        em = _EAGER_RE.search(comment)
        if em:
            reason = (em.group(1) or "").strip()
            if not reason:
                bad.append(Finding(
                    "bad-suppression", path, lineno, col,
                    "eager pin without a reason; every graftlint "
                    "annotation must say WHY: "
                    "'# graftlint: eager -- <reason>'"))
                continue
            own_line = lines[lineno - 1] if lineno <= len(lines) else ""
            standalone = own_line[:col].strip() == ""
            eager[lineno + 1 if standalone else lineno] = reason
            continue
        m = _SUPPRESS_RE.search(comment)
        if not m:
            # only directive-looking comments (marker followed by a colon)
            # are checked; prose that merely mentions graftlint is fine
            if "graftlint" + ":" in comment:
                bad.append(Finding(
                    "bad-suppression", path, lineno, col,
                    "malformed graftlint comment; expected '# graftlint: "
                    "disable=<rule>[,<rule>] -- <reason>' or "
                    "'# graftlint: eager -- <reason>'"))
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        unknown = [r for r in rules if r not in known]
        if unknown:
            bad.append(Finding(
                "bad-suppression", path, lineno, col,
                f"unknown rule(s) {unknown} in suppression; known rules: "
                f"{sorted(RULES)}"))
            continue
        if not reason:
            bad.append(Finding(
                "bad-suppression", path, lineno, col,
                "suppression without a reason; every graftlint suppression "
                "must say WHY: '# graftlint: disable=<rule> -- <reason>'"))
            continue
        own_line = lines[lineno - 1] if lineno <= len(lines) else ""
        standalone = own_line[:col].strip() == ""
        target = lineno + 1 if standalone else lineno
        sups.append(Suppression(lineno, target, rules, reason))
    return sups, eager, bad


def lint_paths(paths, select=None, ignore=None) -> LintResult:
    """Run graftlint over files/directories. ``select``/``ignore`` are
    iterables of rule names (select wins; both default to all rules)."""
    file_paths = collect_files(paths)
    sources: list[SourceFile] = []
    findings: list[Finding] = []
    suppressions: dict[str, list[Suppression]] = {}
    for path in file_paths:
        display = _display_path(path)
        try:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
            tree = ast.parse(text, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(Finding(
                "parse-error", display,
                getattr(e, "lineno", 1) or 1, 0,
                f"cannot analyze: {type(e).__name__}: {e}"))
            continue
        sups, eager, bad = _parse_suppressions(display, text)
        sources.append(SourceFile(path=display, text=text, tree=tree,
                                  eager_lines=eager))
        suppressions[display] = sups
        findings.extend(bad)

    project = analyze(sources)
    active_rules = dict(RULES)
    if select:
        wanted = set(select)
        unknown = wanted - set(RULES)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        active_rules = {k: v for k, v in RULES.items() if k in wanted}
    if ignore:
        unknown = set(ignore) - set(RULES)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        active_rules = {k: v for k, v in active_rules.items()
                        if k not in set(ignore)}
    for check in active_rules.values():
        findings.extend(check(project))

    active: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        hit = None
        if f.rule not in META_RULES:  # meta findings cannot be suppressed
            for s in suppressions.get(f.path, []):
                if f.line == s.target_line and f.rule in s.rules:
                    hit = s
                    break
        if hit is not None:
            f.suppressed = True
            suppressed.append(f)
        else:
            active.append(f)
    active.sort(key=Finding.sort_key)
    suppressed.sort(key=Finding.sort_key)
    return LintResult(findings=active, suppressed=suppressed,
                      files=[s.path for s in sources])
