"""graftlint runner: file collection, suppressions, rule orchestration.

Suppression syntax (reason REQUIRED — a suppression that does not say why
is itself a finding, and cannot be suppressed):

    x = os.environ.get("K")  # graftlint: disable=env-at-trace -- initial default only
    # graftlint: disable=axis-name-consistency -- fixture exercises drift
    psum(x, "data")

A comment alone on its line covers the next line; a trailing comment
covers its own line. Multiple rules: ``disable=rule-a,rule-b -- reason``.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

from .analysis import SourceFile, analyze
from .rules import FAMILIES, Finding, META_RULES, RULES

__all__ = ["LintResult", "changed_files", "collect_files", "lint_paths"]

# anchored at the comment START: a directive is the comment's whole job —
# prose that merely QUOTES the syntax mid-comment (the lint tool's own
# sources do) is not an annotation and must not land in the --debt report
_SUPPRESS_RE = re.compile(
    r"^#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(.*?))?\s*$"
)
# function-level trace barrier: on (or directly above) a def line, declares
# the function eager-only by contract; reason mandatory like suppressions
_EAGER_RE = re.compile(r"^#\s*graftlint:\s*eager(?:\s*--\s*(.*?))?\s*$")


@dataclasses.dataclass
class Suppression:
    line: int  # line the comment sits on
    target_line: int  # line whose findings it covers
    rules: tuple[str, ...]
    reason: str


@dataclasses.dataclass
class Annotation:
    """One graftlint source annotation (suppression or eager pin) — the
    unit of the ``--debt`` report: every one is reasoned by construction
    (reasonless annotations are bad-suppression findings instead)."""

    kind: str  # "disable" | "eager"
    path: str
    line: int
    rules: tuple[str, ...]  # ("eager",) for pins
    reason: str

    def to_dict(self):
        return {"kind": self.kind, "path": self.path, "line": self.line,
                "rules": list(self.rules), "reason": self.reason}


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # active (unsuppressed), sorted
    suppressed: list[Finding]
    files: list[str]
    # every reasoned annotation in the analyzed set (suppression debt)
    annotations: list[Annotation] = dataclasses.field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_dict(self):
        return {
            "version": 2,
            "files_analyzed": len(self.files),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "counts": _counts(self.findings),
            "annotations": [a.to_dict() for a in self.annotations],
        }


def _counts(findings):
    out: dict[str, int] = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return dict(sorted(out.items()))


def collect_files(paths) -> list[str]:
    """Expand files/directories into a sorted .py file list (skips hidden
    dirs and __pycache__)."""
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if not d.startswith(".") and d != "__pycache__"
                )
                for n in sorted(names):
                    if n.endswith(".py"):
                        out.append(os.path.join(root, n))
        else:
            raise FileNotFoundError(p)
    seen = set()
    uniq = []
    for p in out:
        key = os.path.abspath(p)
        if key not in seen:
            seen.add(key)
            uniq.append(p)
    return uniq


def _display_path(path: str) -> str:
    rel = os.path.relpath(path)
    return rel if not rel.startswith("..") else os.path.abspath(path)


def _parse_suppressions(path: str, text: str):
    """(suppressions, eager-pin lines, bad-suppression findings)."""
    sups: list[Suppression] = []
    eager: dict[int, str] = {}
    bad: list[Finding] = []
    known = set(RULES) | set(META_RULES)
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        comments = [(t.start[0], t.start[1], t.string)
                    for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sups, eager, bad
    lines = text.splitlines()
    for lineno, col, comment in comments:
        em = _EAGER_RE.search(comment)
        if em:
            reason = (em.group(1) or "").strip()
            if not reason:
                bad.append(Finding(
                    "bad-suppression", path, lineno, col,
                    "eager pin without a reason; every graftlint "
                    "annotation must say WHY: "
                    "'# graftlint: eager -- <reason>'"))
                continue
            own_line = lines[lineno - 1] if lineno <= len(lines) else ""
            standalone = own_line[:col].strip() == ""
            eager[lineno + 1 if standalone else lineno] = reason
            continue
        m = _SUPPRESS_RE.search(comment)
        if not m:
            # only comments that START with the marker are directives;
            # prose that merely mentions/quotes graftlint syntax is fine
            if re.match(r"^#\s*graftlint\s*:", comment):
                bad.append(Finding(
                    "bad-suppression", path, lineno, col,
                    "malformed graftlint comment; expected '# graftlint: "
                    "disable=<rule>[,<rule>] -- <reason>' or "
                    "'# graftlint: eager -- <reason>'"))
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        unknown = [r for r in rules if r not in known]
        if unknown:
            bad.append(Finding(
                "bad-suppression", path, lineno, col,
                f"unknown rule(s) {unknown} in suppression; known rules: "
                f"{sorted(RULES)}"))
            continue
        if not reason:
            bad.append(Finding(
                "bad-suppression", path, lineno, col,
                "suppression without a reason; every graftlint suppression "
                "must say WHY: '# graftlint: disable=<rule> -- <reason>'"))
            continue
        own_line = lines[lineno - 1] if lineno <= len(lines) else ""
        standalone = own_line[:col].strip() == ""
        target = lineno + 1 if standalone else lineno
        sups.append(Suppression(lineno, target, rules, reason))
    return sups, eager, bad


def _expand_rule_tokens(tokens) -> set[str]:
    """Resolve a select/ignore token list: family names expand to their
    member rules; unknown tokens raise."""
    out: set[str] = set()
    unknown = []
    for tok in tokens:
        if tok in FAMILIES:
            out.update(FAMILIES[tok])
        elif tok in RULES:
            out.add(tok)
        else:
            unknown.append(tok)
    if unknown:
        raise ValueError(
            f"unknown rule(s)/famil(ies): {sorted(unknown)} "
            f"(rules: {sorted(RULES)}; families: {sorted(FAMILIES)})")
    return out


def changed_files(base: str, cwd: str | None = None) -> set[str]:
    """Absolute paths of .py files changed vs ``base`` per ``git diff
    --name-only`` (committed + staged + worktree changes). Raises
    ValueError when git cannot answer (not a repo, unknown base)."""
    import subprocess

    cwd = cwd or os.getcwd()
    try:
        proc = subprocess.run(
            ["git", "diff", "--name-only", base, "--"],
            cwd=cwd, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        raise ValueError(f"--changed: cannot run git: {e}") from e
    if proc.returncode != 0:
        raise ValueError(
            f"--changed: git diff --name-only {base} failed: "
            f"{proc.stderr.strip()}")
    root = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"],
        cwd=cwd, capture_output=True, text=True, timeout=30,
    ).stdout.strip() or cwd
    return {
        os.path.abspath(os.path.join(root, line.strip()))
        for line in proc.stdout.splitlines()
        if line.strip().endswith(".py")
    }


def lint_paths(paths, select=None, ignore=None, only=None) -> LintResult:
    """Run graftlint over files/directories. ``select``/``ignore`` are
    iterables of rule names OR family names (select wins; both default to
    all rules). ``only`` (a set of absolute file paths — the --changed
    mode) restricts which files findings are REPORTED for; the analysis
    itself always runs over the full file set so cross-file facts (axis
    constants, the call graph, guard propagation) stay sound."""
    file_paths = collect_files(paths)
    sources: list[SourceFile] = []
    findings: list[Finding] = []
    suppressions: dict[str, list[Suppression]] = {}
    annotations: list[Annotation] = []
    for path in file_paths:
        display = _display_path(path)
        try:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
            tree = ast.parse(text, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(Finding(
                "parse-error", display,
                getattr(e, "lineno", 1) or 1, 0,
                f"cannot analyze: {type(e).__name__}: {e}"))
            continue
        sups, eager, bad = _parse_suppressions(display, text)
        sources.append(SourceFile(path=display, text=text, tree=tree,
                                  eager_lines=eager))
        suppressions[display] = sups
        findings.extend(bad)
        for s in sups:
            annotations.append(Annotation(
                "disable", display, s.line, s.rules, s.reason))
        for line, reason in sorted(eager.items()):
            annotations.append(Annotation(
                "eager", display, line, ("eager",), reason))

    project = analyze(sources)
    active_rules = dict(RULES)
    if select:
        wanted = _expand_rule_tokens(select)
        active_rules = {k: v for k, v in RULES.items() if k in wanted}
    if ignore:
        dropped = _expand_rule_tokens(ignore)
        active_rules = {k: v for k, v in active_rules.items()
                        if k not in dropped}
    for check in active_rules.values():
        findings.extend(check(project))

    active: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        hit = None
        if f.rule not in META_RULES:  # meta findings cannot be suppressed
            for s in suppressions.get(f.path, []):
                if f.line == s.target_line and f.rule in s.rules:
                    hit = s
                    break
        if hit is not None:
            f.suppressed = True
            suppressed.append(f)
        else:
            active.append(f)
    if only is not None:
        keep = {os.path.abspath(p) for p in only}

        def kept(f: Finding) -> bool:
            return os.path.abspath(f.path) in keep

        active = [f for f in active if kept(f)]
        suppressed = [f for f in suppressed if kept(f)]
        annotations = [a for a in annotations
                       if os.path.abspath(a.path) in keep]
    active.sort(key=Finding.sort_key)
    suppressed.sort(key=Finding.sort_key)
    annotations.sort(key=lambda a: (a.path, a.line))
    return LintResult(findings=active, suppressed=suppressed,
                      files=[s.path for s in sources],
                      annotations=annotations)
