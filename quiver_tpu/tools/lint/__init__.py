"""graftlint — trace-safety and collective-consistency static analysis.

AST-only (the analyzed code is never imported), seeded with rules distilled
from bugs this repo actually shipped and fixed:

* ``env-at-trace`` — ``os.environ`` reads reachable from jit/shard_map/
  lax-control-flow bodies (the QUIVER_COUNTS bug): the value freezes at
  first trace while looking like a live switch.
* ``axis-name-consistency`` — collective/PartitionSpec axis names must use
  the shared ``parallel/mesh.py`` constants; unknown literals are flagged
  as drift.
* ``cond-branch-parity`` — ``lax.cond`` branches returning mismatched
  tuple structure (the psum-fallback pattern).
* ``host-op-on-tracer`` — ``int()``/``.item()``/``range(len())`` on values
  flowing from traced parameters.
* ``per-call-logging-in-jit`` — logging in traced bodies that is not the
  one-shot ``info_once`` idiom.
* ``export-doc-drift`` — ``__all__`` exports missing from ``docs/API.md``.

CLI: ``python -m quiver_tpu.tools.lint [paths]`` (``--json``,
``--list-rules``, ``--select``, ``--ignore``; exit 0 clean / 1 findings /
2 usage). Inline suppression: ``# graftlint: disable=<rule> -- <reason>``
— the reason is mandatory.
"""

from .rules import Finding, RULES, rule_docs
from .runner import LintResult, collect_files, lint_paths
from .cli import main

__all__ = [
    "Finding",
    "LintResult",
    "RULES",
    "collect_files",
    "lint_paths",
    "main",
    "rule_docs",
]
