"""graftlint — trace-safety and collective-consistency static analysis.

AST-only (the analyzed code is never imported), seeded with rules distilled
from bugs this repo actually shipped and fixed:

* ``env-at-trace`` — ``os.environ`` reads reachable from jit/shard_map/
  lax-control-flow bodies (the QUIVER_COUNTS bug): the value freezes at
  first trace while looking like a live switch.
* ``axis-name-consistency`` — collective/PartitionSpec axis names must use
  the shared ``parallel/mesh.py`` constants; unknown literals are flagged
  as drift.
* ``cond-branch-parity`` — ``lax.cond`` branches returning mismatched
  tuple structure (the psum-fallback pattern).
* ``host-op-on-tracer`` — ``int()``/``.item()``/``range(len())`` on values
  flowing from traced parameters.
* ``per-call-logging-in-jit`` — logging in traced bodies that is not the
  one-shot ``info_once`` idiom.
* ``export-doc-drift`` — ``__all__`` exports missing from ``docs/API.md``.

graftlint v2 adds an interprocedural dataflow engine — per-function CFGs
with dominator computation (``tools/lint/cfg.py``), so rules require that
an operation is *dominated* by a guard, with guard facts propagated
across the call graph — and three rule families distilled from the
PR 5-8 disciplines:

* **staleness** — ``stale-version-read``: public methods of a
  version-guarded class reading re-captured state without a dominating
  ``VersionMismatchError`` guard (the PR 8 discipline).
* **transaction** — ``non-atomic-publish`` / ``commit-marker-order`` /
  ``replace-without-fsync``: the temp-dir + fsync + ``os.replace`` +
  COMMIT-last save discipline (PR 7) machine-checked.
* **concurrency** — ``executor-lifecycle`` / ``lock-held-across-call`` /
  ``metric-name-constant``: executors need a reachable shutdown path,
  non-reentrant locks must not be held across re-entering calls, and
  registry metric names must use the ``obs/registry.py`` constants.

CLI: ``python -m quiver_tpu.tools.lint [paths]`` (``--json``,
``--list-rules``, ``--select``/``--ignore`` accepting rules or families,
``--changed BASE`` for O(diff) reporting, ``--sarif PATH`` for CI
annotation, ``--debt`` for the reasoned-suppression report; exit 0 clean
/ 1 findings / 2 usage). Inline suppression: ``# graftlint:
disable=<rule> -- <reason>`` — the reason is mandatory.
"""

from .rules import FAMILIES, Finding, RULES, family_of, rule_docs
from .runner import LintResult, changed_files, collect_files, lint_paths
from .report import build_debt, build_sarif
from .cfg import CFG, build_cfg
from .cli import main

__all__ = [
    "CFG",
    "FAMILIES",
    "Finding",
    "LintResult",
    "RULES",
    "build_cfg",
    "build_debt",
    "build_sarif",
    "changed_files",
    "collect_files",
    "family_of",
    "lint_paths",
    "main",
    "rule_docs",
]
