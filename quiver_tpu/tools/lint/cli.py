"""graftlint CLI.

    python -m quiver_tpu.tools.lint quiver_tpu/ scripts/ benchmarks/

Exit codes (stable, for CI):
  0 — clean (suppressed findings are fine)
  1 — findings (including parse errors and bad suppressions)
  2 — usage error (unknown rule/family, missing path, bad --changed base)

``--select``/``--ignore`` accept rule names AND family names (trace,
consistency, staleness, transaction, concurrency). ``--changed BASE``
restricts *reporting* to files in ``git diff --name-only BASE`` — the
analysis still runs over the full path set so cross-file facts (axis
constants, the call graph, guard propagation) stay sound. ``--sarif
PATH`` writes a SARIF 2.1.0 document for CI annotation ("-" = stdout);
``--debt`` prints the reasoned-suppression report (with --json, embeds it
in the JSON document).
"""

from __future__ import annotations

import argparse
import json
import sys

from .rules import FAMILIES, family_of, rule_docs
from .runner import changed_files, lint_paths

__all__ = ["main"]


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m quiver_tpu.tools.lint",
        description="graftlint — trace-safety, collective-consistency and "
                    "dataflow (staleness/transaction/concurrency) static "
                    "analysis for quiver_tpu",
    )
    p.add_argument("paths", nargs="*", default=["."],
                   help="files or directories to lint (default: .)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--select", default=None,
                   help="comma-separated rules/families to run "
                        "(default: all)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rules/families to skip")
    p.add_argument("--changed", default=None, metavar="BASE",
                   help="report findings only for files changed vs the "
                        "given git base (analysis stays whole-tree)")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="write a SARIF 2.1.0 report to PATH ('-' for "
                        "stdout) for CI annotation")
    p.add_argument("--debt", action="store_true",
                   help="print the reasoned-suppression debt report "
                        "(rule, file, reason, commit age)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry (grouped by family) "
                        "and exit")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        docs = rule_docs()
        for fam, rules in FAMILIES.items():
            print(f"[{fam}]")
            for name in rules:
                doc = docs.get(name, "")
                first = doc.splitlines()[0] if doc else ""
                print(f"  {name}: {first}")
        return 0
    split = (lambda s: [r.strip() for r in s.split(",") if r.strip()])
    try:
        only = None
        if args.changed is not None:
            only = changed_files(args.changed)
        result = lint_paths(
            args.paths,
            select=split(args.select) if args.select else None,
            ignore=split(args.ignore) if args.ignore else None,
            only=only,
        )
    except (FileNotFoundError, ValueError) as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2
    if args.sarif:
        from .report import build_sarif

        doc = json.dumps(build_sarif(result), indent=1)
        if args.sarif == "-":
            print(doc)
        else:
            with open(args.sarif, "w", encoding="utf-8") as fh:
                fh.write(doc + "\n")
    debt = None
    if args.debt:
        from .report import build_debt

        debt = build_debt(result)
    if args.as_json:
        payload = result.to_dict()
        if debt is not None:
            payload["debt"] = debt
        print(json.dumps(payload, indent=1))
        return result.exit_code
    for f in result.findings:
        print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule}: "
              f"[{family_of(f.rule)}] {f.message}")
    if debt is not None:
        from .report import format_debt

        print(format_debt(debt))
    changed_note = ""
    if only is not None:
        changed_note = f" [--changed: {len(only)} candidate file(s)]"
    print(
        f"graftlint: {len(result.findings)} finding(s) "
        f"({len(result.suppressed)} suppressed) in "
        f"{len(result.files)} file(s){changed_note}"
    )
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
