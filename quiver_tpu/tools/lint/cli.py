"""graftlint CLI.

    python -m quiver_tpu.tools.lint quiver_tpu/ scripts/ benchmarks/

Exit codes (stable, for CI):
  0 — clean (suppressed findings are fine)
  1 — findings (including parse errors and bad suppressions)
  2 — usage error (unknown rule, missing path)
"""

from __future__ import annotations

import argparse
import json
import sys

from .rules import rule_docs
from .runner import lint_paths

__all__ = ["main"]


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m quiver_tpu.tools.lint",
        description="graftlint — trace-safety and collective-consistency "
                    "static analysis for quiver_tpu",
    )
    p.add_argument("paths", nargs="*", default=["."],
                   help="files or directories to lint (default: .)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--select", default=None,
                   help="comma-separated rules to run (default: all)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rules to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        for name, doc in rule_docs().items():
            first = doc.splitlines()[0] if doc else ""
            print(f"{name}: {first}")
        return 0
    split = (lambda s: [r.strip() for r in s.split(",") if r.strip()])
    try:
        result = lint_paths(
            args.paths,
            select=split(args.select) if args.select else None,
            ignore=split(args.ignore) if args.ignore else None,
        )
    except (FileNotFoundError, ValueError) as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(result.to_dict(), indent=1))
        return result.exit_code
    for f in result.findings:
        print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule}: {f.message}")
    print(
        f"graftlint: {len(result.findings)} finding(s) "
        f"({len(result.suppressed)} suppressed) in "
        f"{len(result.files)} file(s)"
    )
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
