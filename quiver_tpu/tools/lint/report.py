"""Machine-readable graftlint outputs: SARIF for CI annotation, and the
``--debt`` suppression report.

SARIF (Static Analysis Results Interchange Format, 2.1.0) is the shape CI
platforms ingest for inline PR annotation; the builder here emits the
minimal valid subset — one run, the rule registry as ``tool.driver.rules``
(rule docs as help text), every active finding as an ``error`` result and
every suppressed finding as a result carrying a ``suppressions`` entry
whose justification is the inline reason.

The debt report makes reasoned-suppression count visible per PR: every
``# graftlint: disable=... -- why`` and ``# graftlint: eager -- why`` in
the analyzed set, with the annotation's commit age from ``git blame``
(best-effort — "?" off a git checkout) so stale pins are findable.
"""

from __future__ import annotations

import time

from .rules import family_of, rule_docs
from .runner import LintResult

__all__ = ["build_sarif", "build_debt", "format_debt"]

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def _sarif_result(f, suppressed: bool) -> dict:
    res = {
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path.replace("\\", "/")},
                "region": {"startLine": int(f.line),
                           "startColumn": int(f.col) + 1},
            },
        }],
    }
    if suppressed:
        res["suppressions"] = [{"kind": "inSource",
                                "justification": "reasoned inline "
                                                 "suppression"}]
    return res


def build_sarif(result: LintResult) -> dict:
    """SARIF 2.1.0 document for a lint run (active + suppressed)."""
    docs = rule_docs()
    rules = [
        {
            "id": name,
            "shortDescription": {
                "text": (doc.splitlines()[0] if doc else name)},
            "fullDescription": {"text": doc},
            "properties": {"family": family_of(name)},
        }
        for name, doc in docs.items()
    ]
    results = [_sarif_result(f, False) for f in result.findings]
    results += [_sarif_result(f, True) for f in result.suppressed]
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://github.com/quiver-tpu/quiver-tpu",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def _blame_age_days(path: str, line: int) -> float | None:
    """Days since the annotation's line was last touched, via git blame
    (None when git/the repo cannot answer)."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "blame", "-L", f"{line},{line}", "--porcelain",
             "--", path],
            capture_output=True, text=True, timeout=15,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    for out_line in proc.stdout.splitlines():
        if out_line.startswith("committer-time "):
            try:
                then = int(out_line.split()[1])
            except (IndexError, ValueError):
                return None
            return max(time.time() - then, 0.0) / 86400.0
    return None


def build_debt(result: LintResult, blame: bool = True) -> dict:
    """The suppression-debt report: one record per reasoned annotation
    (rule(s), file, line, reason, commit age in days)."""
    records = []
    for a in result.annotations:
        rec = a.to_dict()
        rec["age_days"] = (_blame_age_days(a.path, a.line)
                           if blame else None)
        records.append(rec)
    return {
        "annotations": records,
        "total": len(records),
        "by_rule": _count_by_rule(result),
    }


def _count_by_rule(result: LintResult) -> dict:
    out: dict[str, int] = {}
    for a in result.annotations:
        for r in a.rules:
            out[r] = out.get(r, 0) + 1
    return dict(sorted(out.items()))


def format_debt(debt: dict) -> str:
    """Human-readable debt table (the --debt text output)."""
    lines = [f"graftlint debt: {debt['total']} reasoned annotation(s)"]
    for rule, n in debt["by_rule"].items():
        lines.append(f"  {rule}: {n}")
    for rec in debt["annotations"]:
        age = rec.get("age_days")
        age_s = f"{age:6.0f}d" if age is not None else "     ?"
        lines.append(
            f"  {age_s}  {rec['path']}:{rec['line']}  "
            f"[{','.join(rec['rules'])}]  {rec['reason']}")
    return "\n".join(lines)
