"""Machine-readable graftlint outputs: SARIF for CI annotation, and the
``--debt`` suppression report.

SARIF (Static Analysis Results Interchange Format, 2.1.0) is the shape CI
platforms ingest for inline PR annotation. The document builder lives in
``tools/sarif.py`` — shared with graftaudit so both analyzers emit one
schema and CI merges them into a single ``analysis.sarif`` artifact;
this module binds it to graftlint's rule registry.

The debt report makes reasoned-suppression count visible per PR: every
``# graftlint: disable=... -- why`` and ``# graftlint: eager -- why`` in
the analyzed set, with the annotation's commit age from ``git blame``
(best-effort — "?" off a git checkout) so stale pins are findable.
"""

from __future__ import annotations

import time

from ..sarif import build_sarif_doc
from .rules import family_of, rule_docs
from .runner import LintResult

__all__ = ["build_sarif", "build_debt", "format_debt"]


def build_sarif(result: LintResult) -> dict:
    """SARIF 2.1.0 document for a lint run (active + suppressed)."""
    return build_sarif_doc("graftlint", rule_docs(), family_of,
                           result.findings, result.suppressed)


def _blame_age_days(path: str, line: int) -> float | None:
    """Days since the annotation's line was last touched, via git blame
    (None when git/the repo cannot answer)."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "blame", "-L", f"{line},{line}", "--porcelain",
             "--", path],
            capture_output=True, text=True, timeout=15,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    for out_line in proc.stdout.splitlines():
        if out_line.startswith("committer-time "):
            try:
                then = int(out_line.split()[1])
            except (IndexError, ValueError):
                return None
            return max(time.time() - then, 0.0) / 86400.0
    return None


def build_debt(result: LintResult, blame: bool = True) -> dict:
    """The suppression-debt report: one record per reasoned annotation
    (rule(s), file, line, reason, commit age in days)."""
    records = []
    for a in result.annotations:
        rec = a.to_dict()
        rec["age_days"] = (_blame_age_days(a.path, a.line)
                           if blame else None)
        records.append(rec)
    return {
        "annotations": records,
        "total": len(records),
        "by_rule": _count_by_rule(result),
    }


def _count_by_rule(result: LintResult) -> dict:
    out: dict[str, int] = {}
    for a in result.annotations:
        for r in a.rules:
            out[r] = out.get(r, 0) + 1
    return dict(sorted(out.items()))


def format_debt(debt: dict) -> str:
    """Human-readable debt table (the --debt text output)."""
    lines = [f"graftlint debt: {debt['total']} reasoned annotation(s)"]
    for rule, n in debt["by_rule"].items():
        lines.append(f"  {rule}: {n}")
    for rec in debt["annotations"]:
        age = rec.get("age_days")
        age_s = f"{age:6.0f}d" if age is not None else "     ?"
        lines.append(
            f"  {age_s}  {rec['path']}:{rec['line']}  "
            f"[{','.join(rec['rules'])}]  {rec['reason']}")
    return "\n".join(lines)
