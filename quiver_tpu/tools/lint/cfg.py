"""Per-function control-flow graphs + dominators — the graftlint v2 engine.

graftlint v1 could ask *"is a guard called somewhere in this function?"* —
good enough for trace-reachability, useless for the PR 5-8 disciplines
where placement matters: ``check_topo_version()`` inside one ``if`` branch
protects nothing, and a version check *after* the stale read is theater.
The question the new rule families need is *"does a guard DOMINATE this
operation?"* — every path from function entry to the operation passes
through the guard.

This module answers it with the textbook construction, statement-granular:

1. **CFG**: one graph per function; basic blocks hold *entries* — either a
   simple statement (owning its whole subtree) or a compound-statement
   *header* (owning only the test/iter/items expressions; the body lives
   in its own blocks). ``if``/``while``/``for``/``try``/``with``/``match``
   and ``break``/``continue``/``return``/``raise`` get their usual edges;
   every statement inside a ``try`` body additionally edges to each
   handler (an exception can occur at any statement boundary).
2. **Dominators**: the iterative forward dataflow on reverse-postorder —
   function-sized graphs make the classic O(n^2) bound irrelevant.
3. **Guard queries**: ``calls_dominating(node)`` (terminal call names
   guaranteed to have run before ``node``), ``exit_dominating_calls()``
   (calls guaranteed to run on every normal completion — the seed of the
   interprocedural *guard-establisher* fixpoint: a function whose exit is
   dominated by a guard call is itself a guard for its callers).

Known simplifications, all conservative toward *more* findings, never
fewer: a ``finally`` body is modeled on the normal path only (a guard
placed solely in ``finally`` is not credited as dominating later reads),
``while True:`` keeps its loop-exit edge, and a ``raise`` edges to the
handlers *and* the exit.
"""

from __future__ import annotations

import ast
import dataclasses

from .analysis import FuncInfo, Project, terminal_name

__all__ = [
    "Block",
    "CFG",
    "build_cfg",
    "cfg_of",
    "propagate_guard_establishers",
]

# entry kinds: "stmt" owns the whole statement subtree; "header" owns only
# the control expression(s) of a compound statement
_NESTED_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _owned_exprs(entry: tuple[str, ast.AST]) -> list[ast.AST]:
    """The expressions an entry actually evaluates when control reaches
    it (a header evaluates its test/iter/items, not its body)."""
    kind, node = entry
    if kind == "stmt":
        return [node]
    if isinstance(node, ast.If) or isinstance(node, ast.While):
        return [node.test]
    if isinstance(node, (ast.For, ast.AsyncFor)):
        return [node.target, node.iter]
    if isinstance(node, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in node.items]
    if isinstance(node, ast.Match):
        return [node.subject]
    if isinstance(node, (ast.Try, getattr(ast, "TryStar", ast.Try))):
        return []
    return []


def _walk_shallow(root: ast.AST):
    """ast.walk that does not descend into nested function/class bodies
    (their statements do not execute when this entry does)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _NESTED_DEFS):
                continue
            stack.append(child)


@dataclasses.dataclass
class Block:
    id: int
    entries: list[tuple[str, ast.AST]] = dataclasses.field(
        default_factory=list)
    succs: set[int] = dataclasses.field(default_factory=set)
    preds: set[int] = dataclasses.field(default_factory=set)


class CFG:
    """Control-flow graph of one function, with dominators on demand."""

    def __init__(self, func_node: ast.AST):
        self.func_node = func_node
        self.blocks: list[Block] = []
        self.entry = self._new_block().id
        self.exit = self._new_block().id
        # id(ast node) -> (block id, entry index) for every node owned by
        # an entry's evaluated expressions
        self._node_entry: dict[int, tuple[int, int]] = {}
        self._dom: list[set[int]] | None = None
        self._exit_calls: set[str] | None = None
        _Builder(self).build()
        self._index_nodes()

    # -- construction helpers (used by _Builder) -----------------------------

    def _new_block(self) -> Block:
        b = Block(id=len(self.blocks))
        self.blocks.append(b)
        return b

    def _edge(self, a: int, b: int) -> None:
        self.blocks[a].succs.add(b)
        self.blocks[b].preds.add(a)

    def _index_nodes(self) -> None:
        for b in self.blocks:
            for idx, entry in enumerate(b.entries):
                for expr in _owned_exprs(entry):
                    for node in _walk_shallow(expr):
                        self._node_entry.setdefault(id(node), (b.id, idx))

    # -- dominators ----------------------------------------------------------

    def _reachable(self) -> list[int]:
        seen = {self.entry}
        stack = [self.entry]
        while stack:
            b = stack.pop()
            for s in self.blocks[b].succs:
                if s not in seen:
                    seen.add(s)
                    stack.append(s)
        return sorted(seen)

    def dominators(self) -> list[set[int]]:
        """dom[b] = blocks on EVERY entry->b path (b included);
        unreachable blocks get an empty set."""
        if self._dom is not None:
            return self._dom
        reach = self._reachable()
        n = len(self.blocks)
        all_reach = set(reach)
        dom: list[set[int]] = [set() for _ in range(n)]
        for b in reach:
            dom[b] = {self.entry} if b == self.entry else set(all_reach)
        changed = True
        while changed:
            changed = False
            for b in reach:
                if b == self.entry:
                    continue
                preds = [p for p in self.blocks[b].preds if p in all_reach]
                new = set(all_reach)
                for p in preds:
                    new &= dom[p]
                if not preds:
                    new = set()
                new |= {b}
                if new != dom[b]:
                    dom[b] = new
                    changed = True
        self._dom = dom
        return dom

    # -- queries -------------------------------------------------------------

    def entry_of(self, node: ast.AST) -> tuple[int, int] | None:
        """(block id, entry index) of the entry that evaluates ``node``,
        or None when the node is not part of this CFG's evaluated code
        (e.g. inside a nested def)."""
        return self._node_entry.get(id(node))

    def dominating_entries(self, node: ast.AST):
        """Yield every entry guaranteed to have executed before ``node``
        does: entries of strictly-dominating blocks, plus earlier entries
        of the node's own block."""
        where = self.entry_of(node)
        if where is None:
            return
        bid, idx = where
        dom = self.dominators()
        for d in dom[bid]:
            if d == bid:
                continue
            yield from self.blocks[d].entries
        for entry in self.blocks[bid].entries[:idx]:
            yield entry

    def calls_dominating(self, node: ast.AST) -> set[str]:
        """Terminal names of every call guaranteed to have run before
        ``node`` executes."""
        out: set[str] = set()
        for entry in self.dominating_entries(node):
            for expr in _owned_exprs(entry):
                for n in _walk_shallow(expr):
                    if isinstance(n, ast.Call):
                        t = terminal_name(n.func)
                        if t:
                            out.add(t)
        return out

    def exit_dominating_calls(self) -> set[str]:
        """Terminal names of calls guaranteed to run on EVERY path that
        reaches the function's exit — what the function *establishes* for
        its callers. A function with no reachable exit (every path
        raises) establishes everything it calls on the way out; we return
        the calls of entry-dominated blocks in that case."""
        if self._exit_calls is not None:
            return self._exit_calls
        dom = self.dominators()
        out: set[str] = set()
        target = self.exit
        if not dom[target]:  # exit unreachable: use the entry block chain
            target = self.entry
        for d in dom[target]:
            for entry in self.blocks[d].entries:
                for expr in _owned_exprs(entry):
                    for n in _walk_shallow(expr):
                        if isinstance(n, ast.Call):
                            t = terminal_name(n.func)
                            if t:
                                out.add(t)
        self._exit_calls = out
        return out


class _Builder:
    """One pass over a function body, threading a current block."""

    def __init__(self, cfg: CFG):
        self.cfg = cfg
        # (break target, continue target) stack for loops
        self.loops: list[tuple[int, int]] = []
        # handler-entry block ids for the innermost try statements
        self.handlers: list[list[int]] = []

    def build(self) -> None:
        node = self.cfg.func_node
        if isinstance(node, ast.Lambda):
            b = self.cfg._new_block()
            self.cfg._edge(self.cfg.entry, b.id)
            b.entries.append(("stmt", ast.Expr(value=node.body)))
            # keep the real nodes indexed (the synthetic Expr is unmapped)
            self.cfg._edge(b.id, self.cfg.exit)
            return
        body = getattr(node, "body", [])
        if not isinstance(body, list):
            body = [body]
        first = self.cfg._new_block()
        self.cfg._edge(self.cfg.entry, first.id)
        last = self.stmts(body, first.id)
        if last is not None:
            self.cfg._edge(last, self.cfg.exit)

    # returns the open block id after the statements, or None if flow
    # cannot fall through (return/raise/break/continue on every path)
    def stmts(self, body: list[ast.stmt], cur: int) -> int | None:
        for stmt in body:
            if cur is None:
                # unreachable code still gets blocks (so its nodes index
                # somewhere), but no incoming edges
                cur = self.cfg._new_block().id
            cur = self.stmt(stmt, cur)
        return cur

    def _exc_edges(self, bid: int) -> None:
        """An exception raised in ``bid`` can jump to every enclosing
        handler."""
        for handler_blocks in self.handlers:
            for h in handler_blocks:
                self.cfg._edge(bid, h)

    def stmt(self, node: ast.stmt, cur: int) -> int | None:
        cfg = self.cfg
        if isinstance(node, (ast.Return,)):
            cfg.blocks[cur].entries.append(("stmt", node))
            cfg._edge(cur, cfg.exit)
            return None
        if isinstance(node, ast.Raise):
            cfg.blocks[cur].entries.append(("stmt", node))
            self._exc_edges(cur)
            cfg._edge(cur, cfg.exit)
            return None
        if isinstance(node, ast.Break):
            cfg.blocks[cur].entries.append(("stmt", node))
            if self.loops:
                cfg._edge(cur, self.loops[-1][0])
            return None
        if isinstance(node, ast.Continue):
            cfg.blocks[cur].entries.append(("stmt", node))
            if self.loops:
                cfg._edge(cur, self.loops[-1][1])
            return None
        if isinstance(node, ast.If):
            cfg.blocks[cur].entries.append(("header", node))
            after = cfg._new_block().id
            then = cfg._new_block().id
            cfg._edge(cur, then)
            then_end = self.stmts(node.body, then)
            if then_end is not None:
                cfg._edge(then_end, after)
            if node.orelse:
                els = cfg._new_block().id
                cfg._edge(cur, els)
                els_end = self.stmts(node.orelse, els)
                if els_end is not None:
                    cfg._edge(els_end, after)
            else:
                cfg._edge(cur, after)
            return after if cfg.blocks[after].preds else None
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            header = cfg._new_block().id
            cfg._edge(cur, header)
            cfg.blocks[header].entries.append(("header", node))
            after = cfg._new_block().id
            body = cfg._new_block().id
            cfg._edge(header, body)
            self.loops.append((after, header))
            body_end = self.stmts(node.body, body)
            self.loops.pop()
            if body_end is not None:
                cfg._edge(body_end, header)
            if node.orelse:
                els = cfg._new_block().id
                cfg._edge(header, els)
                els_end = self.stmts(node.orelse, els)
                if els_end is not None:
                    cfg._edge(els_end, after)
            else:
                cfg._edge(header, after)
            return after if cfg.blocks[after].preds else None
        if isinstance(node, (ast.With, ast.AsyncWith)):
            cfg.blocks[cur].entries.append(("header", node))
            body = cfg._new_block().id
            cfg._edge(cur, body)
            return self.stmts(node.body, body)
        if isinstance(node, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            return self._try(node, cur)
        if isinstance(node, ast.Match):
            cfg.blocks[cur].entries.append(("header", node))
            after = cfg._new_block().id
            exhaustive = False
            for case in node.cases:
                cb = cfg._new_block().id
                cfg._edge(cur, cb)
                end = self.stmts(case.body, cb)
                if end is not None:
                    cfg._edge(end, after)
                if (isinstance(case.pattern, ast.MatchAs)
                        and case.pattern.pattern is None
                        and case.guard is None):
                    exhaustive = True
            if not exhaustive:
                cfg._edge(cur, after)
            return after if cfg.blocks[after].preds else None
        # simple statement (incl. nested def/class, whose body is opaque)
        cfg.blocks[cur].entries.append(("stmt", node))
        return cur

    def _try(self, node, cur: int) -> int | None:
        cfg = self.cfg
        after = cfg._new_block().id
        # handler entry blocks exist before the body so body statements
        # can edge into them
        handler_entries = [cfg._new_block().id for _ in node.handlers]
        self.handlers.append(handler_entries)
        # each try-body statement sits in its own block with an edge to
        # every handler: the exception can fire at any statement boundary
        body_cur = cfg._new_block().id
        cfg._edge(cur, body_cur)
        for h in handler_entries:
            cfg._edge(body_cur, h)
        for stmt in node.body:
            nxt = self.stmt(stmt, body_cur)
            if nxt is None:
                body_cur = None
                break
            if nxt == body_cur:
                # split so the NEXT statement gets its own handler edges
                fresh = cfg._new_block().id
                cfg._edge(nxt, fresh)
                body_cur = fresh
            else:
                body_cur = nxt
            for h in handler_entries:
                cfg._edge(body_cur, h)
        self.handlers.pop()
        ends: list[int] = []
        if body_cur is not None:
            if node.orelse:
                els = cfg._new_block().id
                cfg._edge(body_cur, els)
                els_end = self.stmts(node.orelse, els)
                if els_end is not None:
                    ends.append(els_end)
            else:
                ends.append(body_cur)
        for h_entry, handler in zip(handler_entries, node.handlers):
            h_end = self.stmts(handler.body, h_entry)
            if h_end is not None:
                ends.append(h_end)
        if node.finalbody:
            fin = cfg._new_block().id
            for e in ends:
                cfg._edge(e, fin)
            if not ends:
                # every path raised/returned: the finally still runs, but
                # we keep it off the normal path (conservative)
                cfg._edge(cur, fin)
            return self.stmts(node.finalbody, fin)
        for e in ends:
            cfg._edge(e, after)
        return after if cfg.blocks[after].preds else None


def build_cfg(func_node: ast.AST) -> CFG:
    return CFG(func_node)


def cfg_of(project: Project, info: FuncInfo) -> CFG:
    """Project-memoized CFG for one function."""
    cache = project.cfg_cache
    cfg = cache.get(id(info.node))
    if cfg is None:
        cfg = CFG(info.node)
        cache[id(info.node)] = cfg
    return cfg


def propagate_guard_establishers(project: Project,
                                 seeds: set[str]) -> set[str]:
    """Interprocedural guard-fact propagation over the call graph: start
    from ``seeds`` (function names that ARE guards — e.g. they raise
    VersionMismatchError) and add every named function whose exit is
    dominated by a call to a known guard; repeat to fixpoint. A call to
    any returned name counts as a guard call for dominance queries
    (terminal-name linking, consistent with the rest of graftlint's
    conservative call-graph resolution)."""
    names = set(seeds)
    if not names:
        return names
    candidates = [
        f for f in project.funcs
        if f.name and not f.is_module
        and isinstance(f.node, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    changed = True
    while changed:
        changed = False
        for f in candidates:
            if f.name in names:
                continue
            if cfg_of(project, f).exit_dominating_calls() & names:
                names.add(f.name)
                changed = True
    return names
