"""graftaudit entry-point registry — the canonical programs the auditor
traces and walks.

Each :class:`Target` names one lowered program the repo stakes an
invariant on, a builder that AOT-traces it (``jax.jit(...).trace`` +
``.lower()`` — NO device execution; everything runs under
``JAX_PLATFORMS=cpu`` on a 2-device ``--xla_force_host_platform_device_count``
mesh), the source files whose edits make the target worth re-auditing
(``--changed`` scoping), and per-rule metadata/waivers.

Builders are memoized: a full ``run_audit()`` traces each program once and
every rule walks the shared artifact. Donation warnings are captured at
build time — jax reports an *unusable* donation only as a
``UserWarning`` at trace/lower time (the lowered text carries no attr for
it), so the warning stream is part of the audit artifact.
"""

from __future__ import annotations

import dataclasses
import warnings

import numpy as np

__all__ = ["Built", "Target", "REGISTRY", "build", "build_from",
           "clear_cache"]

MESH_DEVICES = 2  # the audit mesh: (data=1, feature=2)

_REGISTRY: dict = {}
_BUILT: dict = {}
_SHARED: dict = {}  # memoized heavyweight fixtures (trainers, ladders)


@dataclasses.dataclass(frozen=True)
class Built:
    """One audited program: the traced jaxpr, the lowered StableHLO text,
    and the donation warnings the build emitted."""

    name: str
    jaxpr: object  # ClosedJaxpr
    mlir: str
    donation_warnings: tuple
    meta: dict


@dataclasses.dataclass(frozen=True)
class Target:
    name: str
    doc: str
    builder: object  # () -> jax Traced (jit(...).trace result)
    sources: tuple  # repo-relative files this program is lowered from
    meta: dict = dataclasses.field(default_factory=dict)
    # rule -> reason: registry-side reasoned waivers (suppressed findings)
    waivers: dict = dataclasses.field(default_factory=dict)


def _register(name, doc, sources, meta=None, waivers=None):
    def deco(fn):
        _REGISTRY[name] = Target(
            name=name, doc=doc, builder=fn, sources=tuple(sources),
            meta=dict(meta or {}), waivers=dict(waivers or {}),
        )
        return fn

    return deco


REGISTRY = _REGISTRY


def build_from(t: Target) -> Built:
    """Trace + lower one target, capturing donation warnings (jax reports
    unusable donations ONLY as warnings — they lower to no attr). Also
    the entry point tests use to audit fixture programs that are not in
    the registry."""
    with warnings.catch_warnings(record=True) as wlist:
        warnings.simplefilter("always")
        traced = t.builder()
        mlir = traced.lower().as_text()
    donation = tuple(
        str(w.message) for w in wlist
        if "donat" in str(w.message).lower()
    )
    return Built(name=t.name, jaxpr=traced.jaxpr, mlir=mlir,
                 donation_warnings=donation, meta=t.meta)


def build(name: str) -> Built:
    """Memoized :func:`build_from` over the registry."""
    if name not in _BUILT:
        _BUILT[name] = build_from(_REGISTRY[name])
    return _BUILT[name]


def clear_cache() -> None:
    _BUILT.clear()
    _SHARED.clear()


# -- shared fixtures ----------------------------------------------------------


def _mesh():
    import jax

    from ...parallel.mesh import make_mesh

    if jax.device_count() < MESH_DEVICES:
        raise RuntimeError(
            f"graftaudit needs {MESH_DEVICES} CPU devices; run via the CLI "
            "(sets XLA_FLAGS before jax imports) or under tests/conftest.py"
        )
    if "mesh" not in _SHARED:
        _SHARED["mesh"] = make_mesh(MESH_DEVICES, data=1, feature=2)
    return _SHARED["mesh"]


def _tiny_trainer(**kw):
    """The test_obs.py acceptance-differential trainer, on the 2-device
    audit mesh: 96 nodes, 8-dim features, [3, 2] fanouts, local_batch=8,
    seed_sharding='all' — so the sharded-feature gather routes over
    all_to_all and the audited epoch body carries the full comm schedule.
    """
    key = tuple(sorted(kw.items()))
    if key in _SHARED:
        return _SHARED[key]
    import jax
    import jax.numpy as jnp
    import optax

    from ...core.topology import CSRTopo
    from ...feature.shard import ShardedFeature
    from ...models.sage import GraphSAGE
    from ...parallel.trainer import DistributedTrainer
    from ...sampling.sampler import GraphSageSampler

    mesh = _mesh()
    rng = np.random.default_rng(0)
    n = 96
    ei = rng.integers(0, n, size=(2, 800)).astype(np.int64)
    topo = CSRTopo(edge_index=ei)
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    feature_kw = {}
    if kw.pop("int8", False):
        feature_kw["dtype"] = "int8"
    store = ShardedFeature(
        mesh, device_cache_size="1G", csr_topo=topo, **feature_kw
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [3, 2], seed=0, seed_capacity=8)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    trainer = DistributedTrainer(
        mesh, sampler, store, model, optax.sgd(1e-2), local_batch=8,
        seed_sharding="all", **kw,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    out = (trainer, params, opt, labels)
    _SHARED[key] = out
    return out


def _trace_epoch(trainer, params, opt, labels, steps=1):
    import jax
    import jax.numpy as jnp

    seed_mat = trainer.pack_epoch(np.arange(steps * trainer.global_batch),
                                  seed=0)
    packed = jnp.asarray(seed_mat)
    keys = jax.random.split(jax.random.PRNGKey(1), steps)
    inject = jnp.zeros((steps,), bool)
    return trainer._epoch_fn.trace(
        params, opt, trainer.topo, trainer._feature_parts(), packed, labels,
        keys, inject,
    )


def _trace_step(trainer, params, opt, labels):
    import jax
    import jax.numpy as jnp

    seed_mat = trainer.pack_epoch(np.arange(trainer.global_batch), seed=0)
    packed = jnp.asarray(seed_mat)[0]
    key = jax.random.PRNGKey(1)
    inject = jnp.asarray(False)
    return trainer._step.trace(
        params, opt, trainer.topo, trainer._feature_parts(), packed, labels,
        key, inject,
    )


# comm model of the audited epoch body: W workers (seed_sharding="all"
# => every device), local_batch seeds each, prod(sizes) lanes per seed
_EPOCH_COMM = dict(feature_shards=2, local_len=2 * 8 * 3 * 2, feature_dim=8)

# the tiny step's metric reductions beyond the training math: the
# feature.routed_overflow scalar psum over "data" and the
# feature.tier_hits (3,) psum over ("data", "feature") — update alongside
# obs/registry.py when a new per-step metric collective lands
_EXPECTED_METRIC_REDUCTIONS = 2


# -- targets ------------------------------------------------------------------


@_register(
    "routed_gather",
    "capped-bucket routed feature gather with the forced psum fallback "
    "cond (cap < per-shard demand)",
    sources=("quiver_tpu/feature/shard.py", "quiver_tpu/parallel/routing.py",
             "quiver_tpu/parallel/mesh.py"),
    meta={"hbm_budget": 2048},
)
def _routed_gather():
    import jax
    import jax.numpy as jnp

    from ...feature.shard import ShardedTensor
    from ...parallel.mesh import FEATURE_AXIS, shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh()
    tbl = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    st = ShardedTensor(mesh).from_cpu_tensor(tbl)
    ids = jnp.arange(8, dtype=jnp.int32)

    def body(local, i):
        # cap=2 < the 8-lane demand: the overflow fallback cond is LIVE in
        # the lowered program (a statically exact cap folds it away)
        return st.routed_gather(local, i, cap=2, with_overflow=True)

    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(FEATURE_AXIS, None), P(FEATURE_AXIS)),
        out_specs=(P(FEATURE_AXIS, None), P()),
    ))
    return fn.trace(st.table, ids)


@_register(
    "tiered_lookup_int8",
    "trainer step over an int8-quantized ShardedFeature: the three-tier "
    "lookup with int8 codes riding the routed all_to_all",
    sources=("quiver_tpu/feature/shard.py", "quiver_tpu/feature/feature.py",
             "quiver_tpu/parallel/trainer.py"),
    meta={"int8_path": True, "hbm_budget": 40 * 1024},
)
def _tiered_lookup_int8():
    return _trace_step(*_tiny_trainer(int8=True, collect_metrics=False))


@_register(
    "sample_hop",
    "topo-sharded multilayer sample program (dist_sample_layer hops in "
    "shard_map, owner-routed frontiers)",
    sources=("quiver_tpu/sampling/dist.py", "quiver_tpu/sampling/sampler.py",
             "quiver_tpu/core/topology.py"),
    meta={"hbm_budget": 32 * 1024},
)
def _sample_hop():
    import jax

    from ...core.topology import CSRTopo
    from ...sampling.sampler import GraphSageSampler

    mesh = _mesh()
    rng = np.random.default_rng(7)
    ei = rng.integers(0, 120, size=(2, 900)).astype(np.int64)
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(
        topo, [3, 2], seed=7, seed_capacity=16, dedup="sort",
        topo_sharding="mesh", mesh=mesh,
    )
    run, _caps = sampler._compiled(16)
    seeds = jax.ShapeDtypeStruct((sampler.workers * 16,), np.int32)
    key = jax.ShapeDtypeStruct(np.shape(sampler._key),
                               np.asarray(sampler._key).dtype)
    return run.trace(*sampler._topo_operands(), seeds, key)


@_register(
    "epoch_body_alpha1",
    "fused epoch body (scan over the one-program step) at routed_alpha=1 "
    "— the comm-budget anchor at the tight cap",
    sources=("quiver_tpu/parallel/trainer.py", "quiver_tpu/control/cost.py",
             "quiver_tpu/feature/shard.py"),
    meta={"comm": dict(_EPOCH_COMM, alpha=1.0), "hbm_budget": 64 * 1024},
)
def _epoch_alpha1():
    return _trace_epoch(*_tiny_trainer(routed_alpha=1.0))


@_register(
    "epoch_body_alpha2",
    "fused epoch body at routed_alpha=2 (the default budget) — comm "
    "lanes double against the same analytic model",
    sources=("quiver_tpu/parallel/trainer.py", "quiver_tpu/control/cost.py",
             "quiver_tpu/feature/shard.py"),
    meta={"comm": dict(_EPOCH_COMM, alpha=2.0), "hbm_budget": 64 * 1024},
)
def _epoch_alpha2():
    return _trace_epoch(*_tiny_trainer(routed_alpha=2.0))


@_register(
    "epoch_pipelined",
    "software-pipelined epoch body (pipeline_depth=1, one-step skew): "
    "same invariants as the serial scan",
    sources=("quiver_tpu/parallel/trainer.py",
             "quiver_tpu/parallel/pipeline.py"),
    meta={"hbm_budget": 128 * 1024},
)
def _epoch_pipelined():
    return _trace_epoch(*_tiny_trainer(pipeline_depth=1), steps=2)


@_register(
    "epoch_donating",
    "epoch body with donate_epoch_state=True: every params+opt leaf must "
    "actually be donated (aliased or buffer-donor) with zero "
    "unusable-donation warnings",
    sources=("quiver_tpu/parallel/trainer.py",),
    meta={"donation": "claimed", "hbm_budget": 64 * 1024},
)
def _epoch_donating():
    import jax

    trainer, params, opt, labels = _tiny_trainer(donate_epoch_state=True)
    leaves = len(jax.tree_util.tree_leaves((params, opt)))
    # record the exact claimed-leaf count for the donation-audit rule
    _REGISTRY["epoch_donating"].meta["donated_leaves"] = leaves
    return _trace_epoch(trainer, params, opt, labels)


@_register(
    "serve_forward",
    "serving-ladder forward program (largest bucket): AOT ladder rung the "
    "steady-state replay contract is staked on",
    sources=("quiver_tpu/serving/ladder.py", "quiver_tpu/models/sage.py",
             "quiver_tpu/models/layers.py", "quiver_tpu/parallel/train.py"),
    meta={"donation": "none", "hbm_budget": 24 * 1024},
)
def _serve_forward():
    lad = _ladder()
    return lad.trace_forward(4)


@_register(
    "serve_sample",
    "serving-ladder per-bucket sample program (scan over lane samples)",
    sources=("quiver_tpu/serving/ladder.py", "quiver_tpu/ops/sample.py"),
    meta={"donation": "none", "hbm_budget": 24 * 1024},
)
def _serve_sample():
    lad = _ladder()
    return lad.trace_sample(4)


@_register(
    "metrics_on",
    "trainer step with collect_metrics=True — the telemetry-carrying "
    "half of the metrics-strip differential",
    sources=("quiver_tpu/parallel/trainer.py", "quiver_tpu/obs/registry.py"),
    meta={"hbm_budget": 64 * 1024},
)
def _metrics_on():
    return _trace_step(*_tiny_trainer(collect_metrics=True))


@_register(
    "metrics_off",
    "trainer step with collect_metrics=False — must equal metrics_on "
    "minus exactly the declared metric reductions",
    sources=("quiver_tpu/parallel/trainer.py", "quiver_tpu/obs/registry.py"),
    meta={"metrics_pair": "metrics_on",
          "expected_metric_reductions": _EXPECTED_METRIC_REDUCTIONS,
          "hbm_budget": 64 * 1024},
)
def _metrics_off():
    return _trace_step(*_tiny_trainer(collect_metrics=False))


@_register(
    "pallas_fused_interp",
    "fused sample megakernel family, interpret-mode lowering in ONE "
    "traced program: the uniform+eid hop over a host-numpy CSRTopo "
    "closure (regression: host indptr indexing broke this trace "
    "entirely), the weighted inverse-CDF hop, and the Pallas row gather "
    "(the QUIVER_{SAMPLE,GATHER}_KERNEL=pallas election paths)",
    sources=("quiver_tpu/ops/pallas/fused.py",
             "quiver_tpu/ops/pallas/sample.py",
             "quiver_tpu/ops/pallas/gather.py",
             "quiver_tpu/ops/election.py"),
    meta={"hbm_budget": 64 * 1024},
    # the CSR topology rides the closure as trace constants — bounded at
    # ~10KB here, and the production path passes topology as operands
    waivers={"constant-bloat": "fixture topology is closure-captured by "
                               "construction; production paths pass "
                               "topology operands"},
)
def _pallas_fused():
    import jax

    from ...core.topology import CSRTopo
    from ...ops.pallas.fused import fused_sample_layer
    from ...ops.pallas.gather import gather_rows

    rng = np.random.default_rng(0)
    ei = np.stack([rng.integers(0, 64, 900), rng.integers(0, 64, 900)])
    topo = CSRTopo(edge_index=ei)
    topo.set_edge_weight(rng.random(900).astype(np.float32) + 0.1)
    wtopo = topo.to_device(with_weights=True)
    seeds = jax.ShapeDtypeStruct((16,), np.int32)
    key = jax.ShapeDtypeStruct((2,), np.uint32)
    tbl = jax.ShapeDtypeStruct((64, 8), np.float32)
    ids = jax.ShapeDtypeStruct((16,), np.int32)

    def program(s, k, t, i):
        uni = fused_sample_layer(topo, s, 16, 4, k, with_eid=True,
                                 window=128, interpret=True)
        wei = fused_sample_layer(wtopo, s, 16, 4, k, weighted=True,
                                 window=128, interpret=True)
        return uni, wei, gather_rows(t, i, interpret=True)

    return jax.jit(program).trace(seeds, key, tbl, ids)


def _ladder():
    if "ladder" in _SHARED:
        return _SHARED["ladder"]
    import jax
    import jax.numpy as jnp

    from ...core.topology import CSRTopo
    from ...models.sage import GraphSAGE
    from ...parallel.train import empty_adjs, init_model
    from ...sampling.sampler import GraphSageSampler
    from ...serving.ladder import ServeLadder

    rng = np.random.default_rng(0)
    n, e = 240, 1600
    ei = np.stack([rng.integers(0, n, e), rng.integers(0, n, e)])
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(topo, [4, 3], seed=1, seed_capacity=4)
    model = GraphSAGE(hidden=16, num_classes=5, num_layers=2)
    adjs = empty_adjs([4, 3], batch=4, node_count=n)
    params = init_model(
        model, jax.random.PRNGKey(0),
        jnp.zeros((adjs[0].size[0], 12), jnp.float32), adjs,
    )
    lad = ServeLadder(sampler, model, feature_dim=12)
    lad.bind_params(params)
    _SHARED["ladder"] = lad
    return lad


@_register(
    "serve_fleet_forward",
    "fleet replica serve-ladder forward, warm-from-AOT variant: the "
    "program a second replica REPLAYS after deserializing the first "
    "replica's published executables (PR 17's zero-compile join) — the "
    "traced forward must carry the same invariants whether it was "
    "compiled locally or loaded from the shared cache",
    sources=("quiver_tpu/serving/fleet.py", "quiver_tpu/serving/aot.py",
             "quiver_tpu/serving/server.py", "quiver_tpu/serving/ladder.py"),
    meta={"donation": "none", "hbm_budget": 24 * 1024},
)
def _serve_fleet_forward():
    fleet = _fleet()
    # the warm joiner, not the cache-populating first replica
    return fleet.servers[-1]._ladder.trace_forward(4)


@_register(
    "mmap_tiered_gather",
    "MmapFeatureStore device-side tier merge (quiver-ooc): the traced "
    "tiered_lookup + dequant wrapping one staged batch runs, with the "
    "host-assembled cold block as a program operand — the out-of-core "
    "path's only on-device program",
    sources=("quiver_tpu/ooc/store.py", "quiver_tpu/ooc/format.py",
             "quiver_tpu/ooc/stager.py", "quiver_tpu/feature/feature.py"),
    meta={"hbm_budget": 16 * 1024},
)
def _mmap_tiered_gather():
    return _mmap_store().trace_lookup(16)


def _fleet():
    """A two-replica ServingFleet over a throwaway disk AOT cache: the
    first replica compiles+publishes (bucket 4 only, to bound build
    cost), the second joins warm. Construction compiles — never
    executes — which keeps the registry's trace-only discipline."""
    if "fleet" in _SHARED:
        return _SHARED["fleet"]
    import tempfile

    import jax
    import jax.numpy as jnp

    from ...core.topology import CSRTopo
    from ...feature.feature import Feature
    from ...models.sage import GraphSAGE
    from ...parallel.train import empty_adjs, init_model
    from ...sampling.sampler import GraphSageSampler
    from ...serving.fleet import ServingFleet

    rng = np.random.default_rng(3)
    n, e = 160, 900
    ei = np.stack([rng.integers(0, n, e), rng.integers(0, n, e)])
    topo = CSRTopo(edge_index=ei)
    feat = Feature(device_cache_size="1G").from_cpu_tensor(
        rng.normal(size=(n, 12)).astype(np.float32))
    sampler = GraphSageSampler(topo, [4, 3], seed=1, seed_capacity=4)
    model = GraphSAGE(hidden=16, num_classes=5, num_layers=2)
    adjs = empty_adjs([4, 3], batch=4, node_count=n)
    params = init_model(
        model, jax.random.PRNGKey(0),
        jnp.zeros((adjs[0].size[0], 12), jnp.float32), adjs,
    )
    cache_dir = tempfile.mkdtemp(prefix="graftmem-aot-")
    fleet = ServingFleet(
        sampler, model, params, feat, replicas=1, aot_cache=cache_dir,
        seed=7, warm=True, max_batch=4, buckets=(4,),
    )
    fleet.add_replica(warm=True)
    # record the join ledger so tests can assert the audited program
    # really is the warm-from-AOT variant (zero compiles on join)
    _REGISTRY["serve_fleet_forward"].meta["warm_join"] = dict(
        loaded=int(fleet.cold_starts[-1]["loaded"]),
        compiled=int(fleet.cold_starts[-1]["compiled"]),
    )
    _SHARED["fleet"] = fleet
    return fleet


def _mmap_store():
    """A tiny on-disk raw feature dir + reopened MmapFeatureStore with
    live hot AND cold tiers (device_cache_size splits the 64 rows)."""
    if "mmap_store" in _SHARED:
        return _SHARED["mmap_store"]
    import tempfile

    from ...core.topology import CSRTopo
    from ...ooc.store import MmapFeatureStore

    rng = np.random.default_rng(5)
    n, f = 64, 8
    ei = np.stack([rng.integers(0, n, 400), rng.integers(0, n, 400)])
    topo = CSRTopo(edge_index=ei)
    tensor = rng.normal(size=(n, f)).astype(np.float32)
    path = tempfile.mkdtemp(prefix="graftmem-ooc-")
    MmapFeatureStore.write(path, tensor,
                           device_cache_size=16 * f * 4, csr_topo=topo)
    store = MmapFeatureStore(path, access="mmap", window_rows=16)
    _SHARED["mmap_store"] = store
    return store
