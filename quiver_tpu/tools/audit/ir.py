"""jaxpr/StableHLO walking utilities for graftaudit.

Everything here operates on *already traced* artifacts — a
``jax.core.ClosedJaxpr`` (from ``jax.jit(fn).trace(...)``) and the
lowered StableHLO text (``.lower().as_text()``). Nothing executes on a
device; the only JAX dependency is the ``ClosedJaxpr``/``Jaxpr`` types
for recursion.

Primitive-name notes (pinned against the in-repo jax):

* ``psum`` appears as ``psum2`` inside ``shard_map`` bodies (the
  replication-tracking rewrite); both names are reductions here.
* ``pbroadcast`` is a replication *cast*, not communication — never
  counted as a collective.
* ``lax.cond`` is the ``cond`` primitive; per-branch programs live in
  ``eqn.params["branches"]`` as ClosedJaxprs.
* donation shows up in the lowered text as ``tf.aliasing_output`` on
  inputs jax pre-aliased to an output, or ``jax.buffer_donor`` on donated
  inputs whose pairing is deferred to XLA (scan-carried state lowers this
  way). An UNUSABLE donation leaves NO attr at all — jax only reports it
  as a trace/lower-time warning, which the target builders capture.
"""

from __future__ import annotations

import dataclasses
import re

__all__ = [
    "COLLECTIVES",
    "REDUCTIONS",
    "Collective",
    "iter_eqns",
    "collectives_of",
    "conds_of",
    "branch_collectives",
    "predicate_axis_reduced",
    "main_arg_attrs",
    "iter_consts",
    "f64_eqns",
]

# comm primitives (jaxpr names). psum2 / all_gather_invariant are the
# shard_map-internal spellings; reduce_scatter is psum_scatter's lowering.
REDUCTIONS = frozenset({
    "psum", "psum2", "psum_invariant", "pmin", "pmax", "pmean",
})
COLLECTIVES = REDUCTIONS | frozenset({
    "all_to_all", "all_gather", "all_gather_invariant", "ppermute",
    "pshuffle", "reduce_scatter", "psum_scatter",
})


def _jaxpr_of(obj):
    """Unwrap ClosedJaxpr -> Jaxpr; pass raw Jaxpr through; else None."""
    eqns = getattr(obj, "eqns", None)
    if eqns is not None:
        return obj
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and getattr(inner, "eqns", None) is not None:
        return inner
    return None


def _sub_jaxprs(eqn):
    """(param_key, index, Jaxpr) for every sub-program an eqn carries
    (pjit/shard_map ``jaxpr``, cond ``branches``, scan/while bodies,
    custom_* call jaxprs, ...) — keyed generically off the params so new
    primitives keep working."""
    for key, val in eqn.params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for i, sub in enumerate(vals):
            j = _jaxpr_of(sub)
            if j is not None:
                yield key, i, j


def iter_eqns(jaxpr, path=()):
    """Yield ``(eqn, path)`` over a jaxpr and all nested sub-jaxprs.
    ``path`` is a tuple of ``"prim"``/``"prim[i]"`` hops — e.g.
    ``("pjit", "shard_map", "cond[1]")`` — used to print *where* in the
    program a finding sits."""
    j = _jaxpr_of(jaxpr)
    if j is None:
        return
    for eqn in j.eqns:
        yield eqn, path
        for key, i, sub in _sub_jaxprs(eqn):
            hop = (f"{eqn.primitive.name}[{i}]"
                   if eqn.primitive.name == "cond" else eqn.primitive.name)
            yield from iter_eqns(sub, path + (hop,))


def _axes_of(eqn) -> tuple:
    """Normalized mesh-axis names of a collective eqn."""
    for key in ("axis_name", "axes", "axis"):
        if key in eqn.params:
            ax = eqn.params[key]
            if isinstance(ax, (tuple, list)):
                return tuple(str(a) for a in ax)
            return (str(ax),)
    return ()


@dataclasses.dataclass(frozen=True)
class Collective:
    """One comm op in a lowered program, as the rules compare them."""
    prim: str
    axes: tuple
    shape: tuple
    dtype: str
    path: tuple = dataclasses.field(default=(), compare=False)

    @property
    def lanes(self) -> int:
        """Leading-two-dims product — the bucket-lane count of a routed
        ``all_to_all`` operand shaped ``(F, cap, ...)``."""
        if len(self.shape) >= 2:
            return int(self.shape[0]) * int(self.shape[1])
        return int(self.shape[0]) if self.shape else 1

    def signature(self):
        """Identity used for multiset comparison across programs."""
        return (self.prim, self.axes, self.shape, self.dtype)

    def __str__(self):
        loc = "/".join(self.path) or "top"
        return (f"{self.prim}[{','.join(self.axes)}] "
                f"{self.dtype}{list(self.shape)} @ {loc}")


def _as_collective(eqn, path) -> Collective:
    v = eqn.invars[0]
    aval = v.aval
    return Collective(
        prim=eqn.primitive.name,
        axes=_axes_of(eqn),
        shape=tuple(getattr(aval, "shape", ())),
        dtype=str(getattr(aval, "dtype", "?")),
        path=path,
    )


def collectives_of(jaxpr, include_paths=True) -> list:
    """Ordered collectives of a program (nested programs included)."""
    out = []
    for eqn, path in iter_eqns(jaxpr):
        if eqn.primitive.name in COLLECTIVES:
            out.append(_as_collective(eqn, path if include_paths else ()))
    return out


def conds_of(jaxpr) -> list:
    """``(cond_eqn, enclosing_jaxpr, path)`` for every ``cond``. The
    enclosing jaxpr is kept so predicate provenance can be sliced in the
    scope the predicate variable is defined in."""
    out = []

    def _walk(j, path):
        j = _jaxpr_of(j)
        if j is None:
            return
        for eqn in j.eqns:
            if eqn.primitive.name == "cond":
                out.append((eqn, j, path))
            for key, i, sub in _sub_jaxprs(eqn):
                hop = (f"{eqn.primitive.name}[{i}]"
                       if eqn.primitive.name == "cond"
                       else eqn.primitive.name)
                _walk(sub, path + (hop,))

    _walk(jaxpr, ())
    return out


def branch_collectives(cond_eqn) -> list:
    """Per-branch ordered collective lists of a ``cond`` eqn."""
    return [collectives_of(br) for br in cond_eqn.params["branches"]]


def predicate_axis_reduced(cond_eqn, enclosing_jaxpr, axes) -> bool:
    """Is the cond predicate provably uniform across ``axes``?

    Backward slice from the predicate variable inside its defining scope:
    True when the slice passes through a reduction collective
    (psum/pmin/pmax/...) whose axis set covers ``axes`` — the repo's
    psum-fallback discipline (``parallel/routing.py``: the fallback cond's
    predicate is the axis-psum of the overflow count, so every axis member
    takes the same branch and the collectives inside cannot desync).
    In-slice nested calls (pjit wrappers around jnp ops) are scanned
    transitively. A predicate whose provenance leaves the scope (a scope
    input) is NOT provably reduced — callers treat that as a finding when
    the branches' collectives differ.
    """
    need = set(axes)
    if not need:
        return True
    defmap = {}
    for eqn in enclosing_jaxpr.eqns:
        for ov in eqn.outvars:
            defmap[ov] = eqn
    seen = set()
    stack = [cond_eqn.invars[0]]
    while stack:
        v = stack.pop()
        # Literals carry .val and define nothing; they are also unhashable
        if id(v) in seen or not hasattr(v, "aval") or hasattr(v, "val"):
            continue
        seen.add(id(v))
        eqn = defmap.get(v)
        if eqn is None:
            continue  # literal, const, or scope input — not reduced here
        if eqn.primitive.name in REDUCTIONS and need <= set(_axes_of(eqn)):
            return True
        # an in-slice call (pjit etc.): a covering reduction anywhere
        # inside reduces every output of the call
        for _k, _i, sub in _sub_jaxprs(eqn):
            for inner, _p in iter_eqns(sub):
                if (inner.primitive.name in REDUCTIONS
                        and need <= set(_axes_of(inner))):
                    return True
        stack.extend(eqn.invars)
    return False


# -- StableHLO text helpers ---------------------------------------------------

_MAIN_RE = re.compile(r"func\.func\s+(?:public\s+)?@main\((.*?)\)\s*->",
                      re.DOTALL)


def _split_top_level(s: str) -> list:
    """Split an MLIR argument list on top-level commas (respects nesting
    of ``<>``, ``{}``, ``()`` and ``[]`` inside type/attr expressions,
    and ignores brackets inside string attrs — a sharding literal like
    ``"{devices=[2,1]<=[2]}"`` carries an unbalanced ``<`` that would
    otherwise swallow every following comma and merge arguments)."""
    parts, depth, cur, in_str = [], 0, [], False
    for ch in s:
        if ch == '"':
            in_str = not in_str
        elif not in_str:
            if ch in "<{([":
                depth += 1
            elif ch in ">})]":
                depth -= 1
        if ch == "," and depth == 0 and not in_str:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    return parts


_ALIAS_IDX_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")


def main_arg_attrs(mlir_text: str) -> list:
    """Per-argument donation facts of ``@main``: a list (one dict per
    flattened argument, in order) of ``{"aliased": bool, "donor": bool,
    "alias_output": int | None}``.
    ``aliased`` = jax wired the input to an output buffer at lowering
    (``tf.aliasing_output``), with ``alias_output`` the flattened result
    index it writes into — the operand↔result *pairing*, not just the
    count; ``donor`` = donated with the buffer pairing deferred to XLA
    (``jax.buffer_donor``, ``alias_output`` None). Either attr counts as
    the donation being real; a donated arg with NEITHER never lowered at
    all (unusable donations surface only as build warnings)."""
    m = _MAIN_RE.search(mlir_text)
    if m is None:
        return []
    out = []
    for arg in _split_top_level(m.group(1)):
        am = _ALIAS_IDX_RE.search(arg)
        out.append({
            "aliased": am is not None,
            "donor": "jax.buffer_donor" in arg,
            "alias_output": None if am is None else int(am.group(1)),
        })
    return out


def iter_consts(closed_jaxpr, path=()):
    """Yield ``(const, path)`` for every constant captured by the program
    or any nested sub-program (closure-folded arrays land here)."""
    for c in getattr(closed_jaxpr, "consts", ()) or ():
        yield c, path
    j = _jaxpr_of(closed_jaxpr)
    if j is None:
        return
    for eqn in j.eqns:
        for key, i, sub in _sub_jaxprs(eqn):
            # recurse through the *closed* object when the param holds one
            # (its consts are what we are after), else the raw jaxpr
            vals = eqn.params[key]
            vals = vals if isinstance(vals, (tuple, list)) else (vals,)
            closed = vals[i]
            yield from iter_consts(closed, path + (eqn.primitive.name,))


def f64_eqns(jaxpr) -> list:
    """``(eqn, aval, path)`` wherever a float64/complex128 value is
    produced — the f64-leak detector (the repo runs x64-disabled; any
    wide float in a lowered program is an upcast bug or a config leak)."""
    out = []
    for eqn, path in iter_eqns(jaxpr):
        for v in eqn.outvars:
            dt = str(getattr(v.aval, "dtype", ""))
            if dt in ("float64", "complex128"):
                out.append((eqn, v.aval, path))
    return out
