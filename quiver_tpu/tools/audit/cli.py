"""graftaudit CLI.

    python -m quiver_tpu.tools.audit [--json] [--sarif PATH] \
        [--select rules] [--ignore rules] [--targets names] \
        [--changed BASE] [--list-rules] [--list-targets] \
        [--mem-table [--mem-xla]]

Exit codes (stable, for CI — same contract as graftlint):
  0 — clean (waived findings are fine)
  1 — findings (including targets that fail to build)
  2 — usage error (unknown rule/family/target, bad --changed base)

The auditor traces and lowers programs but never executes them: it runs
on CPU with a forced 2-device host platform. Those env knobs must be set
BEFORE jax initializes its backend, so this module touches jax only
inside :func:`main` after pinning the environment (a no-op when the
process — e.g. pytest via conftest — already configured a mesh).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["main"]


def _pin_platform() -> None:
    if "jax" in sys.modules:
        # a host process (mega_session, a pytest run) may already have
        # chosen a backend; flipping jax_platforms after init would poison
        # its later work. Merely-imported jax (the image's sitecustomize
        # pulls it in at interpreter start) must still be pinned.
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m quiver_tpu.tools.audit",
        description="graftaudit — jaxpr/StableHLO-level program auditor: "
                    "collective parity, metric stripping, donation, dtype "
                    "discipline, constant bloat, the comm budget and the "
                    "graftmem memory family (peak-HBM, replication, VMEM, "
                    "padding), proven on lowered IR without executing a "
                    "step",
    )
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--select", default=None,
                   help="comma-separated rules/families to run "
                        "(default: all)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rules/families to skip")
    p.add_argument("--targets", default=None,
                   help="comma-separated registry targets to audit "
                        "(default: all)")
    p.add_argument("--changed", default=None, metavar="BASE",
                   help="audit only targets whose declared sources "
                        "changed vs the given git base")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="write a SARIF 2.1.0 report to PATH ('-' for "
                        "stdout) for CI annotation")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry (grouped by family) "
                        "and exit")
    p.add_argument("--list-targets", action="store_true",
                   help="print the audited program registry and exit")
    p.add_argument("--mem-table", action="store_true",
                   help="print the graftmem per-target budget table "
                        "(est peak / args / out / budget / headroom) "
                        "and exit")
    p.add_argument("--mem-xla", action="store_true",
                   help="with --mem-table: compile each target and join "
                        "XLA memory_analysis() peaks as a cross-check "
                        "column (the only compiling audit path)")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    from .rules import FAMILIES, family_of, rule_docs

    if args.list_rules:
        docs = rule_docs()
        for fam, rules in FAMILIES.items():
            print(f"[{fam}]")
            for name in rules:
                first = docs[name].splitlines()[0] if docs.get(name) else ""
                print(f"  {name}: {first}")
        return 0
    _pin_platform()
    from .audit_targets import REGISTRY
    from .runner import changed_files, run_audit

    if args.list_targets:
        for name, t in REGISTRY.items():
            print(f"{name}: {t.doc}")
            print(f"    sources: {', '.join(t.sources)}")
            for rule, reason in sorted(t.waivers.items()):
                print(f"    waiver[{rule}]: {reason}")
        return 0
    split = (lambda s: [r.strip() for r in s.split(",") if r.strip()])
    if args.mem_table:
        from .mem import format_peak_table, peak_table

        names = split(args.targets) if args.targets else None
        rows = peak_table(names, with_xla=args.mem_xla)
        print(format_peak_table(rows))
        over = [r for r in rows
                if r["hbm_budget"] is None
                or (r["headroom_bytes"] is not None
                    and r["headroom_bytes"] < 0)]
        return 1 if over else 0
    try:
        changed = None
        if args.changed is not None:
            changed = changed_files(args.changed)
        result = run_audit(
            select=split(args.select) if args.select else None,
            ignore=split(args.ignore) if args.ignore else None,
            targets=split(args.targets) if args.targets else None,
            changed=changed,
        )
    except ValueError as e:
        print(f"graftaudit: error: {e}", file=sys.stderr)
        return 2
    if args.sarif:
        from ..sarif import build_sarif_doc

        doc = json.dumps(build_sarif_doc(
            "graftaudit", rule_docs(), family_of,
            result.findings, result.suppressed,
        ), indent=1)
        if args.sarif == "-":
            print(doc)
        else:
            with open(args.sarif, "w", encoding="utf-8") as fh:
                fh.write(doc + "\n")
    if args.as_json:
        print(json.dumps(result.to_dict(), indent=1))
        return result.exit_code
    for f in result.findings:
        print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule}: "
              f"[{family_of(f.rule)}] {f.message}")
    changed_note = ""
    if changed is not None:
        changed_note = f" [--changed: {len(changed)} changed file(s)]"
    print(
        f"graftaudit: {len(result.findings)} finding(s) "
        f"({len(result.suppressed)} waived) across "
        f"{len(result.targets)} program(s){changed_note}"
    )
    return result.exit_code
