"""graftaudit — static analysis over *lowered* jax programs.

graftlint (``tools/lint``) reads source; graftaudit reads what XLA will
actually run. It AOT-traces the registry of canonical programs
(``audit_targets.py`` — routed gather, tiered lookup, sample hop, epoch
bodies, serve ladder, metrics pair, Pallas kernels) under
``JAX_PLATFORMS=cpu`` with no device execution, then walks the
jaxpr/StableHLO to machine-check the repo's compiled-program invariants,
one rule family per established discipline:

* ``collective-parity`` — cond branches share one collective schedule,
  or the predicate is reduced over the branches' axes (PR 1/3).
* ``metrics-strip`` — ``collect_metrics=False`` strips exactly the
  declared metric reductions and nothing else moves (PR 5).
* ``donation-audit`` — programs donate exactly the buffers they claim;
  unusable-donation warnings are findings (PR 11/12).
* ``dtype-discipline`` — no f64 leakage; int8 codes ride the routed
  all_to_all un-upcast (PR 4).
* ``constant-bloat`` — no large closure-folded constants (PR 11).
* ``comm-budget`` — lowered epoch all_to_all lanes ==
  ``control/cost.routed_lanes_per_hop`` exactly (PR 6/8).

The graftmem family (``tools/audit/mem.py``) extends the same registry
from comm invariants to memory/layout invariants — still proven on the
lowered IR, never by executing:

* ``peak-hbm-budget`` — donation-aware liveness walk computes each
  target's per-device peak bytes under the audit-mesh shardings and
  gates it against the registry-declared ``hbm_budget``; an unpriced
  target is itself a finding.
* ``no-silent-replication`` — an intermediate that degenerates to full
  replication along the feature axis (the all_gather cliff the routed
  path exists to avoid), attributed to its producing op.
* ``vmem-budget`` — static VMEM/scratch accounting of every Pallas
  kernel's resident blocks vs the ~16 MiB per-core budget.
* ``padding-waste`` — lanes-vs-payload ratio per routed all_to_all;
  over-provisioned bucket caps ship padding bought with real HBM.

CLI: ``python -m quiver_tpu.tools.audit`` (``--json``, ``--sarif PATH``,
``--select``/``--ignore`` rules or families, ``--targets``,
``--changed BASE``, ``--list-rules``, ``--list-targets``,
``--mem-table`` [``--mem-xla``]; exit 0 clean / 1 findings / 2 usage). Waivers are registry-side: a ``Target``
declaration carries its reasoned exemptions, since an IR finding has no
source line for an inline comment.

This module imports no jax at import time, so the CLI can pin
``XLA_FLAGS``/``JAX_PLATFORMS`` before the backend initializes; builders
import jax lazily when a target is traced.
"""

from .audit_targets import REGISTRY, Built, Target, build, build_from
from .cli import main
from .mem import estimate_peak, peak_table
from .rules import FAMILIES, RULES, family_of, rule_docs
from .runner import AuditResult, changed_files, run_audit, select_targets

__all__ = [
    "AuditResult",
    "Built",
    "FAMILIES",
    "REGISTRY",
    "RULES",
    "Target",
    "build",
    "build_from",
    "changed_files",
    "estimate_peak",
    "family_of",
    "main",
    "peak_table",
    "rule_docs",
    "run_audit",
    "select_targets",
]
