"""graftmem — static per-device memory & layout accounting over lowered
programs.

The comm rule families prove what a program *moves*; this module proves
what it *holds*. Everything operates on the same traced artifacts the
rest of graftaudit walks (a ``ClosedJaxpr`` + the lowered StableHLO
text) — nothing executes, and the only compile anywhere is the optional
XLA cross-check (:func:`xla_memory_stats`), which the CI memory-audit
job and the slow-lane tolerance test run, not the rules.

The accounting model, calibrated against XLA ``memory_analysis()`` on
the 2-device CPU audit mesh:

* **per-device bytes** — a top-level operand counts its global aval
  bytes divided by the product of the mesh-axis sizes its consuming
  ``shard_map`` partitions it over (:func:`arg_divisors` propagates the
  divisor through ``pjit``/``scan``/``while``/``cond`` wrappers; inside a
  shard_map body shapes are already per-device local). Argument and
  output byte totals reproduce XLA's ``argument_size_in_bytes`` /
  ``output_size_in_bytes`` exactly on the simple registry targets (the
  exact-match list lives in tests/test_memaudit.py); multi-output
  programs carry an 8-byte tuple-table entry per output
  (:data:`OUT_TUPLE_ENTRY_BYTES`).
* **peak** — a liveness walk over the eqns: a buffer is born at its
  defining eqn (or entry, for args/consts) and dies after its last use;
  the peak is the largest live set at any program point. A sub-program
  eqn contributes ``max(0, inner_peak - inner_operand_bytes)`` on top of
  the outer live set (XLA reuses the operand buffers across the call
  boundary). ``pallas_call`` is special-cased: its kernel works out of
  VMEM/SMEM blocks (counted by :func:`vmem_usages`), so its HBM
  contribution is its operands/results, not the interpret-mode body.
* **donation** — args the lowering aliased to outputs
  (``tf.aliasing_output`` / ``jax.buffer_donor``, via
  :func:`~quiver_tpu.tools.audit.ir.main_arg_attrs`) are discounted from
  the peak: XLA writes the output into the donated buffer.

The estimate is a fusion-blind upper-shape of the true footprint (XLA
fuses intermediates away, and pads/aligns small buffers up), so it
tracks — not equals — the compiled number; the stated agreement band
lives with the slow-lane test. Budgets (``meta["hbm_budget"]``) gate the
*estimate*, which keeps the rule trace-only and regression-sensitive:
a program that doubles its lowered footprint doubles its estimate.
"""

from __future__ import annotations

import dataclasses
import math
import re

import numpy as np

from . import ir

__all__ = [
    "DEFAULT_VMEM_BUDGET",
    "OUT_TUPLE_ENTRY_BYTES",
    "PADDING_WASTE_LIMIT",
    "REPLICATION_BYTES_LIMIT",
    "MemoryEstimate",
    "VmemUsage",
    "arg_divisors",
    "aval_bytes",
    "estimate_peak",
    "feature_replications",
    "out_divisors",
    "padding_waste",
    "peak_table",
    "vmem_usages",
    "xla_memory_stats",
]

# TPU VMEM is ~16 MB/core; a Pallas kernel whose resident blocks+scratch
# exceed it cannot schedule. Targets override via meta["vmem_budget"].
DEFAULT_VMEM_BUDGET = 16 * 1024 * 1024

# XLA's tuple result table: one pointer entry per output buffer when a
# program returns more than one (single-output programs return the
# buffer bare) — measured against memory_analysis() on the audit mesh.
OUT_TUPLE_ENTRY_BYTES = 8

# a feature-axis-replicated intermediate below this is noise (scalars,
# overflow flags); above it, replication is a real F-times memory cliff.
# Targets override via meta["replication_bytes_limit"].
REPLICATION_BYTES_LIMIT = 1 << 10

# padded all_to_all lanes above this fraction of the shipped buckets are
# a finding: alpha=2 (the default routed budget) sits at 0.5 waste by
# construction, so the default threshold clears it with margin while
# catching runaway caps. Targets override via meta["padding_waste_limit"].
PADDING_WASTE_LIMIT = 0.6


def _itemsize(dt) -> int:
    try:
        return np.dtype(dt).itemsize
    except TypeError:
        # extended dtypes (PRNG key arrays): jax exposes no numpy dtype;
        # a threefry key is 2 x uint32
        return int(getattr(dt, "itemsize", 8))


def _unwrap(obj):
    """ClosedJaxpr/Jaxpr/param-wrapped program -> the raw Jaxpr."""
    j = ir._jaxpr_of(obj)
    if j is not None and not hasattr(j, "invars"):
        j = j.jaxpr
    return j


def aval_bytes(aval, divisor: int = 1) -> int:
    """Per-device bytes of one abstract value under a sharding divisor
    (ceil division: an uneven shard still allocates the padded block)."""
    shape = getattr(aval, "shape", None)
    dt = getattr(aval, "dtype", None)
    if dt is None or shape is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return int(math.ceil(n * _itemsize(dt) / max(int(divisor), 1)))


def _shard_div(names_entry, mesh) -> int:
    """shard_map in/out_names entry ({dim: (axis, ...)}) -> the product
    of partitioned mesh-axis sizes, i.e. the per-device byte divisor."""
    div = 1
    for axes in names_entry.values():
        for ax in axes:
            div *= int(mesh.shape[ax])
    return div


def _operand_pairs(eqn):
    """``[(inner_jaxpr, [(outer_var, inner_var), ...])]`` for sub-program
    eqns whose operand positions correspond shape-for-shape: pjit/cond
    map every operand, scan maps consts+carry (xs are sliced inside),
    while maps the body's consts+carry."""
    prim = eqn.primitive.name
    out = []
    if prim in ("pjit", "closed_call", "core_call") or \
            prim.startswith("custom_"):
        sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        sj = _unwrap(sub)
        if sj is not None and len(sj.invars) == len(eqn.invars):
            out.append((sj, list(zip(eqn.invars, sj.invars))))
    elif prim == "cond":
        ops = eqn.invars[1:]
        for br in eqn.params.get("branches", ()):
            sj = _unwrap(br)
            if sj is not None and len(sj.invars) == len(ops):
                out.append((sj, list(zip(ops, sj.invars))))
    elif prim == "scan":
        sj = _unwrap(eqn.params.get("jaxpr"))
        if sj is not None:
            n = int(eqn.params.get("num_consts", 0)) + int(
                eqn.params.get("num_carry", 0))
            out.append((sj, list(zip(eqn.invars[:n], sj.invars[:n]))))
    elif prim == "while":
        sj = _unwrap(eqn.params.get("body_jaxpr"))
        cn = int(eqn.params.get("cond_nconsts", 0))
        if sj is not None:
            ops = eqn.invars[cn:]
            if len(sj.invars) == len(ops):
                out.append((sj, list(zip(ops, sj.invars))))
    return out


def _names_divisors(jaxpr, select):
    """Shared engine of :func:`arg_divisors` / :func:`out_divisors`:
    chase the given top-level vars through operand-pairing wrappers to
    the shard_map that names their sharding. ``select(eqn)`` returns the
    ``(vars, names, mesh)`` triple to read at a shard_map eqn."""
    j = _unwrap(jaxpr)
    divs: dict = {}
    if j is None:
        return divs

    def _scan(jx, lift):
        for eqn in jx.eqns:
            if eqn.primitive.name == "shard_map":
                evars, names, mesh = select(eqn)
                for v, nm in zip(evars, names):
                    if hasattr(v, "val"):
                        continue
                    key = lift.get(id(v))
                    if key is not None:
                        divs.setdefault(key, _shard_div(nm, mesh))
            else:
                for sj, opairs in _operand_pairs(eqn):
                    inner = {}
                    for ov, iv in opairs:
                        if not hasattr(ov, "val") and id(ov) in lift:
                            inner[id(iv)] = lift[id(ov)]
                    if inner:
                        _scan(sj, inner)

    _scan(j, {id(v): id(v) for v in j.invars})
    return divs


def arg_divisors(jaxpr) -> dict:
    """``{id(top_level_invar): divisor}`` — the per-device byte divisor
    each argument's consuming shard_map declares for it, propagated
    through pjit/scan/while/cond wrappers. Args no shard_map consumes
    (replicated operands) are absent — divisor 1."""
    return _names_divisors(
        jaxpr,
        lambda eqn: (eqn.invars, eqn.params["in_names"],
                     eqn.params["mesh"]),
    )


def out_divisors(jaxpr) -> dict:
    """``{id(top_level_outvar): divisor}`` via shard_map ``out_names``,
    propagated through pjit outvar positions."""
    j = _unwrap(jaxpr)
    divs: dict = {}
    if j is None:
        return divs

    def _scan(jx, lift):
        for eqn in jx.eqns:
            if eqn.primitive.name == "shard_map":
                mesh = eqn.params["mesh"]
                for v, nm in zip(eqn.outvars, eqn.params["out_names"]):
                    key = lift.get(id(v))
                    if key is not None:
                        divs.setdefault(key, _shard_div(nm, mesh))
            elif eqn.primitive.name == "pjit":
                sj = _unwrap(eqn.params["jaxpr"])
                if sj is not None and \
                        len(sj.outvars) == len(eqn.outvars):
                    inner = {}
                    for ov, iv in zip(eqn.outvars, sj.outvars):
                        if id(ov) in lift and not hasattr(iv, "val"):
                            inner[id(iv)] = lift[id(ov)]
                    if inner:
                        _scan(sj, inner)

    _scan(j, {id(v): id(v) for v in j.outvars if not hasattr(v, "val")})
    return divs


_TENSOR_RE = re.compile(r"tensor<([0-9x]*?)x?([a-z]+[0-9]*)>")
_DEVICES_RE = re.compile(r"devices=\[([0-9,]+)\]")

_MLIR_ITEMSIZE = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1,
}


def _mlir_arg_bytes(arg_text: str) -> int:
    """Per-device bytes of one lowered ``@main`` argument, from its
    MLIR text: the ``tensor<...>`` type (global shape) divided by the
    device product of any ``mhlo.sharding`` attr on the arg."""
    m = _TENSOR_RE.search(arg_text)
    if m is None:
        return 0
    dims, dt = m.group(1), m.group(2)
    n = 1
    for d in dims.split("x"):
        if d:
            n *= int(d)
    nbytes = n * _MLIR_ITEMSIZE.get(dt, 8)
    dm = _DEVICES_RE.search(arg_text)
    if dm is not None:
        div = 1
        for d in dm.group(1).split(","):
            div *= int(d)
        nbytes = int(math.ceil(nbytes / max(div, 1)))
    return nbytes


def _donated_bytes(mlir_text: str) -> int:
    """Per-device bytes of every ``@main`` argument the lowering donated
    (``tf.aliasing_output`` / ``jax.buffer_donor``), read straight off
    the MLIR arg text — the jaxpr's invars can NOT be zipped against the
    lowered args (``keep_unused=False`` prunes dead operands), and the
    arg text carries both the type and the sharding in one place.
    Matches XLA's ``alias_size_in_bytes`` on the donating targets."""
    m = ir._MAIN_RE.search(mlir_text)
    if m is None:
        return 0
    total = 0
    for arg in ir._split_top_level(m.group(1)):
        if "tf.aliasing_output" in arg or "jax.buffer_donor" in arg:
            total += _mlir_arg_bytes(arg)
    return total


@dataclasses.dataclass(frozen=True)
class MemoryEstimate:
    """Per-device static memory model of one lowered program."""

    peak_bytes: int  # liveness-walk peak, donation-discounted
    arg_bytes: int  # argument footprint (matches XLA on exact targets)
    out_bytes: int  # output footprint incl. the tuple-table entries
    aliased_bytes: int  # donated-arg bytes discounted from the peak
    n_args: int
    n_outputs: int


def _kernel_block_bytes(eqn) -> int:
    """HBM-side stand-in for a pallas_call body: the VMEM/SMEM-resident
    blocks (the kernel's working set — everything else it touches stays
    in place as the call's operands/results)."""
    kj = _unwrap(eqn.params.get("jaxpr"))
    total = 0
    if kj is None:
        return 0
    for kv in kj.invars:
        ms = str(getattr(kv.aval, "memory_space", ""))
        if "vmem" in ms or "smem" in ms:
            total += aval_bytes(kv.aval)
    return total


def _walk_peak(jaxpr, div_in=None) -> int:
    """The liveness walk: peak live bytes over one jaxpr's program
    points, recursing into sub-programs (see module docstring)."""
    j = _unwrap(jaxpr)
    if j is None:
        return 0
    divs: dict = {}
    if div_in is None:
        div_in = [1] * len(j.invars)
    for v, d in zip(j.invars, div_in):
        divs[id(v)] = d

    def b(v):
        if hasattr(v, "val"):  # literal
            return 0
        return aval_bytes(v.aval, divs.get(id(v), 1))

    last_use: dict = {}
    for i, eqn in enumerate(j.eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):
                last_use[id(v)] = i
    for v in j.outvars:
        if not hasattr(v, "val"):
            last_use[id(v)] = len(j.eqns)

    live = {id(v): b(v) for v in list(j.invars) + list(j.constvars)}
    peak = sum(live.values())

    for i, eqn in enumerate(j.eqns):
        prim = eqn.primitive.name
        inner_extra = 0
        out_div = [1] * len(eqn.outvars)
        if prim == "pallas_call":
            inner_extra = _kernel_block_bytes(eqn)
        elif prim == "shard_map":
            mesh = eqn.params["mesh"]
            inner = eqn.params["jaxpr"]
            ij = _unwrap(inner)
            # body shapes are already per-device local -> divisor 1
            inner_peak = _walk_peak(inner, [1] * len(ij.invars))
            in_b = sum(
                aval_bytes(v.aval, _shard_div(nm, mesh))
                for v, nm in zip(eqn.invars, eqn.params["in_names"])
                if not hasattr(v, "val"))
            inner_extra = max(0, inner_peak - in_b)
            out_div = [_shard_div(nm, mesh)
                       for nm in eqn.params["out_names"]]
        else:
            pair_divs: dict = {}
            for sj_, opairs in _operand_pairs(eqn):
                for ov, iv in opairs:
                    if not hasattr(ov, "val"):
                        pair_divs[id(iv)] = divs.get(id(ov), 1)
            subpeaks = []
            for _k, _i, sub in ir._sub_jaxprs(eqn):
                sj = _unwrap(sub)
                din = [pair_divs.get(id(v), 1) for v in sj.invars]
                subpeaks.append(_walk_peak(sub, din))
            if subpeaks:
                in_b = sum(b(v) for v in eqn.invars)
                inner_extra = max(0, max(subpeaks) - in_b)
        for v, d in zip(eqn.outvars, out_div):
            divs[id(v)] = d
        out_b = sum(b(v) for v in eqn.outvars)
        peak = max(peak, sum(live.values()) + out_b + inner_extra)
        for v in eqn.outvars:
            live[id(v)] = b(v)
        for v in eqn.invars:
            if not hasattr(v, "val") and last_use.get(id(v)) == i:
                live.pop(id(v), None)
        peak = max(peak, sum(live.values()))
    return peak


def estimate_peak(closed_jaxpr, mlir: str | None = None) -> MemoryEstimate:
    """Static per-device memory model of a traced program: argument and
    output footprints under the audit mesh's shardings, plus the
    liveness-walk peak (donation-discounted when the lowered text is
    provided — an aliased arg's buffer is reused for its output)."""
    top = _unwrap(closed_jaxpr)
    if top is None:
        return MemoryEstimate(0, 0, 0, 0, 0, 0)
    adiv = arg_divisors(closed_jaxpr)
    odiv = out_divisors(closed_jaxpr)
    din = [adiv.get(id(v), 1) for v in top.invars]
    arg_bytes = sum(
        aval_bytes(v.aval, adiv.get(id(v), 1)) for v in top.invars
    )
    outs = [v for v in top.outvars if not hasattr(v, "val")]
    out_bytes = sum(aval_bytes(v.aval, odiv.get(id(v), 1)) for v in outs)
    if len(outs) > 1:
        out_bytes += OUT_TUPLE_ENTRY_BYTES * len(outs)
    peak = _walk_peak(closed_jaxpr, din)
    aliased = _donated_bytes(mlir) if mlir else 0
    return MemoryEstimate(
        peak_bytes=max(0, peak - aliased),
        arg_bytes=arg_bytes,
        out_bytes=out_bytes,
        aliased_bytes=aliased,
        n_args=len(top.invars),
        n_outputs=len(outs),
    )


# -- VMEM accounting ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class VmemUsage:
    """One Pallas kernel's static on-core footprint: every VMEM/SMEM
    memory-ref the kernel body binds (grid blocks + scratch buffers)."""

    name: str
    path: tuple
    vmem_bytes: int
    smem_bytes: int
    buffers: tuple  # ("vmem int32[8,128]", ...) for the finding message

    def __str__(self):
        loc = "/".join(self.path) or "top"
        return (f"{self.name} @ {loc}: vmem={self.vmem_bytes}B "
                f"smem={self.smem_bytes}B [{', '.join(self.buffers)}]")


def vmem_usages(closed_jaxpr) -> list:
    """Static VMEM/scratch accounting per ``pallas_call`` in a program.

    The kernel jaxpr's invars are memory-refs carrying their space
    (``vmem`` grid blocks and scratch, ``smem`` scalar prefetch, ``any``
    un-staged HBM tables, ``semaphore_mem`` DMA semaphores); the VMEM
    total is what must fit on-core simultaneously — window lanes, gather
    tiles and scratch all at once."""
    out = []
    for eqn, path in ir.iter_eqns(closed_jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        kj = _unwrap(eqn.params.get("jaxpr"))
        if kj is None:
            continue
        vmem = smem = 0
        bufs = []
        for kv in kj.invars:
            ms = str(getattr(kv.aval, "memory_space", ""))
            nb = aval_bytes(kv.aval)
            shape = tuple(getattr(kv.aval, "shape", ()))
            dt = getattr(kv.aval, "dtype", "?")
            if "vmem" in ms:
                vmem += nb
                bufs.append(f"vmem {dt}{list(shape)}")
            elif "smem" in ms:
                smem += nb
                bufs.append(f"smem {dt}{list(shape)}")
        name = getattr(eqn.params.get("name_and_src_info"), "name",
                       None) or "pallas_call"
        out.append(VmemUsage(name=str(name), path=path, vmem_bytes=vmem,
                             smem_bytes=smem, buffers=tuple(bufs)))
    return out


# -- replication detection ----------------------------------------------------

_GATHER_PRIMS = frozenset({"all_gather", "all_gather_invariant"})


def feature_replications(closed_jaxpr, axis: str = "feature",
                         limit: int = REPLICATION_BYTES_LIMIT) -> list:
    """Intermediates whose sharding degenerates to full replication
    along ``axis``: every gather-family collective over the axis whose
    result is at least ``limit`` bytes — the exact op that turns a
    "sharded" operand into an F-times-per-device buffer. Each entry
    carries a backward-slice attribution naming the producer of the
    gathered operand."""
    out = []

    def _walk(jx, path):
        j = _unwrap(jx)
        if j is None:
            return
        defmap = {}
        for eqn in j.eqns:
            for ov in eqn.outvars:
                defmap[id(ov)] = eqn
        for eqn in j.eqns:
            if eqn.primitive.name in _GATHER_PRIMS and \
                    axis in ir._axes_of(eqn):
                res = eqn.outvars[0].aval
                nbytes = aval_bytes(res)
                if nbytes >= int(limit):
                    op = eqn.invars[0]
                    src = defmap.get(id(op))
                    producer = (src.primitive.name if src is not None
                                else "a program input")
                    out.append({
                        "prim": eqn.primitive.name,
                        "path": path,
                        "axis": axis,
                        "shape": tuple(getattr(res, "shape", ())),
                        "dtype": str(getattr(res, "dtype", "?")),
                        "bytes": nbytes,
                        "producer": producer,
                    })
            for _k, i, sub in ir._sub_jaxprs(eqn):
                hop = (f"{eqn.primitive.name}[{i}]"
                       if eqn.primitive.name == "cond"
                       else eqn.primitive.name)
                _walk(sub, path + (hop,))

    _walk(closed_jaxpr, ())
    return out


# -- padding waste ------------------------------------------------------------


def padding_waste(built) -> list:
    """Lanes-vs-payload accounting per routed all_to_all of a target
    declaring a comm model (``meta["comm"]``): the shipped buckets are
    ``F * cap`` lanes, the real payload is ``local_len * (1 - h0)``
    requests, and the difference is bought with real HBM and wire bytes.
    Returns one entry per all_to_all with its waste fraction."""
    comm = built.meta.get("comm")
    if comm is None:
        return []
    F = int(comm["feature_shards"])
    L = int(comm["local_len"])
    h0 = float(comm.get("h0", 0.0))
    payload = L * (1.0 - h0)
    out = []
    for c in ir.collectives_of(built.jaxpr):
        if c.prim != "all_to_all" or len(c.shape) < 2:
            continue
        lanes = int(c.shape[0]) * int(c.shape[1])
        waste = 1.0 - min(payload / lanes, 1.0) if lanes else 0.0
        out.append({
            "collective": str(c),
            "cap": int(c.shape[1]),
            "lanes": lanes,
            "payload_lanes": payload,
            "waste": waste,
        })
    return out


# -- XLA cross-check + table --------------------------------------------------

_XLA_STATS: dict = {}


def xla_memory_stats(target) -> dict | None:
    """Compile one registry target on the audit mesh and return XLA's
    buffer-assignment totals (``memory_analysis()``), or None when the
    backend exposes none. This is the ONLY compiling entry point in the
    auditor — the rules never call it; the memory-audit CI job and the
    slow-lane tolerance test do."""
    name = getattr(target, "name", str(target))
    if name in _XLA_STATS:
        return _XLA_STATS[name]
    stats = None
    try:
        compiled = target.builder().lower().compile()
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = int(ma.argument_size_in_bytes)
            outb = int(ma.output_size_in_bytes)
            temp = int(ma.temp_size_in_bytes)
            alias = int(ma.alias_size_in_bytes)
            stats = {
                "argument_bytes": arg,
                "output_bytes": outb,
                "temp_bytes": temp,
                "alias_bytes": alias,
                "peak_bytes": arg + outb + temp - alias,
            }
    except Exception:  # noqa: BLE001 — cross-check is best-effort by contract
        stats = None
    _XLA_STATS[name] = stats
    return stats


def clear_xla_cache() -> None:
    _XLA_STATS.clear()


def peak_table(names=None, with_xla: bool = False) -> list:
    """Per-target memory rows for the CLI table, the memory-audit
    scoreboard job and ``CostModel.calibrate_hbm``: the static estimate,
    the declared budget and its headroom, optionally joined with the
    compiled XLA stats (``with_xla=True`` compiles every row)."""
    from .audit_targets import REGISTRY, build

    rows = []
    for name in (names or list(REGISTRY)):
        t = REGISTRY[name]
        built = build(name)
        est = estimate_peak(built.jaxpr, built.mlir)
        budget = built.meta.get("hbm_budget")
        row = {
            "target": name,
            "peak_bytes": est.peak_bytes,
            "arg_bytes": est.arg_bytes,
            "out_bytes": est.out_bytes,
            "aliased_bytes": est.aliased_bytes,
            "hbm_budget": None if budget is None else int(budget),
            "headroom_bytes": (None if budget is None
                               else int(budget) - est.peak_bytes),
        }
        if with_xla:
            stats = xla_memory_stats(t)
            row["xla_peak_bytes"] = (None if stats is None
                                     else stats["peak_bytes"])
            row["xla_ratio"] = (
                None if not stats or not stats["peak_bytes"]
                else round(est.peak_bytes / stats["peak_bytes"], 3))
        rows.append(row)
    return rows


def format_peak_table(rows) -> str:
    """Render :func:`peak_table` rows as the fixed-width budget table the
    memory-audit CI job prints into its log."""
    with_xla = any("xla_peak_bytes" in r for r in rows)
    head = (f"{'target':26s} {'est-peak':>10s} {'args':>8s} {'out':>7s} "
            f"{'budget':>8s} {'headroom':>9s}")
    if with_xla:
        head += f" {'xla-peak':>9s} {'ratio':>6s}"
    lines = [head]
    for r in rows:
        budget = r["hbm_budget"]
        line = (f"{r['target']:26s} {r['peak_bytes']:10d} "
                f"{r['arg_bytes']:8d} {r['out_bytes']:7d} "
                f"{'-' if budget is None else budget:>8} "
                f"{'-' if r['headroom_bytes'] is None else r['headroom_bytes']:>9}")
        if with_xla:
            xp = r.get("xla_peak_bytes")
            ratio = r.get("xla_ratio")
            line += (f" {'-' if xp is None else xp:>9}"
                     f" {'-' if ratio is None else ratio:>6}")
        lines.append(line)
    return "\n".join(lines)
