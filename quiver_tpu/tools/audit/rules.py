"""graftaudit rules — invariants checked on *lowered* programs.

Each rule is a function ``(target, built, registry_builds) -> [Finding]``
over one :class:`~quiver_tpu.tools.audit.audit_targets.Built` artifact
(``registry_builds`` resolves a paired target, e.g. the metrics on/off
differential). Findings reuse graftlint's :class:`Finding` shape so both
tools share SARIF plumbing; the path is the target's primary source file
(the program is lowered FROM it) and the message names the target.

Rule families and the PR whose discipline they machine-check:

* parity (collective-parity) — PR 1/3: psum-fallback conds keep both
  branches on one collective schedule, or reduce their predicate.
* metrics (metrics-strip) — PR 5: ``collect_metrics=False`` strips every
  metric collective from the compiled step.
* donation (donation-audit) — PR 11/12: programs donate the buffers they
  claim to, and nothing they don't.
* dtype (dtype-discipline) — PR 4: no f64 leakage; int8 tier codes ride
  the wire un-upcast.
* constants (constant-bloat) — PR 11: no large closure-folded arrays
  (an HBM + recompile hazard for AOT ladders).
* comm (comm-budget) — PR 6/8: the lowered epoch body's all_to_all lanes
  equal ``control/cost.routed_lanes_per_hop`` exactly.
* graftmem (hbm / replication / vmem / padding) — this PR: per-device
  memory & layout invariants from :mod:`.mem` — liveness-walk peak vs
  ``meta["hbm_budget"]``, feature-axis replication cliffs, Pallas VMEM
  block budgets, and padded-lane waste per routed all_to_all. Select all
  four at once with ``--select mem``.
"""

from __future__ import annotations

from collections import Counter

from ..lint.rules import Finding
from . import ir, mem

__all__ = ["FAMILIES", "RULES", "family_of", "rule_docs"]

# closure-folded constants above this ride every program version through
# the compile cache; targets can tighten/loosen via meta["const_bytes_limit"]
CONST_BYTES_LIMIT = 1 << 20


def _finding(rule, target, message) -> Finding:
    return Finding(rule=rule, path=target.sources[0], line=1, col=0,
                   message=f"[{target.name}] {message}")


def check_collective_parity(target, built, builds) -> list:
    """Both branches of every lowered ``lax.cond`` carry the same ordered
    multiset of collectives (prim/axes/shape/dtype), OR the predicate is
    provably axis-uniform (its backward slice passes through a reduction
    covering the branches' collective axes — the psum-fallback discipline
    from parallel/routing.py). Anything else can deadlock a mesh: members
    disagreeing on the predicate enter mismatched collective schedules."""
    out = []
    for cond_eqn, encl, path in ir.conds_of(built.jaxpr):
        per_branch = ir.branch_collectives(cond_eqn)
        sigs = [Counter(c.signature() for c in br) for br in per_branch]
        if all(s == sigs[0] for s in sigs):
            continue
        axes = set(a for br in per_branch for c in br for a in c.axes)
        if ir.predicate_axis_reduced(cond_eqn, encl, axes):
            continue
        loc = "/".join(path) or "top"
        detail = "; ".join(
            f"branch[{i}]: " + (", ".join(str(c) for c in br) or "none")
            for i, br in enumerate(per_branch)
        )
        out.append(_finding(
            "collective-parity", target,
            f"cond at {loc} has branch-divergent collectives and its "
            f"predicate is not reduced over {sorted(axes)} — {detail}",
        ))
    return out


def check_metrics_strip(target, built, builds) -> list:
    """The ``collect_metrics=False`` program must equal its metrics-on
    pair minus EXACTLY the declared metric reductions: identical
    all_to_all/all_gather schedules (telemetry must never reshape data
    movement), reductions(off) a sub-multiset of reductions(on), and the
    difference count == ``meta["expected_metric_reductions"]`` (update the
    declaration alongside obs/registry.py when a metric collective
    lands)."""
    pair = built.meta.get("metrics_pair")
    if pair is None:
        return []
    on = builds(pair)
    out = []
    off_cols = ir.collectives_of(built.jaxpr)
    on_cols = ir.collectives_of(on.jaxpr)

    def _split(cols):
        red = Counter(c.signature() for c in cols
                      if c.prim in ir.REDUCTIONS)
        moves = Counter(c.signature() for c in cols
                        if c.prim not in ir.REDUCTIONS)
        return red, moves

    off_red, off_moves = _split(off_cols)
    on_red, on_moves = _split(on_cols)
    if off_moves != on_moves:
        out.append(_finding(
            "metrics-strip", target,
            f"data-movement collectives differ from pair '{pair}': "
            f"off-only={dict(off_moves - on_moves)} "
            f"on-only={dict(on_moves - off_moves)}",
        ))
    extra_off = off_red - on_red
    if extra_off:
        out.append(_finding(
            "metrics-strip", target,
            f"reductions present with collect_metrics=False but absent in "
            f"'{pair}': {dict(extra_off)} — a metric psum survived the "
            "strip",
        ))
    expected = int(built.meta.get("expected_metric_reductions", 0))
    stripped = sum((on_red - off_red).values())
    if stripped != expected:
        out.append(_finding(
            "metrics-strip", target,
            f"metrics-on program carries {stripped} extra reduction(s) "
            f"over the stripped baseline, registry declares {expected}: "
            f"{dict(on_red - off_red)}",
        ))
    return out


def check_donation_audit(target, built, builds) -> list:
    """Programs donate exactly the buffers they claim. A target claiming
    donation (``meta['donated_leaves']``) must lower that many arguments
    with a donation attr (``tf.aliasing_output`` or ``jax.buffer_donor``)
    and emit zero unusable-donation warnings; a target claiming none must
    lower zero. Any captured donation warning is a finding — an unusable
    donation never lowers to an attr, saves nothing, and (donation
    consumes its argument on every backend) deletes a buffer the caller
    may still believe in."""
    out = []
    for w in built.donation_warnings:
        out.append(_finding(
            "donation-audit", target,
            f"build emitted a donation warning: {w.splitlines()[0]}",
        ))
    attrs = ir.main_arg_attrs(built.mlir)
    donated = sum(1 for a in attrs if a["aliased"] or a["donor"])
    claimed = int(built.meta.get("donated_leaves", 0))
    if donated != claimed:
        out.append(_finding(
            "donation-audit", target,
            f"{donated} argument(s) lower with donation attrs, registry "
            f"claims {claimed} (of {len(attrs)} args)",
        ))
    return out


def check_dtype_discipline(target, built, builds) -> list:
    """No f64/complex128 anywhere in a lowered program (the repo runs
    x64-disabled; a wide float means a config leak or a silent upcast),
    and on ``int8_path`` targets the routed all_to_all payload must carry
    int8 codes — dequantizing before the wire silently 4x-es hop bytes
    (feature/feature.py dequantizes AFTER the tier gathers by design)."""
    out = []
    for eqn, aval, path in ir.f64_eqns(built.jaxpr):
        loc = "/".join(path) or "top"
        out.append(_finding(
            "dtype-discipline", target,
            f"{eqn.primitive.name} at {loc} produces {aval.dtype}",
        ))
    if built.meta.get("int8_path"):
        a2a = [c for c in ir.collectives_of(built.jaxpr)
               if c.prim == "all_to_all"]
        if not any(c.dtype == "int8" for c in a2a):
            out.append(_finding(
                "dtype-discipline", target,
                "int8 tier path lowers no int8 all_to_all — codes were "
                f"upcast before routing (saw {sorted({c.dtype for c in a2a})})",
            ))
    return out


def check_constant_bloat(target, built, builds) -> list:
    """Arrays closure-folded into a program as constants above the size
    limit. Baked-in constants re-enter HBM per program version, defeat
    the AOT ladder's executable cache keying, and mark an operand that
    should have been an argument."""
    limit = int(built.meta.get("const_bytes_limit", CONST_BYTES_LIMIT))
    out = []
    for const, path in ir.iter_consts(built.jaxpr):
        nbytes = int(getattr(const, "nbytes", 0))
        if nbytes > limit:
            loc = "/".join(path) or "top"
            shape = getattr(const, "shape", ())
            dtype = getattr(const, "dtype", "?")
            out.append(_finding(
                "constant-bloat", target,
                f"closure-folded constant {dtype}{list(shape)} "
                f"({nbytes} bytes > {limit}) at {loc}",
            ))
    return out


def check_comm_budget(target, built, builds) -> list:
    """The lowered epoch body's routed all_to_all lanes reconcile with
    ``control/cost.routed_lanes_per_hop`` EXACTLY: the ids hop is
    ``int(F, cap)``, the payload hop ``(F, cap, feature_dim)``, and
    ``F * cap == lanes_per_hop`` for the registry-declared
    ``(local_len, F, alpha)``. Turns the scoreboard's analytic comm model
    from a claim into a checked contract on the IR."""
    comm = built.meta.get("comm")
    if comm is None:
        return []
    from ...control.cost import routed_lanes_per_hop

    F = int(comm["feature_shards"])
    model = routed_lanes_per_hop(int(comm["local_len"]), F,
                                 float(comm["alpha"]))
    cap, lanes = int(model["cap"]), int(model["lanes_per_hop"])
    a2a = [c for c in ir.collectives_of(built.jaxpr)
           if c.prim == "all_to_all"]
    out = []
    if not a2a:
        out.append(_finding(
            "comm-budget", target,
            "no all_to_all lowered in an epoch body declaring a comm "
            "budget — the routed gather fell off the a2a path",
        ))
    for c in a2a:
        ok_ids = (len(c.shape) == 2 and c.dtype.startswith("int")
                  and tuple(c.shape) == (F, cap))
        ok_payload = (len(c.shape) == 3
                      and tuple(c.shape) == (F, cap,
                                             int(comm["feature_dim"])))
        if not (ok_ids or ok_payload):
            out.append(_finding(
                "comm-budget", target,
                f"{c} does not match the comm model (expect ids "
                f"int[{F}, {cap}] or payload [{F}, {cap}, "
                f"{comm['feature_dim']}] for alpha={comm['alpha']}, "
                f"local_len={comm['local_len']})",
            ))
        elif c.lanes != lanes:
            out.append(_finding(
                "comm-budget", target,
                f"{c} moves {c.lanes} lanes/hop, model says {lanes}",
            ))
    return out


def check_peak_hbm_budget(target, built, builds) -> list:
    """Every target's liveness-walk peak (per-device bytes under the
    audit mesh's shardings, donation-discounted — see
    :func:`~quiver_tpu.tools.audit.mem.estimate_peak`) fits its declared
    ``meta["hbm_budget"]``; a target declaring NO budget is itself a
    finding, so new programs enter the registry priced. Regressions fail
    this audit on the lowered IR, not a TPU run."""
    est = mem.estimate_peak(built.jaxpr, built.mlir)
    budget = built.meta.get("hbm_budget")
    if budget is None:
        return [_finding(
            "peak-hbm-budget", target,
            f"no meta['hbm_budget'] declared (estimated per-device peak "
            f"is {est.peak_bytes} bytes) — every registry program must "
            "enter priced",
        )]
    if est.peak_bytes > int(budget):
        return [_finding(
            "peak-hbm-budget", target,
            f"estimated per-device peak {est.peak_bytes} bytes exceeds "
            f"the declared hbm_budget of {int(budget)} (args="
            f"{est.arg_bytes}, out={est.out_bytes}, donation discount="
            f"{est.aliased_bytes})",
        )]
    return []


def check_no_silent_replication(target, built, builds) -> list:
    """No intermediate silently degenerates to full replication along
    the feature axis: a gather-family collective over ``feature`` whose
    result crosses ``meta["replication_bytes_limit"]`` (default
    :data:`~quiver_tpu.tools.audit.mem.REPLICATION_BYTES_LIMIT`) is the
    exact op that makes a "sharded" operand cost F× memory per device.
    The finding names the producer of the gathered operand (backward
    slice) so the fix starts at the source op, not the symptom."""
    limit = int(built.meta.get("replication_bytes_limit",
                               mem.REPLICATION_BYTES_LIMIT))
    out = []
    for rep in mem.feature_replications(built.jaxpr, limit=limit):
        loc = "/".join(rep["path"]) or "top"
        out.append(_finding(
            "no-silent-replication", target,
            f"{rep['prim']} over '{rep['axis']}' at {loc} replicates "
            f"{rep['dtype']}{list(rep['shape'])} ({rep['bytes']} bytes "
            f">= {limit}) onto every device — gathered operand produced "
            f"by {rep['producer']}",
        ))
    return out


def check_vmem_budget(target, built, builds) -> list:
    """Every Pallas kernel's simultaneously-resident VMEM blocks +
    scratch (window lanes, gather tiles — the memory-refs its body
    binds) fit the per-core budget (``meta["vmem_budget"]``, default
    :data:`~quiver_tpu.tools.audit.mem.DEFAULT_VMEM_BUDGET` ≈ one TPU
    core's VMEM). Machine-checks the megakernel's window sizing instead
    of comment-checking it."""
    budget = int(built.meta.get("vmem_budget", mem.DEFAULT_VMEM_BUDGET))
    out = []
    for u in mem.vmem_usages(built.jaxpr):
        if u.vmem_bytes + u.smem_bytes > budget:
            out.append(_finding(
                "vmem-budget", target,
                f"{u} exceeds the per-core VMEM budget of {budget} bytes",
            ))
    return out


def check_padding_waste(target, built, builds) -> list:
    """Padded all_to_all lanes are bought with real HBM and wire bytes:
    on targets declaring a comm model, each routed hop's waste fraction
    (1 - payload/lanes, payload = ``local_len * (1 - h0)``) must stay
    under ``meta["padding_waste_limit"]`` (default
    :data:`~quiver_tpu.tools.audit.mem.PADDING_WASTE_LIMIT`; the alpha=2
    routed budget sits at 0.5 by construction). Catches runaway caps
    that comm-budget's exact-lane check would only see after the
    registry declaration itself drifted."""
    limit = float(built.meta.get("padding_waste_limit",
                                 mem.PADDING_WASTE_LIMIT))
    out = []
    for w in mem.padding_waste(built):
        if w["waste"] > limit:
            out.append(_finding(
                "padding-waste", target,
                f"{w['collective']} ships {w['lanes']} lanes for "
                f"{w['payload_lanes']:g} payload lanes — waste "
                f"{w['waste']:.3f} > {limit:g} (cap {w['cap']} is "
                "over-provisioned for the declared route)",
            ))
    return out


RULES = {
    "collective-parity": check_collective_parity,
    "metrics-strip": check_metrics_strip,
    "donation-audit": check_donation_audit,
    "dtype-discipline": check_dtype_discipline,
    "constant-bloat": check_constant_bloat,
    "comm-budget": check_comm_budget,
    "peak-hbm-budget": check_peak_hbm_budget,
    "no-silent-replication": check_no_silent_replication,
    "vmem-budget": check_vmem_budget,
    "padding-waste": check_padding_waste,
}

FAMILIES = {
    "parity": ("collective-parity",),
    "metrics": ("metrics-strip",),
    "donation": ("donation-audit",),
    "dtype": ("dtype-discipline",),
    "constants": ("constant-bloat",),
    "comm": ("comm-budget",),
    "hbm": ("peak-hbm-budget",),
    "replication": ("no-silent-replication",),
    "vmem": ("vmem-budget",),
    "padding": ("padding-waste",),
    # umbrella: the whole graftmem family behind one --select handle.
    # Keep LAST so family_of resolves each rule to its specific family.
    "mem": ("peak-hbm-budget", "no-silent-replication", "vmem-budget",
            "padding-waste"),
}

META_RULES = ("audit-error",)


def family_of(rule: str) -> str:
    for fam, rules in FAMILIES.items():
        if rule in rules:
            return fam
    return "meta"


def rule_docs() -> dict:
    docs = {name: (fn.__doc__ or "").strip() for name, fn in RULES.items()}
    docs["audit-error"] = ("a registered target failed to trace/lower — "
                           "the program the invariant lives on no longer "
                           "builds")
    return docs
