"""graftaudit runner: target selection, rule orchestration, waivers.

Mirrors the graftlint runner's contract (result object with stable
``exit_code``, sorted findings, reasoned suppressions) at the registry
level: waivers live on :class:`Target` declarations — an IR finding has
no source line to hang an inline comment on, so the registry entry that
*stakes* the invariant is also where a reasoned exemption must be
written down.
"""

from __future__ import annotations

import dataclasses
import subprocess

from ..lint.rules import Finding
from .audit_targets import REGISTRY, build
from .rules import FAMILIES, META_RULES, RULES

__all__ = ["AuditResult", "changed_files", "run_audit", "select_targets"]


@dataclasses.dataclass
class AuditResult:
    findings: list  # active, sorted
    suppressed: list  # waived, sorted
    targets: list  # target names audited
    waivers: list  # (target, rule, reason) for every waiver consulted

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_dict(self):
        return {
            "version": 1,
            "targets_audited": self.targets,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "counts": _counts(self.findings),
            "waivers": [
                {"target": t, "rule": r, "reason": why}
                for t, r, why in self.waivers
            ],
        }


def _counts(findings):
    out: dict = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return dict(sorted(out.items()))


def changed_files(base: str) -> set:
    """Repo-relative paths changed vs a git base (mirrors graftlint's
    ``--changed``)."""
    try:
        txt = subprocess.run(
            ["git", "diff", "--name-only", base],
            capture_output=True, text=True, check=True,
        ).stdout
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        raise ValueError(f"cannot diff against base {base!r}: {e}")
    return {line.strip() for line in txt.splitlines() if line.strip()}


def select_targets(names=None, changed=None) -> list:
    """Resolve the target set: explicit names, else ``--changed`` scoping
    (targets whose declared sources intersect the diff — an edit under
    ``quiver_tpu/tools/audit/`` or ``tools/sarif.py`` re-runs everything,
    the auditor itself changed), else all."""
    if names:
        unknown = [n for n in names if n not in REGISTRY]
        if unknown:
            raise ValueError(
                f"unknown target(s): {', '.join(unknown)} "
                f"(see --list-targets)"
            )
        return list(names)
    if changed is not None:
        if any(p.startswith("quiver_tpu/tools/audit/")
               or p == "quiver_tpu/tools/sarif.py" for p in changed):
            return list(REGISTRY)
        return [
            name for name, t in REGISTRY.items()
            if changed.intersection(t.sources)
        ]
    return list(REGISTRY)


def _expand(names, what) -> set:
    out: set = set()
    for n in names:
        if n in FAMILIES:
            out.update(FAMILIES[n])
        elif n in RULES or n in META_RULES:
            out.add(n)
        else:
            raise ValueError(f"unknown {what} rule/family: {n!r}")
    return out


def run_audit(select=None, ignore=None, targets=None,
              changed=None) -> AuditResult:
    """Build every selected target once, run every selected rule over
    each artifact; registry waivers demote matching findings to
    suppressed. A target that fails to trace/lower is itself a finding
    (``audit-error``) — the invariant's program no longer builds."""
    active = set(RULES)
    if select is not None:
        active = _expand(select, "--select")
    if ignore is not None:
        active -= _expand(ignore, "--ignore")
    names = select_targets(targets, changed)

    findings: list = []
    suppressed: list = []
    waivers: list = []
    for name in names:
        t = REGISTRY[name]
        for rule, reason in sorted(t.waivers.items()):
            waivers.append((name, rule, reason))
        try:
            built = build(name)
        except Exception as e:  # noqa: BLE001 — any build failure is the finding
            if "audit-error" in active or select is None:
                findings.append(Finding(
                    rule="audit-error", path=t.sources[0], line=1, col=0,
                    message=f"[{name}] target failed to build: "
                            f"{type(e).__name__}: {e}",
                ))
            continue
        for rule in sorted(active & set(RULES)):
            for f in RULES[rule](t, built, build):
                if rule in t.waivers:
                    f.suppressed = True
                    suppressed.append(f)
                else:
                    findings.append(f)
    findings.sort(key=lambda f: f.sort_key())
    suppressed.sort(key=lambda f: f.sort_key())
    return AuditResult(findings=findings, suppressed=suppressed,
                       targets=names, waivers=waivers)
