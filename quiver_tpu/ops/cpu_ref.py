"""Numpy reference sampler — the correctness oracle and CPU fallback.

Capability parity with the reference's CPU tier (torch-quiver quiver.cpp:10-114
``CPUQuiver`` over quiver.cpu.hpp:27-73): serial per-seed reservoir sampling
(``std::sample`` equivalent via numpy choice without replacement) plus a
hash-map reindex (``reindex_group``, quiver.cpp:39-84). Every JAX/Pallas
kernel is differentially tested against this module, mirroring how the
reference's CPU sampler anchors its CI (SURVEY §4).

Outputs use the same padded (S, K) / -1-sentinel contract as the device ops.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "sample_layer_ref",
    "weighted_sample_ref",
    "reindex_layer_ref",
    "multilayer_ref",
]


def sample_layer_ref(indptr, indices, seeds, k, rng=None):
    """Exact without-replacement uniform sampling, padded to (S, k)."""
    rng = rng or np.random.default_rng(0)
    S = len(seeds)
    out = np.full((S, k), -1, dtype=np.int64)
    counts = np.zeros(S, dtype=np.int64)
    for r, s in enumerate(seeds):
        if s < 0:
            continue
        lo, hi = int(indptr[s]), int(indptr[s + 1])
        deg = hi - lo
        if deg == 0:
            continue
        if deg <= k:
            out[r, :deg] = indices[lo:hi]
            counts[r] = deg
        else:
            pick = rng.choice(deg, size=k, replace=False)
            out[r, :k] = indices[lo + pick]
            counts[r] = k
    return out, counts


def weighted_sample_ref(indptr, indices, weights, seeds, k, rng=None):
    """Weight-proportional sampling oracle (reference ``weight_sample``
    semantics, cuda_random.cu.hpp:143-186: k independent inverse-CDF draws
    with replacement; copy-all when deg <= k). Padded to (S, k)."""
    rng = rng or np.random.default_rng(0)
    S = len(seeds)
    out = np.full((S, k), -1, dtype=np.int64)
    counts = np.zeros(S, dtype=np.int64)
    for r, s in enumerate(seeds):
        if s < 0:
            continue
        lo, hi = int(indptr[s]), int(indptr[s + 1])
        deg = hi - lo
        if deg == 0:
            continue
        if deg <= k:
            out[r, :deg] = indices[lo:hi]
            counts[r] = deg
            continue
        w = np.asarray(weights[lo:hi], dtype=np.float64)
        tot = w.sum()
        p = np.full(deg, 1.0 / deg) if tot <= 0 else w / tot
        pick = rng.choice(deg, size=k, replace=True, p=p)
        out[r, :k] = indices[lo + pick]
        counts[r] = k
    return out, counts


def reindex_layer_ref(seeds, neighbors):
    """First-occurrence-order unique of seeds then neighbors (hash-map style).

    Returns (frontier list, col_local (S,K) with -1 for invalid).
    """
    table: dict[int, int] = {}
    frontier: list[int] = []

    def lookup(v: int) -> int:
        if v not in table:
            table[v] = len(frontier)
            frontier.append(v)
        return table[v]

    for s in seeds:
        if s >= 0:
            lookup(int(s))
    col = np.full(neighbors.shape, -1, dtype=np.int64)
    for r in range(neighbors.shape[0]):
        for c in range(neighbors.shape[1]):
            v = int(neighbors[r, c])
            if v >= 0:
                col[r, c] = lookup(v)
    return np.asarray(frontier, dtype=np.int64), col


def multilayer_ref(indptr, indices, seeds, sizes, rng=None):
    """Multi-hop sample, returning per-layer (frontier, edge_index) innermost
    first — the un-reversed order; callers reverse for PyG parity."""
    rng = rng or np.random.default_rng(0)
    layers = []
    cur = np.asarray(seeds)
    for k in sizes:
        nbr, _ = sample_layer_ref(indptr, indices, cur, k, rng)
        frontier, col = reindex_layer_ref(cur, nbr)
        rows, cols = [], []
        for r in range(nbr.shape[0]):
            for c in range(nbr.shape[1]):
                if col[r, c] >= 0:
                    rows.append(r)
                    cols.append(col[r, c])
        edge_index = np.stack([np.asarray(cols), np.asarray(rows)]) if rows else np.zeros((2, 0), np.int64)
        layers.append((frontier, edge_index))
        cur = frontier
    return layers
