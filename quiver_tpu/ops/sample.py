"""Fixed-fanout neighbor sampling, XLA-native with static shapes.

TPU-native replacement for the reference's warp-per-row reservoir kernel
(torch-quiver cuda_random.cu.hpp:7-69 ``CSRRowWiseSampleKernel``) and its
driver (quiver_sample.cu:100-187). Design divergence from the reference
(SURVEY §7.1): outputs are padded ``(S, K)`` blocks with -1 sentinels instead
of ragged flat-list + counts, so everything jits.

Sampling scheme for ``deg > k`` (the reference uses per-warp curand
reservoir sampling): **stratified offsets + uniform random rotation**.
Split ``[0, deg)`` into k contiguous integer strata, pick one jittered point
per stratum, then rotate the whole set by ``r ~ U[0, deg)`` mod deg.
Properties:
  * the k offsets are distinct (strata are disjoint; rotation is a bijection),
  * every neighbor's inclusion probability is exactly ``k/deg`` (rotation
    symmetry), matching the reservoir's first-order marginals,
  * fully vectorized — no per-row loops, no atomics, no rejection.
Higher-order joint inclusion differs from true reservoir sampling (offsets
are negatively correlated within a row, which if anything *reduces* estimator
variance for mean aggregation).

For ``deg <= k`` all neighbors are taken, like the reference's copy-all branch
(cuda_random.cu.hpp:30-39).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "sample_layer",
    "stratified_offsets",
    "temporal_window_counts",
    "weighted_offsets",
    "staged_gather",
]


def stratified_offsets(key, deg, k: int):
    """k distinct offsets per row: one jittered pick per integer stratum.

    Returns (offsets (S, k) int32 in [0, max(deg,1)), sel_mask (S, k) with
    lane i valid iff i < min(deg, k)). For deg <= k the offsets are simply
    0..deg-1 (take-all, CSR order); for deg > k, stratum i covers
    [floor(deg*i/k), floor(deg*(i+1)/k)) and one uniform point is drawn per
    stratum — distinct by construction. Stratum boundaries are computed
    overflow-free in int32 via i*(deg//k) + floor(i*(deg%k)/k) (every
    intermediate <= deg), valid for k <= 46340.
    """
    S = deg.shape[0]
    i = jnp.arange(k, dtype=jnp.int32)[None, :]
    degc = deg[:, None]
    q, r_ = degc // k, degc % k
    lo = i * q + (i * r_) // k
    hi = (i + 1) * q + ((i + 1) * r_) // k
    span = jnp.maximum(hi - lo, 1)
    jitter = jax.random.randint(key, (S, k), 0, span, dtype=jnp.int32)
    off = jnp.where(degc <= k, jnp.minimum(i, jnp.maximum(degc - 1, 0)), lo + jitter)
    sel_mask = i < jnp.minimum(degc, k)
    return off, sel_mask


def rotate_offsets(key, offs, length, k: int):
    """Rotate per-row offsets by a uniform amount modulo ``length``.

    Makes the stratified picks' marginals exactly k/length (strata alone
    are non-uniform when length % k != 0). Take-all rows (length <= k)
    keep CSR order. Overflow-free: offs < length and rot < length, so one
    conditional subtract replaces the mod.
    """
    S = offs.shape[0]
    lenc = length[:, None]
    rot = jax.random.randint(key, (S, 1), 0, jnp.maximum(lenc, 1), dtype=jnp.int32)
    shifted = offs + rot
    rotated = jnp.where(shifted >= lenc, shifted - lenc, shifted)
    return jnp.where(lenc <= k, offs, rotated)


def _cdf_search(cum_weights, u, base, deg, iters: int):
    """Vectorized per-row inverse-CDF binary search.

    For each lane (s, j): smallest CSR slot m in row [base_s, base_s+deg_s)
    with cum_weights[m] >= u[s, j]. ``iters`` >= ceil(log2(max_degree+1))
    guarantees convergence. Returns row-local offsets (S, k) int32.
    """
    S, k = u.shape
    degc = deg[:, None].astype(base.dtype)
    basec = base[:, None]
    # arithmetic masking instead of jnp.where-with-literals: under
    # compute_on("device_host") every select_n operand must share the host
    # memory space, and broadcast scalar literals land in device space
    nonempty = degc > 0
    lo = jnp.broadcast_to(basec, (S, k))
    hi = lo + (degc - 1) * nonempty
    for _ in range(iters):
        mid = (lo + hi) // 2
        pm = cum_weights[mid * nonempty]
        go_right = pm < u
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    return (lo - basec).astype(jnp.int32)


def _cdf_search_host(cum_weights, u, base, deg, iters: int):
    """_cdf_search staged as host compute (HOST mode keeps the prefix array
    in pinned host memory; only the small u/base/deg blocks transit — the
    same memory-space dance as _staged_gather)."""
    from jax.experimental.compute_on import compute_on
    from jax.memory import Space

    u_h = jax.device_put(u, Space.Host)
    base_h = jax.device_put(base, Space.Host)
    deg_h = jax.device_put(deg, Space.Host)

    @compute_on("device_host")
    def search(cw, uu, bb, dd):
        return _cdf_search(cw, uu, bb, dd, iters)

    return jax.device_put(search(cum_weights, u_h, base_h, deg_h), Space.Device)




def weighted_offsets(key, cum_weights, base, deg, k: int, iters: int,
                     host: bool = False):
    """k weight-proportional draws per row via inverse-CDF binary search.

    The TPU rebuild of the reference's ``weight_sample``
    (cuda_random.cu.hpp:143-186): each of the k slots draws independently
    (with replacement, matching the reference's semantics) from the row's
    categorical distribution over the row-local inclusive prefix
    ``cum_weights``. Rows with ``deg <= k`` take all neighbors in CSR order
    instead — the reference's ``safe_sample`` copy-all branch
    (cuda_random.cu.hpp:196-205). With ``host=True`` the search runs as host
    compute against the host-resident prefix array.

    Returns (offsets (S, k) int32 row-local, sel_mask (S, k)).
    """
    S = deg.shape[0]
    degc = deg[:, None]
    end = jnp.maximum(base + deg.astype(base.dtype) - 1, 0)
    tot = staged_gather(cum_weights, end, host)
    tot = jnp.where(deg > 0, tot, 1.0)
    u = jax.random.uniform(key, (S, k), dtype=cum_weights.dtype) * tot[:, None]
    if host:
        off = _cdf_search_host_call(cum_weights, u, base, deg, iters)
    else:
        off = _cdf_search(cum_weights, u, base, deg, iters)
    i = jnp.arange(k, dtype=jnp.int32)[None, :]
    off = jnp.where(degc <= k, jnp.minimum(i, jnp.maximum(degc - 1, 0)), off)
    sel_mask = i < jnp.minimum(degc, k)
    return off, sel_mask


def temporal_window_counts(edge_time, base, deg, lo_t, hi_t, iters: int):
    """Per-row slot range of edges whose timestamp falls in ``[lo_t, hi_t]``.

    Requires rows time-sorted (``CSRTopo.set_edge_time``). Two vectorized
    binary searches over each row's ``deg + 1`` candidate split points:
    ``first`` counts edges with ``t < lo_t``; the window's masked degree
    ``deg_t`` counts edges with ``lo_t <= t <= hi_t``, so the in-window
    edges occupy row-local slots ``[first, first + deg_t)``. ``iters`` >=
    ceil(log2(max_degree + 1)) guarantees convergence (converged lanes are
    frozen arithmetically, so extra iterations are no-ops). Returns
    ``(first, deg_t)``, both (S,) int32.
    """
    degc = deg.astype(base.dtype)
    zero = jnp.zeros_like(degc)
    probe_cap = jnp.maximum(degc - 1, 0)

    def count(cmp):
        lo = zero
        hi = degc
        for _ in range(iters):
            active = lo < hi
            mid = (lo + hi) // 2
            # clamp the probe into the row; inactive/empty lanes read a
            # garbage-but-in-range slot and are masked out of the update
            tv = edge_time[base + jnp.minimum(mid, probe_cap)]
            go = cmp(tv) & active
            lo = jnp.where(go, mid + 1, lo)
            hi = jnp.where(go | ~active, hi, mid)
        return lo

    first = count(lambda t: t < lo_t)
    below_hi = count(lambda t: t <= hi_t)
    return first.astype(jnp.int32), (below_hi - first).astype(jnp.int32)


def sample_layer(topo, seeds, num_seeds, k: int, key, with_eid: bool = False,
                 weighted: bool = False, time_window=None):
    """Sample up to ``k`` neighbors for each valid seed.

    Args:
      topo: DeviceTopology (indptr (N+1,), indices (E,)).
      seeds: (S,) node ids, -1 padded; valid entries occupy a prefix.
      num_seeds: scalar count of valid seeds.
      k: static fanout. Must be >= 1 (use max_degree for full neighborhood,
         the reference's fanout -1, sage_sampler.py:67).
      key: PRNG key.
      with_eid: also return global CSR edge positions per sample.
      time_window: optional ``(lo, hi)`` scalar timestamps; only edges with
        ``lo <= t <= hi`` are drawn from (masked degrees — expired edges
        never appear). Requires a time-sorted topology placed with
        ``to_device(with_times=True)``; mutually exclusive with weighted.

    Returns:
      neighbors: (S, K) sampled node ids, -1 where invalid.
      counts: (S,) number of valid samples per row (min(deg, k), 0 for
        invalid seeds) — the padded analogue of the reference's counts output.
      eids: (S, K) CSR edge slots or -1, only if ``with_eid``.
    """
    if k < 1:
        raise ValueError(f"fanout k must be >= 1, got {k}")
    if k > 46340:
        # the int32 stratum arithmetic below needs i*r_ <= k^2 < 2^31
        raise ValueError(f"fanout k must be <= 46340, got {k}")
    S = seeds.shape[0]
    valid = (jnp.arange(S) < num_seeds) & (seeds >= 0)
    s = jnp.where(valid, seeds, 0)

    base = topo.indptr[s]
    deg = (topo.indptr[s + 1] - base).astype(jnp.int32)
    deg = jnp.where(valid, deg, 0)

    first = None
    if time_window is not None:
        if weighted:
            raise ValueError(
                "time_window cannot be combined with weighted=True; pick "
                "one biased draw per sampler"
            )
        if topo.edge_time is None:
            raise ValueError(
                "temporal sampling needs topo.edge_time; build the "
                "DeviceTopology with to_device(with_times=True)"
            )
        lo_t, hi_t = time_window
        first, deg = temporal_window_counts(
            topo.edge_time, base, deg, lo_t, hi_t, topo.search_iters
        )
        deg = jnp.where(valid, deg, 0)

    if weighted:
        if topo.cum_weights is None:
            raise ValueError(
                "weighted sampling needs topo.cum_weights; build the "
                "DeviceTopology with to_device(with_weights=True)"
            )
        off, mask_sel = weighted_offsets(
            key, topo.cum_weights, base, deg, k, topo.search_iters,
            host=topo.host_indices,
        )
    else:
        kj, kr = jax.random.split(key)
        off_nr, mask_sel = stratified_offsets(kj, deg, k)
        off = rotate_offsets(kr, off_nr, deg, k)
    if first is not None:
        # window offsets are row-local within [first, first + deg_t);
        # rebase them onto the full row before the CSR gather
        off = first[:, None] + off
    mask = valid[:, None] & mask_sel

    epos = base[:, None] + off.astype(base.dtype)
    safe_epos = jnp.where(mask, epos, 0)
    nbr = _gather_indices(topo, safe_epos)
    nbr = jnp.where(mask, nbr, -1).astype(jnp.int32)
    counts = jnp.where(valid, jnp.minimum(deg, k), 0)

    if with_eid:
        eids = jnp.where(mask, epos, -1)
        if topo.eid is not None:
            eids = jnp.where(
                mask, staged_gather(topo.eid, safe_epos, topo.host_indices), -1
            )
        return nbr, counts, eids
    return nbr, counts


def _gather_indices(topo, epos):
    return staged_gather(topo.indices, epos, getattr(topo, "host_indices", False))


def staged_host_call(fn, static_argnums=()):
    """Wrap a host-compute ``fn`` with the traced-vs-eager dispatch.

    Traced calls run ``fn`` inline (its compute_on block composes into the
    enclosing jit). Eager calls go through a cached jit wrapper, because
    eager compute_on leaves a host memory space in the result aval that
    later eager ops reject — the jit boundary re-anchors the result in
    device space.
    """
    static = set(static_argnums)
    jitted = jax.jit(fn, static_argnums=tuple(static_argnums))

    def call(*args):
        dyn = [a for i, a in enumerate(args) if i not in static]
        if any(
            isinstance(x, jax.core.Tracer)
            for x in jax.tree_util.tree_leaves(dyn)
        ):
            return fn(*args)
        return jitted(*args)

    return call


def staged_gather(table, idx, host: bool):
    """Gather rows of ``table``, staging through host memory when ``host``.

    The reference's UVA mode lets the sampling kernel dereference pinned host
    memory directly over PCIe (quiver_sample.cu:400-408). TPUs cannot do
    that, so the HOST-mode equivalent is a *staged* gather: the (small) index
    block hops to host memory, the gather runs as host compute against the
    host-resident table, and only the result returns to HBM — the large
    table itself never transits. Transfers are memory-SPACE moves
    (``jax.memory.Space``), sharding-preserving, so the same code composes
    at the jit level, under vmap/scan, and inside ``shard_map`` bodies (the
    fused beyond-HBM trainer) — a concrete-sharding ``device_put`` would be
    ill-formed in per-device SPMD code.
    """
    if not host:
        return table[idx]
    return _staged_gather_call(table, idx)


def _staged_gather(table, idx):
    from jax.experimental.compute_on import compute_on
    from jax.memory import Space

    idx_h = jax.device_put(idx, Space.Host)

    @compute_on("device_host")
    def host_gather(t, i):
        return t[i]

    out_h = host_gather(table, idx_h)
    return jax.device_put(out_h, Space.Device)


# module-level wrappers so repeated eager calls hit the jit dispatch fastpath
# (iters is hashable, so it rides as a static arg)
_staged_gather_call = staged_host_call(_staged_gather)
_cdf_search_host_call = staged_host_call(_cdf_search_host, static_argnums=(4,))
