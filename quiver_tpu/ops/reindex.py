"""Order-preserving deduplication with static shapes.

TPU-native replacement for the reference's GPU hash-table reindex
(torch-quiver reindex.cu.hpp:17-225 + ``FillWithDuplicates``,
quiver_sample.cu:18-63): instead of atomicCAS open addressing, a stable
sort + segment-representative scan assigns every id the position of its first
occurrence, producing the same order-preserving compaction with fully static
shapes and no atomics. Seeds are placed first in the input, so — exactly as
in the reference's ``reindex_with_seeds`` — the first ``num_seeds`` unique
ids are the seeds themselves, preserving the PyG ``n_id[:batch_size]``
contract.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = [
    "masked_unique",
    "reindex_layer",
    "inverse_permutation",
    "inverse_permutation_gather",
    "complete_permutation",
    "resolve_dedup",
]

DEDUP_STRATEGIES = ("sort", "map", "scan")

# QUIVER_DEDUP resolution caches — ONE env read per process each.
# resolve_dedup is reachable from traced code (dist_multilayer_sample /
# multilayer_sample call it inside shard_map'd bodies), where a per-call
# env read freezes at first trace while looking like a live switch (the
# QUIVER_COUNTS bug class, graftlint env-at-trace). Set QUIVER_DEDUP
# before the first sampler construction or trace; tests reset these.
_forced_dedup: str | None = None
_auto_dedup: str | None = None


def _forced_dedup_env() -> str:
    """The ``QUIVER_DEDUP`` force, read once per process ("" = no force)."""
    global _forced_dedup
    if _forced_dedup is None:
        import os

        _forced_dedup = os.environ.get("QUIVER_DEDUP", "").strip()
    return _forced_dedup


def resolve_dedup(dedup: str) -> str:
    """Resolve a dedup strategy name, mapping ``"auto"`` to the platform
    default.

    The three strategies are bit-identical (tests/test_reindex.py); only
    their cost model differs per backend:

    * **cpu** -> ``"map"`` — measured: the dense scatter-min map is 4-5x
      the sort path at both smoke and full products scale
      (docs/TPU_MEASUREMENTS_R3.md CPU-floor extras).
    * **tpu** -> ``"scan"`` — the zero-scatter strategy, chosen because
      XLA serializes general scatters on TPU while its sort runs at
      ~1.8 ms/M elements (r3 link characterization); provisional until
      the ``sampler-hbm --dedup both`` self-selection lands on hardware.

    ``QUIVER_DEDUP=sort|map|scan`` overrides the ``"auto"`` resolution
    ONLY (chip-window forcing): call sites passing an explicit strategy
    keep it — benchmark variant labels must match what actually ran — and
    the first such ignored force is logged so the mismatch is visible.
    Unknown names raise — a typo must not silently fall back to a
    strategy (the callers' dispatch treats anything non-map/scan as sort).
    Both the force and the "auto" resolution are pinned at FIRST use for
    the process (env-before-first-use contract; this function runs inside
    traced sampler bodies, where the env would freeze at first trace
    regardless — the cache makes the once-semantics explicit).
    """
    if dedup in DEDUP_STRATEGIES:
        forced = _forced_dedup_env()
        if forced and forced != dedup:
            from ..utils.trace import info_once

            info_once(
                f"dedup-env-ignored-{dedup}",
                "QUIVER_DEDUP=%s ignored for explicit dedup=%r (the env "
                "override applies only to dedup='auto')",
                forced, dedup,
            )
        return dedup
    if dedup != "auto":
        raise ValueError(
            f"dedup must be 'auto', 'sort', 'map', or 'scan', got {dedup!r}"
        )
    global _auto_dedup
    if _auto_dedup is None:
        from ..core.config import resolve_platform_strategy

        _auto_dedup = resolve_platform_strategy(
            "QUIVER_DEDUP", DEDUP_STRATEGIES, tpu_default="scan",
            other_default="map",
        )
    return _auto_dedup


def inverse_permutation(p):
    """q with q[p[i]] == i — the reference's ``inverse_permutation``
    (reindex.cu.hpp:304-315), as one XLA scatter instead of a thrust
    for_each."""
    n = p.shape[0]
    return jnp.zeros(n, p.dtype).at[p].set(jnp.arange(n, dtype=p.dtype))


def inverse_permutation_gather(p):
    """The zero-scatter sibling of :func:`inverse_permutation`: argsort of
    a permutation IS its inverse. Costs a sort instead of a scatter — the
    right trade on backends where XLA serializes scatters (shared by the
    dedup scan strategy and the routed feature gather)."""
    return jnp.argsort(p).astype(jnp.int32)


def complete_permutation(p, n: int):
    """Extend an injective partial map ``p`` (m distinct values < n) to a
    full permutation of {0..n-1}: p's entries first (in order), then the
    missing values ascending — the reference's ``complete_permutation``
    (reindex.cu.hpp:277-300, pair-sort construction). Static-shape rebuild:
    rank present values by position in p, absent values by value after all
    present ones, then argsort the rank vector.
    """
    m = p.shape[0]
    if m > n:
        raise ValueError(f"partial permutation longer ({m}) than n ({n})")
    # rank[v] = position in p when present, m + v when absent — absent
    # values compare after every present one yet stay value-ordered.
    # (m + v fits: m <= n and v < n, so rank < 2n < int32 max for any
    # realistic graph.)
    vals = jnp.arange(n, dtype=p.dtype)
    rank = (vals + m).at[p].set(jnp.arange(m, dtype=p.dtype))
    return jnp.argsort(rank).astype(p.dtype)


def masked_unique(ids, valid, size: int, num_forced: int = 0,
                  node_bound: int | None = None,
                  scatter_free: bool = False):
    """First-occurrence-order unique of ``ids[valid]``, padded to ``size``.

    Args:
      ids: (T,) integer ids (values < iinfo.max; padding may be anything).
      valid: (T,) bool mask.
      size: static output capacity for the unique list.
      num_forced: the first ``num_forced`` valid lanes are *unconditionally*
        kept as distinct outputs even if their values repeat. Used for seed
        lanes: PyG's contract is ``n_id[:batch_size] == seeds`` verbatim,
        duplicates included, so a batch like [7, 7, 3] must occupy three
        output slots. Later duplicates of a forced value still map to its
        first occurrence.
      node_bound: static exclusive upper bound on valid id values. When
        given, first occurrences are found with a scatter-min into a
        (node_bound,)-sized position map instead of a stable sort —
        O(node_bound + T) memset/scatter/gather vs O(T log^2 T) sort
        passes. This is the direct analogue of the reference's GPU hash
        table (reindex.cu.hpp:120-139 atomicMin keeps the first
        occurrence); the dense map plays the table, scatter-min plays
        atomicMin. Same contract either way; pick by measurement.
        WARNING — silent corruption if violated: a valid id >= node_bound
        is dropped by the scatter (mode="drop") and its gather clamps to
        the last map slot, so the output is WRONG with no error raised;
        the sort path tolerates arbitrary id values. Callers must derive
        node_bound from the id space that produced ``ids`` (the samplers
        pass topo.node_count; neighbor ids are CSR entries < node_count by
        construction).
      scatter_free: use the ZERO-SCATTER strategy (``dedup="scan"``): two
        sorts + a cumulative max + a binary-search compaction + gathers, no
        ``.at[].set/min`` anywhere — the other two strategies compact their
        output with a scatter. Rationale: the round-3 link characterization
        measured TPU sort at ~1.8 ms/M elements while the reindex stage ran
        tens of ms — XLA scatters with non-trivial index patterns can
        serialize on TPU, so a strategy whose only data movement is sorts,
        scans, and gathers is the natural third candidate. Same contract;
        pick by measurement (ignored when ``node_bound`` is given).

    Returns:
      uniq: (size,) unique ids in first-occurrence order, -1 padded.
      num_unique: scalar — total uniques found (may exceed ``size``; the
        excess is reported, not stored).
      local: (T,) compact id of each element among the uniques, or -1 for
        invalid / overflowed elements.
    """
    T = ids.shape[0]
    pos = jnp.arange(T, dtype=jnp.int32)

    if node_bound is not None:
        safe = jnp.where(valid, ids, 0)
        first_pos = (
            jnp.full((node_bound,), T, jnp.int32)
            .at[safe]
            .min(jnp.where(valid, pos, T), mode="drop")
        )
        rep_pos = first_pos[safe]
    else:
        # shared sorted view: stable value sort, run starts (sentinel run
        # excluded); positions within a run ascend, so a run's first sorted
        # element IS the value's first occurrence
        sent = jnp.iinfo(ids.dtype).max
        vals = jnp.where(valid, ids, sent)
        order = jnp.argsort(vals, stable=True)
        sv = vals[order]
        pv = pos[order]
        first = jnp.concatenate(
            [jnp.ones(1, bool), sv[1:] != sv[:-1]]
        ) & (sv != sent)

        if scatter_free:
            # sorted-view index of the current run's first element: a
            # running max over first-markers (the scatter-free
            # run-representative)
            idx_first = lax.cummax(
                jnp.where(first, jnp.arange(T, dtype=jnp.int32), -1)
            )
            rep_pos_sorted = jnp.where(
                idx_first >= 0, pv[jnp.clip(idx_first, 0)], T
            )
            # back to original positions via the inverse permutation, built
            # by sorting the permutation instead of scattering into it
            rep_pos = rep_pos_sorted[inverse_permutation_gather(order)]
        else:
            run_id = jnp.cumsum(first.astype(jnp.int32)) - 1
            # representative position scattered per run
            by_run = (
                jnp.zeros(T, jnp.int32)
                .at[jnp.where(first, run_id, T)]
                .set(pv, mode="drop")
            )
            rep_pos_sorted = by_run[jnp.clip(run_id, 0)]
            # back to original positions
            rep_pos = jnp.zeros(T, jnp.int32).at[order].set(rep_pos_sorted)

    forced = (pos < num_forced) & valid
    is_rep = (valid & (rep_pos == pos)) | forced
    rank = jnp.cumsum(is_rep.astype(jnp.int32)) - 1  # first-occurrence rank
    num_unique = jnp.sum(is_rep.astype(jnp.int32))

    if scatter_free and node_bound is None:
        # compaction WITHOUT a sort or scatter: ``rank`` is non-decreasing
        # (a cumsum), and the r-th rep's position is the first index whose
        # rank reaches r — a vectorized binary search. The (size,) write is
        # a contiguous slice update.
        m = min(size, T)
        comp_pos = jnp.searchsorted(
            rank, jnp.arange(m, dtype=rank.dtype), side="left"
        )
        packed = jnp.where(
            jnp.arange(m) < num_unique,
            ids[jnp.clip(comp_pos, 0, T - 1)], -1
        ).astype(ids.dtype)
        uniq = jnp.full(size, -1, ids.dtype).at[:m].set(packed)
    else:
        uniq = (
            jnp.full(size, -1, ids.dtype)
            .at[jnp.where(is_rep & (rank < size), rank, size)]
            .set(ids, mode="drop")
        )
    local = rank[rep_pos]
    local = jnp.where(valid & (local < size), local, -1)
    return uniq, num_unique, local


def reindex_layer(seeds, num_seeds, neighbors, frontier_cap: int,
                  node_bound: int | None = None,
                  scatter_free: bool = False):
    """Per-layer reindex: frontier = unique(seeds ∪ neighbors), seeds first.

    Mirrors the reference's ``reindex_single`` contract
    (quiver_sample.cu:294-346) in padded form.

    Args:
      seeds: (S,) seed node ids, -1 padded; valid entries occupy a prefix.
      num_seeds: scalar count of valid seeds.
      neighbors: (S, K) sampled neighbor ids, -1 where invalid.
      frontier_cap: static capacity of the output frontier.
      node_bound: optional static id upper bound enabling the sort-free
        scatter-min dedup (see masked_unique).
      scatter_free: the zero-scatter sort/scan/gather strategy
        (see masked_unique; ignored when node_bound is given).

    Returns:
      frontier: (frontier_cap,) unique node ids, seeds first, -1 padded.
      num_frontier: scalar valid count (clipped to capacity).
      col_local: (S, K) frontier-local id per neighbor, -1 where invalid.
        (Row-local ids need no lookup: seed i's local id is i.)
      overflow: scalar count of uniques dropped for exceeding frontier_cap.
    """
    S, K = neighbors.shape
    ids = jnp.concatenate([seeds, neighbors.reshape(-1)])
    seed_valid = (jnp.arange(S) < num_seeds) & (seeds >= 0)
    nbr_valid = neighbors.reshape(-1) >= 0
    valid = jnp.concatenate([seed_valid, nbr_valid])

    uniq, num_unique, local = masked_unique(
        ids, valid, frontier_cap, num_forced=S, node_bound=node_bound,
        scatter_free=scatter_free,
    )
    col_local = local[S:].reshape(S, K)
    num_frontier = jnp.minimum(num_unique, frontier_cap)
    overflow = jnp.maximum(num_unique - frontier_cap, 0)
    return uniq, num_frontier, col_local, overflow
