"""Fused per-hop Pallas sampling megakernel (windowed row DMA + in-kernel
select), the one engine behind every sampler variant.

TPU-native counterpart of the reference's per-hop CUDA kernel pair —
``CSRRowWiseSampleKernel`` (torch-quiver cuda_random.cu.hpp:7-69) and the
weighted ``WarpSampler`` CDF walk (cuda_random.cu.hpp:143-186) — plus the
eid lane of ``quiver_sample.cu``'s reindex plumbing. The GPU kernels issue
k random cache-line loads per row; TPUs want contiguous DMA, so the design
flips to **window sampling**: per hop, one pass over the HBM-resident CSR
does the degree lookup (XLA indptr gather), the draw, the neighbor-block
copy, and the select:

 1. XLA computes per-row window starts and the PRNG-bit-dependent parts of
    the draw (stratified offsets + rotation for uniform/temporal, the raw
    ``(S, k)`` uniform block for weighted) — everything whose bits depend
    only on the key, keeping bit-parity with the XLA oracle provable.
 2. The kernel DMAs ``indices[start : start+window]`` (and, as aligned
    lanes, the ``cum_weights`` and ``eid`` windows when the variant needs
    them) into VMEM — one bulk DMA per row per table, all rows of a tile
    in flight at once.
 3. Topology-dependent work happens on-chip against the VMEM window: the
    weighted inverse-CDF binary search walks the row's prefix-weight
    segment in VMEM (``_wselect_kernel`` — the WarpSampler walk without
    the log2(deg) random HBM probes), and selection is an exact integer
    one-hot masked-sum on the VPU (no float round-trip, node ids beyond
    2^24 stay exact).

Bit-parity contract (pinned by tests/test_fused_sampler.py): for rows
whose draw span fits the window (uniform/temporal with ``deg <= window``;
weighted always, enforced via ``max_degree <= window``), outputs are
BITWISE equal to ``ops.sample.sample_layer`` under the same key — the
uniform path consumes ``kj, kr = split(key)`` over the same shapes, the
weighted path consumes the key unsplit over the same ``(S, k)`` uniform
block and walks an affine-shifted copy of the same f32 prefix array, and
the temporal path shares ``temporal_window_counts`` outright. Window
placement for over-window rows draws from ``fold_in(key, 1)`` so parity
lanes never consume those bits.

Distribution for ``deg > window`` rows (uniform/temporal only): a
uniformly-placed contiguous window — interior slots boosted by ``deg/T``
over the exact ``k/deg`` (``T = deg-window+1`` placements), first/last
``window-1`` slots attenuated linearly. Policy (decided r5, pinned by
tests/test_pallas_hub_distribution.py): the hub-row attenuation is
ACCEPTED; the XLA path remains the exact reference. The weighted walk
refuses windowing instead (callers degrade to XLA below
``max_degree <= window`` — a truncated CDF would re-weight, not
attenuate).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..sample import rotate_offsets, stratified_offsets, temporal_window_counts

__all__ = [
    "DEFAULT_WINDOW",
    "fused_sample_layer",
    "fused_select_hop",
    "fused_weighted_hop",
]

# default neighbor-window length; callers deciding between this kernel and
# the XLA path compare edge_count against it (quiver_tpu/sampling/sampler.py)
DEFAULT_WINDOW = 2048

_I32MAX = 2**31 - 1


def _select_kernel(tile: int, window: int, k: int, n_tab: int,
                   start_ref, *refs):
    """Windowed gather-select over ``n_tab`` aligned int32 tables.

    ``out[t][j, c] = tables[t][start[j] + offs[j, c]]`` — the uniform /
    temporal / dist-owner select core; the eid lane is just a second table
    riding the same offsets.
    """
    tabs = refs[:n_tab]
    offs_ref = refs[n_tab]
    outs = refs[n_tab + 1:2 * n_tab + 1]
    bufs = refs[2 * n_tab + 1:3 * n_tab + 1]
    sems = refs[3 * n_tab + 1]
    i = pl.program_id(0)

    def dma(t, j):
        return pltpu.make_async_copy(
            tabs[t].at[pl.ds(start_ref[i * tile + j], window)],
            bufs[t].at[j],
            sems.at[t, j],
        )

    # fan out: every row-window DMA of this tile (all tables) in flight
    for t in range(n_tab):
        for j in range(tile):
            dma(t, j).start()
    for t in range(n_tab):
        for j in range(tile):
            dma(t, j).wait()

    # exact integer select: out[j, c] = buf[j, offs[j, c]]
    col = jax.lax.broadcasted_iota(jnp.int32, (tile, k, window), 2)
    hit = col == offs_ref[:, :][:, :, None]
    for t in range(n_tab):
        vals = bufs[t][:, :].reshape(tile, 1, window)
        outs[t][:, :] = jnp.sum(jnp.where(hit, vals, 0), axis=2)


def _wselect_kernel(tile: int, window: int, k: int, iters: int,
                    with_eid: bool, scale_u: bool, start_ref, *refs):
    """Weighted select: in-kernel inverse-CDF walk over the VMEM window.

    The WarpSampler CDF walk (cuda_random.cu.hpp:143-186) against the DMA'd
    prefix-weight window instead of log2(deg) random HBM probes. Row-local
    bisection over window positions ``[off0, off0+wlen)`` is the affine
    shift of ``ops.sample._cdf_search`` by ``start`` — same probed f32
    values, same compares, same bits out. Emits the selected row-local
    offsets too (the eids-without-a-table lane is ``base + off`` in XLA).
    """
    if with_eid:
        (indices_ref, cw_ref, eid_ref, meta_ref, u_ref,
         out_nbr, out_off, out_eid, ibuf, wbuf, ebuf, sems) = refs
    else:
        (indices_ref, cw_ref, meta_ref, u_ref,
         out_nbr, out_off, ibuf, wbuf, sems) = refs
        eid_ref = ebuf = out_eid = None
    i = pl.program_id(0)
    pairs = [(indices_ref, ibuf), (cw_ref, wbuf)]
    if with_eid:
        pairs.append((eid_ref, ebuf))

    def dma(t, j):
        src, dst = pairs[t]
        return pltpu.make_async_copy(
            src.at[pl.ds(start_ref[i * tile + j], window)],
            dst.at[j],
            sems.at[t, j],
        )

    for t in range(len(pairs)):
        for j in range(tile):
            dma(t, j).start()
    for t in range(len(pairs)):
        for j in range(tile):
            dma(t, j).wait()

    off0 = meta_ref[:, 0:1]  # (tile, 1) window offset of the row start
    wl = meta_ref[:, 1:2]    # (tile, 1) row length (== deg; fits the window)
    w = wbuf[:, :]
    # row weight total: the window copy of the row's LAST inclusive-prefix
    # entry — bitwise the oracle's staged_gather(cum_weights, base+deg-1)
    col2 = jax.lax.broadcasted_iota(jnp.int32, (tile, window), 1)
    endw = jnp.maximum(off0 + wl - 1, 0)
    tot = jnp.sum(jnp.where(col2 == endw, w, 0.0), axis=1, keepdims=True)
    tot = jnp.where(wl > 0, tot, 1.0)
    u = u_ref[:, :]
    if scale_u:
        u = u * tot
    # row-local inverse-CDF bisection (ops.sample._cdf_search shifted by
    # start: (2*off0 + lo + hi) // 2 = off0 + (lo + hi) // 2, so every
    # probe touches the same array element the global search would)
    nonempty = (wl > 0).astype(jnp.int32)
    lo = jnp.broadcast_to(off0, (tile, k))
    hi = lo + (wl - 1) * nonempty
    col3 = jax.lax.broadcasted_iota(jnp.int32, (tile, k, window), 2)
    w3 = w.reshape(tile, 1, window)
    for _ in range(iters):
        mid = (lo + hi) // 2
        # the min() is a safety clamp only: valid rows satisfy
        # off0 + wlen <= window, so mid <= window-1 already
        midc = jnp.minimum(mid * nonempty, window - 1)
        pm = jnp.sum(jnp.where(col3 == midc[:, :, None], w3, 0.0), axis=2)
        go = pm < u
        lo = jnp.where(go, mid + 1, lo)
        hi = jnp.where(go, hi, mid)
    row_off = lo - off0
    # take-all override (weighted_offsets / dist serve_wnbr): deg <= k
    # rows keep CSR order — in-kernel so emitted offsets match XLA's
    ii = jax.lax.broadcasted_iota(jnp.int32, (tile, k), 1)
    row_off = jnp.where(
        wl <= k, jnp.minimum(ii, jnp.maximum(wl - 1, 0)), row_off
    )
    sel = off0 + row_off
    hit = col3 == sel[:, :, None]
    ivals = ibuf[:, :].reshape(tile, 1, window)
    out_nbr[:, :] = jnp.sum(jnp.where(hit, ivals, 0), axis=2)
    out_off[:, :] = row_off
    if with_eid:
        evals = ebuf[:, :].reshape(tile, 1, window)
        out_eid[:, :] = jnp.sum(jnp.where(hit, evals, 0), axis=2)


@functools.partial(
    jax.jit, static_argnames=("tile", "window", "k", "interpret")
)
def _run_select(tables, start, offs, tile, window, k, interpret):
    Sp = start.shape[0]
    n_tab = len(tables)
    blk = pl.BlockSpec((tile, k), lambda i, *_: (i, 0),
                       memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # start addresses
        grid=(Sp // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_tab + [blk],
        out_specs=[blk] * n_tab,
        scratch_shapes=(
            [pltpu.VMEM((tile, window), jnp.int32)] * n_tab
            + [pltpu.SemaphoreType.DMA((n_tab, tile))]
        ),
    )
    outs = pl.pallas_call(
        functools.partial(_select_kernel, tile, window, k, n_tab),
        out_shape=[jax.ShapeDtypeStruct((Sp, k), jnp.int32)] * n_tab,
        grid_spec=grid_spec,
        interpret=interpret,
    )(start, *tables, offs)
    return tuple(outs)


@functools.partial(
    jax.jit,
    static_argnames=("tile", "window", "k", "iters", "scale_u", "interpret"),
)
def _run_wselect(indices, cum_weights, eid, start, meta, u, tile, window, k,
                 iters, scale_u, interpret):
    Sp = start.shape[0]
    with_eid = eid is not None
    n_dma = 3 if with_eid else 2
    blk = pl.BlockSpec((tile, k), lambda i, *_: (i, 0),
                       memory_space=pltpu.VMEM)
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    args = [indices, cum_weights] + ([eid] if with_eid else [])
    in_specs = [any_spec] * len(args) + [
        pl.BlockSpec((tile, 2), lambda i, *_: (i, 0),
                     memory_space=pltpu.VMEM),
        blk,
    ]
    n_out = 3 if with_eid else 2
    scratch = [
        pltpu.VMEM((tile, window), jnp.int32),
        pltpu.VMEM((tile, window), cum_weights.dtype),
    ]
    if with_eid:
        scratch.append(pltpu.VMEM((tile, window), jnp.int32))
    scratch.append(pltpu.SemaphoreType.DMA((n_dma, tile)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Sp // tile,),
        in_specs=in_specs,
        out_specs=[blk] * n_out,
        scratch_shapes=scratch,
    )
    outs = pl.pallas_call(
        functools.partial(
            _wselect_kernel, tile, window, k, iters, with_eid, scale_u
        ),
        out_shape=[jax.ShapeDtypeStruct((Sp, k), jnp.int32)] * n_out,
        grid_spec=grid_spec,
        interpret=interpret,
    )(start, *args, meta, u)
    return tuple(outs)


def _default_interpret(interpret):
    if interpret is None:
        return jax.devices()[0].platform != "tpu"
    return interpret


def fused_select_hop(indices, start, offs, *, eid=None,
                     window: int = DEFAULT_WINDOW, tile: int = 8,
                     interpret: bool | None = None):
    """Raw windowed gather-select: ``out[r, c] = indices[start[r] +
    offs[r, c]]`` (plus an aligned ``eid`` lane when given).

    The dist owner-side select core. Contract: ``start`` int32 ``(S,)``
    with ``start + window <= indices.shape[0]`` everywhere, ``offs`` int32
    ``(S, k)`` in ``[0, window)``. Returns a tuple of ``(S, k)`` int32
    arrays, one per table.
    """
    interpret = _default_interpret(interpret)
    S, k = offs.shape
    pad = (-S) % tile
    if pad:
        start = jnp.concatenate([start, jnp.zeros(pad, start.dtype)])
        offs = jnp.concatenate([offs, jnp.zeros((pad, k), offs.dtype)])
    tables = (indices,) if eid is None else (indices, eid)
    outs = _run_select(tables, start, offs, tile, window, k, interpret)
    return tuple(o[:S] for o in outs)


def fused_weighted_hop(indices, cum_weights, start, off0, wlen, u,
                       iters: int, *, eid=None, scale_u: bool = True,
                       window: int = DEFAULT_WINDOW, tile: int = 8,
                       interpret: bool | None = None):
    """Raw windowed weighted select: in-kernel inverse-CDF walk over the
    row window ``[start, start+window)`` with the row at window offset
    ``off0`` and length ``wlen`` (== deg; must fit the window).

    ``u`` is the ``(S, k)`` f32 draw block — raw uniforms scaled by the
    in-kernel row totals when ``scale_u`` (the replicated path), or
    pre-scaled by the owner-exchange totals when not (the dist path).
    Returns ``(nbr, row_off[, eids])``, each ``(S, k)`` int32; ``row_off``
    is the selected row-local offset after the take-all override —
    bitwise ``ops.sample.weighted_offsets``.
    """
    interpret = _default_interpret(interpret)
    S, k = u.shape
    meta = jnp.stack(
        [off0.astype(jnp.int32), wlen.astype(jnp.int32)], axis=1
    )
    pad = (-S) % tile
    if pad:
        start = jnp.concatenate([start, jnp.zeros(pad, start.dtype)])
        meta = jnp.concatenate([meta, jnp.zeros((pad, 2), meta.dtype)])
        u = jnp.concatenate([u, jnp.zeros((pad, k), u.dtype)])
    outs = _run_wselect(indices, cum_weights, eid, start, meta, u, tile,
                        window, k, iters, scale_u, interpret)
    return tuple(o[:S] for o in outs)


def fused_sample_layer(topo, seeds, num_seeds, k: int, key, *,
                       weighted: bool = False, time_window=None,
                       with_eid: bool = False,
                       window: int = DEFAULT_WINDOW, tile: int = 8,
                       interpret: bool | None = None):
    """Fused Pallas per-hop sample; same contract as
    ``ops.sample.sample_layer`` (and bitwise equal wherever the draw span
    fits the window — see the module docstring's parity contract).

    Requires an HBM-resident topology with ``edge_count >= window``
    (callers fall back to the XLA path otherwise); the weighted walk
    additionally requires ``topo.max_degree <= window`` so every row's
    prefix segment is fully VMEM-resident.
    """
    if k < 1:
        raise ValueError(f"fanout k must be >= 1, got {k}")
    if k > 46340:
        raise ValueError(f"fanout k must be <= 46340, got {k}")
    interpret = _default_interpret(interpret)
    E = topo.indices.shape[0]
    if E < window:
        raise ValueError(f"edge_count {E} < window {window}; use the XLA path")
    if E - window > _I32MAX:
        # window starts ride scalar-prefetch SMEM as int32; past 2^31 edges
        # they would wrap (the XLA path keeps indptr dtype and stays exact)
        raise ValueError(
            f"edge_count {E} exceeds the int32 windowed-DMA range; "
            "use the XLA path"
        )
    if k > window:
        raise ValueError(f"fanout k={k} must be <= window={window}")
    if weighted and time_window is not None:
        raise ValueError(
            "time_window cannot be combined with weighted=True; pick one "
            "biased draw per sampler"
        )
    if weighted:
        if topo.cum_weights is None:
            raise ValueError(
                "weighted sampling needs topo.cum_weights; build the "
                "DeviceTopology with to_device(with_weights=True)"
            )
        md = getattr(topo, "max_degree", None)
        if md is None or md > window:
            raise ValueError(
                f"the fused weighted walk needs max_degree <= window "
                f"(got {md} vs {window}); use the XLA path"
            )
    if time_window is not None and topo.edge_time is None:
        raise ValueError(
            "temporal sampling needs topo.edge_time; build the "
            "DeviceTopology with to_device(with_times=True)"
        )
    if with_eid and topo.eid is not None and E > _I32MAX:
        raise ValueError(
            f"edge_count {E} exceeds the int32 eid-lane range; use the "
            "XLA path"
        )

    S = seeds.shape[0]
    valid = (jnp.arange(S) < num_seeds) & (seeds >= 0)
    s = jnp.where(valid, seeds, 0)
    # jnp views of the topology arrays: a host-numpy array indexed by a
    # traced value raises TracerArrayConversionError, so the kernel path
    # would silently lose its jit/lowering story (the PR 15 regression
    # class, kept covered by graftaudit's fused target)
    indptr = jnp.asarray(topo.indptr)
    base = indptr[s]  # keep indptr dtype: values can exceed int32 ranges
    deg = (indptr[s + 1] - base).astype(jnp.int32)
    deg = jnp.where(valid, deg, 0)

    first = None
    if time_window is not None:
        lo_t, hi_t = time_window
        first, deg = temporal_window_counts(
            jnp.asarray(topo.edge_time), base, deg, lo_t, hi_t,
            topo.search_iters,
        )
        deg = jnp.where(valid, deg, 0)
    # global start of the row's draw span (temporal draws begin at the
    # first in-window slot — the oracle rebases offsets by `first`)
    row0 = base if first is None else base + first.astype(base.dtype)

    indices = jnp.asarray(topo.indices).astype(jnp.int32)
    eid_tab = None
    if with_eid and topo.eid is not None:
        eid_tab = jnp.asarray(topo.eid).astype(jnp.int32)

    if weighted:
        cw = jnp.asarray(topo.cum_weights)
        # key UNSPLIT over the same (S, k) block as weighted_offsets; the
        # u * tot scaling happens in-kernel against the same f32 total
        u01 = jax.random.uniform(key, (S, k), dtype=cw.dtype)
        start_wide = jnp.clip(row0, 0, E - window)
        off0 = (row0 - start_wide).astype(jnp.int32)
        res = fused_weighted_hop(
            indices, cw, start_wide.astype(jnp.int32), off0, deg, u01,
            topo.search_iters, eid=eid_tab, scale_u=True, window=window,
            tile=tile, interpret=interpret,
        )
        nbr, row_off = res[0], res[1]
        eid_sel = res[2] if eid_tab is not None else None
        i = jnp.arange(k, dtype=jnp.int32)[None, :]
        mask_sel = i < jnp.minimum(deg[:, None], k)
    else:
        # identical draw scheme/key discipline as ops.sample.sample_layer:
        # kj jitters the strata, kr rotates — deg <= window rows consume
        # exactly the oracle's bits
        kj, kr = jax.random.split(key)
        wlen = jnp.minimum(deg, window)
        offs, mask_sel = stratified_offsets(kj, wlen, k)
        offs = rotate_offsets(kr, offs, wlen, k)
        # window placement for deg > window rows only, from a fold_in key
        # so the parity lanes above never consume these bits
        max_start = jnp.maximum(deg - window, 0)
        r = jax.random.randint(
            jax.random.fold_in(key, 1), (S,), 0, max_start + 1,
            dtype=jnp.int32,
        )
        pos = row0 + r.astype(base.dtype)
        # window never leaves the array (computed in indptr dtype, cast
        # only after the clip bounds it under 2^31 — checked above); the
        # clip can shift a tail-of-array row's window left of pos, and the
        # offsets still land inside the row because offs < wlen <= deg
        start_wide = jnp.clip(pos, 0, E - window)
        off0 = (pos - start_wide).astype(jnp.int32)
        row_off = r[:, None] + offs
        res = fused_select_hop(
            indices, start_wide.astype(jnp.int32), offs + off0[:, None],
            eid=eid_tab, window=window, tile=tile, interpret=interpret,
        )
        nbr = res[0]
        eid_sel = res[1] if eid_tab is not None else None

    mask = valid[:, None] & mask_sel
    nbr = jnp.where(mask, nbr, -1).astype(jnp.int32)
    counts = jnp.where(valid, jnp.minimum(deg, k), 0)
    if not with_eid:
        return nbr, counts
    if eid_tab is None:
        epos = row0[:, None] + row_off.astype(base.dtype)
        eids = jnp.where(mask, epos, -1)
    else:
        eids = jnp.where(mask, eid_sel.astype(topo.eid.dtype), -1)
    return nbr, counts, eids
