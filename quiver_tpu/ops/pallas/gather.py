"""Pallas row-gather kernel: feature collection from an HBM-resident table.

TPU-native equivalent of the reference's ``quiver_tensor_gather`` CUDA kernel
(torch-quiver shard_tensor.cu.hpp:16-58 — warp per output row, UVA loads):
here each grid step serves a tile of output rows by issuing one async DMA per
row straight from the HBM table into the output's VMEM block, with all DMAs
of a tile in flight simultaneously (the DMA engines play the role of the
GPU's coalesced warp loads). Row indices arrive via scalar prefetch so the
DMA addresses are known before the kernel body runs
(pltpu.PrefetchScalarGridSpec).

XLA's stock gather lowers to a serial dynamic-slice loop on TPU for this
pattern; the explicit fan-out of row DMAs is where the win comes from.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["gather_rows"]


def _gather_kernel(tile: int, ids_ref, table_ref, out_ref, sems):
    i = pl.program_id(0)

    def dma(j):
        idx = ids_ref[i * tile + j]
        return pltpu.make_async_copy(table_ref.at[idx], out_ref.at[j], sems.at[j])

    # fan out: all row DMAs of this tile in flight at once
    for j in range(tile):
        dma(j).start()
    for j in range(tile):
        dma(j).wait()


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _gather_rows_impl(table, ids, tile: int, interpret: bool):
    n_ids = ids.shape[0]
    f = table.shape[1]
    grid = (n_ids // tile,)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],  # table stays in HBM
        out_specs=pl.BlockSpec(
            (tile, f), lambda i, ids: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.SemaphoreType.DMA((tile,))],
    )
    return pl.pallas_call(
        functools.partial(_gather_kernel, tile),
        out_shape=jax.ShapeDtypeStruct((n_ids, f), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(has_side_effects=False)
        if not interpret
        else None,
    )(ids, table)


def gather_rows(table, ids, tile: int = 16, interpret: bool | None = None):
    """Gather ``table[ids]`` with explicit row-DMA pipelining.

    Args:
      table: (N, F) array in HBM. F should be a multiple of 128 for full
        DMA efficiency (pad the feature dim at load time).
      ids: (B,) int32 row indices; must be in-range (callers mask/clamp).
      tile: rows per grid step (= DMAs in flight).
      interpret: force interpreter mode; defaults to True off-TPU so the
        kernel stays testable on the virtual CPU mesh.

    Returns (B, F) gathered rows.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    n = ids.shape[0]
    pad = (-n) % tile
    if pad:
        ids = jnp.concatenate([ids, jnp.zeros(pad, ids.dtype)])
    out = _gather_rows_impl(table, ids, tile, interpret)
    return out[:n] if pad else out
