"""Windowed Pallas CSR sampler — compatibility front for the fused engine.

The original single-purpose windowed kernel grew into the fused per-hop
megakernel in ``ops/pallas/fused.py`` (weighted inverse-CDF walk, temporal
windows, eid lanes, dist owner-side select — one audited engine behind
every sampler variant). This module keeps the historical entry point:
``sample_layer_windowed`` is the fused engine's uniform path, unchanged in
contract, and now BITWISE equal to ``ops.sample.sample_layer`` for rows
with ``deg <= window`` (the fused engine adopted the oracle's 2-way key
split; see fused.py's parity contract and the hub-row attenuation policy
for ``deg > window``).
"""

from __future__ import annotations

from .fused import DEFAULT_WINDOW, fused_sample_layer

__all__ = ["sample_layer_windowed", "DEFAULT_WINDOW"]


def sample_layer_windowed(topo, seeds, num_seeds, k: int, key,
                          window: int = DEFAULT_WINDOW, tile: int = 8,
                          interpret: bool | None = None):
    """Windowed Pallas sampling; same (S, K)/-1 padded contract as
    ops.sample.sample_layer.

    Requires an HBM-resident topology with edge_count >= window (callers
    fall back to the XLA path otherwise). Uniform draws only — the fused
    engine (ops/pallas/fused.py fused_sample_layer) adds the weighted,
    temporal, and eid lanes.
    """
    return fused_sample_layer(
        topo, seeds, num_seeds, k, key, window=window, tile=tile,
        interpret=interpret,
    )
