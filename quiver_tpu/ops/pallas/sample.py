"""Pallas CSR neighbor-sampling kernel (windowed row DMA).

TPU-native counterpart of the reference's warp-per-row reservoir kernel
(torch-quiver cuda_random.cu.hpp:7-69 ``CSRRowWiseSampleKernel``). The GPU
kernel issues k random cache-line loads per row; TPUs want contiguous DMA,
so the design flips to **window sampling**:

 1. XLA precomputes per row a random aligned window into the neighbor span
    and k distinct stratified offsets within it (shared math:
    ops.sample.stratified_offsets).
 2. The kernel DMAs ``indices[start : start+window]`` into VMEM — one bulk
    DMA per row, all rows of a tile in flight at once (the DMA engines play
    the role of the GPU's coalesced warp loads).
 3. Selection is an exact integer one-hot masked-sum on the VPU (no float
    round-trip, so node ids beyond 2^24 stay exact).

Distribution: rows with deg <= window are *identical in distribution* to the
XLA sampler (window = whole row, same strata). Rows with deg > window sample
from a uniformly-placed contiguous window: slot p's marginal is
``n(p)/T * k/window`` with ``T = deg-window+1`` placements and
``n(p) = min(p, T-1) - max(p-window+1, 0) + 1`` — interior slots boosted by
``deg/T`` over the exact ``k/deg``, the first/last (window-1) slots
attenuated linearly toward the row ends. With the default window 2048 this
affects the <0.1% power-law tail.

Policy (decided r5, pinned by tests/test_pallas_hub_distribution.py): the
hub-row attenuation is ACCEPTED rather than patched with multi-window
draws — ``kernel='pallas'`` is an explicit opt-in, and the exact XLA path
remains the default and the correctness reference (the reference's
reservoir kernel, cuda_random.cu.hpp:41-57, is exact at any degree).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..sample import rotate_offsets, stratified_offsets

__all__ = ["sample_layer_windowed", "DEFAULT_WINDOW"]

# default neighbor-window length; callers deciding between this kernel and
# the XLA path compare edge_count against it (quiver_tpu/sampling/sampler.py)
DEFAULT_WINDOW = 2048


def _kernel(tile: int, window: int, k: int,
            start_ref, indices_ref, offs_ref, out_ref, buf, sems):
    i = pl.program_id(0)

    def dma(j):
        return pltpu.make_async_copy(
            indices_ref.at[pl.ds(start_ref[i * tile + j], window)],
            buf.at[j],
            sems.at[j],
        )

    # fan out: all row-window DMAs of this tile in flight at once
    for j in range(tile):
        dma(j).start()
    for j in range(tile):
        dma(j).wait()

    # exact integer select: out[j, c] = buf[j, offs[j, c]]
    col = jax.lax.broadcasted_iota(jnp.int32, (tile, k, window), 2)
    offs = offs_ref[:, :]
    hit = col == offs[:, :, None]
    vals = buf[:, :].reshape(tile, 1, window)
    out_ref[:, :] = jnp.sum(jnp.where(hit, vals, 0), axis=2)


@functools.partial(jax.jit, static_argnames=("tile", "window", "k", "interpret"))
def _run(indices, start, offs, tile, window, k, interpret):
    Sp = start.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # start addresses
        grid=(Sp // tile,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # indices stay in HBM
            pl.BlockSpec((tile, k), lambda i, *_: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile, k), lambda i, *_: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((tile, window), jnp.int32),
            pltpu.SemaphoreType.DMA((tile,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, tile, window, k),
        out_shape=jax.ShapeDtypeStruct((Sp, k), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(start, indices, offs)


def sample_layer_windowed(topo, seeds, num_seeds, k: int, key,
                          window: int = DEFAULT_WINDOW, tile: int = 8,
                          interpret: bool | None = None):
    """Windowed Pallas sampling; same (S, K)/-1 padded contract as
    ops.sample.sample_layer.

    Requires an HBM-resident int32 ``indices`` with edge_count >= window
    (callers fall back to the XLA path otherwise).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    E = topo.indices.shape[0]
    if E < window:
        raise ValueError(f"edge_count {E} < window {window}; use the XLA path")
    if E - window > jnp.iinfo(jnp.int32).max:
        # window starts ride scalar-prefetch SMEM as int32; past 2^31 edges
        # they would wrap (the XLA path keeps indptr dtype and stays exact)
        raise ValueError(
            f"edge_count {E} exceeds the int32 windowed-DMA range; "
            "use the XLA path"
        )
    if k > window:
        # counts reports min(deg, k); with k > window only `window` lanes
        # could ever be valid and counts would overstate them
        raise ValueError(f"fanout k={k} must be <= window={window}")

    S = seeds.shape[0]
    valid = (jnp.arange(S) < num_seeds) & (seeds >= 0)
    s = jnp.where(valid, seeds, 0)
    # jnp view of indptr: a host-numpy indptr indexed by a traced ``s``
    # raises TracerArrayConversionError, so the windowed path silently
    # lost its jit/lowering story (caught by graftaudit's pallas target)
    indptr = jnp.asarray(topo.indptr)
    base = indptr[s]  # keep indptr dtype: values can exceed int32 ranges
    deg = (indptr[s + 1] - base).astype(jnp.int32)
    deg = jnp.where(valid, deg, 0)

    kr, kj, kw = jax.random.split(key, 3)
    # window placement: whole row when it fits, else uniform aligned window
    max_start = jnp.maximum(deg - window, 0)
    r = jax.random.randint(kr, (S,), 0, max_start + 1, dtype=jnp.int32)
    wlen = jnp.minimum(deg, window)
    # distinct offsets within the window (deg<=k rows: take-all, CSR order),
    # plus a uniform rotation so marginals are exactly k/wlen even when
    # wlen % k != 0 (same construction as the XLA path)
    offs, sel_mask = stratified_offsets(kj, wlen, k)
    offs = rotate_offsets(kw, offs, wlen, k)

    # window never leaves the array (computed in indptr dtype, cast only
    # after the clip bounds it under 2^31 — checked above)
    start_wide = jnp.clip(base + r.astype(base.dtype), 0, E - window)
    # the clip can shift a tail-of-array row's window left of base+r; the
    # offsets then still land inside the row because offs < wlen <= deg
    off_base = ((base + r.astype(base.dtype)) - start_wide).astype(jnp.int32)
    start = start_wide.astype(jnp.int32)
    offs = offs + off_base[:, None]

    pad = (-S) % tile
    if pad:
        start = jnp.concatenate([start, jnp.zeros(pad, start.dtype)])
        offs = jnp.concatenate([offs, jnp.zeros((pad, k), offs.dtype)])

    nbr = _run(
        topo.indices.astype(jnp.int32), start, offs, tile, window, k, interpret
    )[:S]

    mask = valid[:, None] & sel_mask
    nbr = jnp.where(mask, nbr, -1)
    counts = jnp.where(valid, jnp.minimum(deg, k), 0)
    return nbr, counts
