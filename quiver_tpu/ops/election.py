"""Measured kernel elections (pallas vs xla) with one shared disk cache.

The gather election (feature/feature.py, the ``quiver_tensor_gather``
precedent) and the sample election (sampling/sampler.py, the fused
megakernel) follow one contract, factored here:

1. an explicit ``kernel="pallas"|"xla"`` bypasses everything (fail loudly
   on request);
2. ``kernel="auto"`` off-TPU resolves to xla (the Pallas CPU interpret
   path is correct but slow);
3. on TPU, auto runs a one-time correctness smoke (a Pallas regression
   degrades auto to xla with ONE warning — fail-safe, never fail-closed),
   then ELECTS BY MEASURED THROUGHPUT between the two kernels — "it
   compiled and returned right rows" is not evidence it is fast (VERDICT
   r3 item 4);
4. the election is memoised per process and persisted in ONE disk cache
   file shared by every election (``QUIVER_ELECTION_CACHE``, default
   ``~/.cache/quiver_tpu/kernel_elections.json``), keyed by election name
   and invalidated by (rev, jax version, device kind) so a kernel or
   toolchain change forces re-election instead of trusting stale numbers.
   The file is an optimization, never a failure source: a corrupt or
   truncated cache degrades to re-election with ONE warning (fail-safe,
   see :func:`tolerant_cache_read`) and every rewrite is an atomic
   publish (:func:`atomic_publish_bytes`) — both shared with the serving
   AOT executable cache (serving/aot.py);
5. ``env_var=pallas|xla`` (e.g. ``QUIVER_GATHER_KERNEL``,
   ``QUIVER_SAMPLE_KERNEL``) overrides the measurement.

Env-before-first-use: the force knob and ``QUIVER_ELECTION_CACHE`` are
resolved ONCE per process at the first auto resolution — the election
runs behind the first ``kernel="auto"`` call, which may sit inside a
traced body, where a per-call env read would freeze at first trace while
looking live (graftlint env-at-trace). Set them before the first
gather/sample; flipping them afterwards is inert
(tests/test_kernel_election.py pins this). Tests call ``reset()`` (and
reset ``_ELECTION_CACHE_PATH``) to simulate a fresh process.
"""

from __future__ import annotations

from collections.abc import Callable

import jax

from ..utils.trace import get_logger, warn_once

__all__ = [
    "KernelElection",
    "atomic_publish_bytes",
    "tolerant_cache_read",
    "validate_kernel_arg",
]


def validate_kernel_arg(kernel: str) -> str:
    """Eager argument check only — MUST NOT touch the JAX backend (object
    construction must stay cheap and never initialize/lock backend choice)."""
    if kernel not in ("auto", "pallas", "xla"):
        raise ValueError(f"kernel must be auto|pallas|xla, got {kernel!r}")
    return kernel


_ELECTION_CACHE_PATH: str | None = None


def _election_cache_path() -> str:
    """Disk-cache path shared by ALL elections (``QUIVER_ELECTION_CACHE``),
    resolved ONCE per process (env-before-first-use, see module docstring).
    Tests reset ``_ELECTION_CACHE_PATH`` to re-resolve."""
    global _ELECTION_CACHE_PATH
    if _ELECTION_CACHE_PATH is None:
        import os

        _ELECTION_CACHE_PATH = os.environ.get(
            "QUIVER_ELECTION_CACHE",
            os.path.expanduser("~/.cache/quiver_tpu/kernel_elections.json"),
        )
    return _ELECTION_CACHE_PATH


# -- shared disk-cache discipline (elections AND the serving AOT cache) -----
#
# Both persisted caches are pure *optimizations*: a hit skips a
# re-measurement (election) or a recompilation (serving/aot.py). They must
# therefore be fail-safe in both directions — a corrupt/truncated/
# unreadable file degrades to a miss with ONE process-wide warning (never
# a raise on the serve/train path), and a publish is atomic (readers of
# the shared file never observe a half-written blob, even with several
# replicas warming concurrently).

def tolerant_cache_read(path: str, reader, *, what: str,
                        child: str | None = None):
    """Fail-safe shared-cache read: ``reader(binary_file)`` or ``None``.

    A missing file is a silent miss; anything else (truncation, garbage
    bytes, a permission error, a reader that chokes) is a miss plus ONE
    warning per (process, path) — the caller recomputes and republishes
    over the bad file, so the warning self-heals.
    """
    try:
        with open(path, "rb") as f:
            return reader(f)
    except FileNotFoundError:
        return None
    except Exception as e:  # noqa: BLE001 — any corruption degrades to a
        # recompute; a cache must never be the thing that takes serving down
        warn_once(
            f"cache-unreadable:{path}",
            "%s cache %s unreadable (%s: %s); ignoring it — recomputing "
            "and republishing over it", what, path, type(e).__name__,
            str(e)[:200], child=child,
        )
        return None


def atomic_publish_bytes(path: str, data: bytes) -> None:
    """Atomically publish ``data`` at ``path`` (write temp + fsync +
    ``os.replace``): concurrent readers — other serving replicas warming
    from the same cache — see either the old blob or the new one, never a
    torn write. Raises ``OSError`` on failure; callers that treat the
    cache as optional catch it."""
    import os

    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp_path = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp_path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_path, path)
    except OSError:
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        raise


class KernelElection:
    """One named pallas-vs-xla election (see module docstring for the
    contract).

    ``smoke`` is a zero-arg correctness gate (False/raise degrades auto to
    xla); ``measure`` maps ``"pallas"|"xla"`` to a higher-is-better score
    in ``unit``. Both are called lazily at first auto resolution, never at
    construction. ``result`` exposes the decided election
    (``{"kernel", "how", ...}``) for tests and telemetry; ``reset()`` is
    the test seam simulating a fresh process (forgets the memo AND the
    pinned env force — not the shared cache-path pin, which
    tests/monkeypatch reset on the module).
    """

    def __init__(self, name: str, env_var: str, rev: int,
                 smoke: Callable[[], bool],
                 measure: Callable[[str], float],
                 unit: str = "GB/s", log_child: str | None = None):
        self.name = name
        self.env_var = env_var
        self.rev = int(rev)
        self._smoke = smoke
        self._measure = measure
        self.unit = unit
        self._log_child = log_child or name
        self.result: dict | None = None
        self._forced: str | None = None

    # -- env force (pinned at first use) ----------------------------------
    def forced(self) -> str:
        """The env force ("" = none), read ONCE per process."""
        if self._forced is None:
            import os

            self._forced = os.environ.get(self.env_var, "").strip().lower()
        return self._forced

    # -- disk cache (one file, nested by election name) -------------------
    def cache_key(self) -> str:
        return (f"rev{self.rev}-jax{jax.__version__}-"
                + str(jax.devices()[0].device_kind))

    def _load_blob(self) -> dict:
        """The whole shared cache file as a dict — ``{}`` on miss, and
        ``{}`` with ONE warning on a corrupt/truncated file (fail-safe to
        re-election, never a raise; tests/test_kernel_election.py pins
        it). A non-dict JSON document counts as corrupt too."""
        import json

        blob = tolerant_cache_read(
            _election_cache_path(), json.load,
            what="kernel-election", child=self._log_child,
        )
        if blob is not None and not isinstance(blob, dict):
            warn_once(
                f"cache-unreadable:{_election_cache_path()}:shape",
                "kernel-election cache %s holds a %s, not an object; "
                "ignoring it — re-electing and republishing over it",
                _election_cache_path(), type(blob).__name__,
                child=self._log_child,
            )
            return {}
        return blob or {}

    def _load_cached(self, cache_key: str) -> dict | None:
        entry = self._load_blob().get(self.name)
        if (isinstance(entry, dict) and entry.get("key") == cache_key
                and entry.get("kernel") in ("pallas", "xla")):
            return entry
        return None

    def _store(self, entry: dict) -> None:
        import json

        path = _election_cache_path()
        # drop anything that is not a nested election entry (e.g. a
        # pre-generalization flat gather_election.json pointed at by
        # QUIVER_ELECTION_CACHE)
        blob = {k: v for k, v in self._load_blob().items()
                if isinstance(v, dict) and "kernel" in v}
        blob[self.name] = entry
        try:
            atomic_publish_bytes(path, json.dumps(blob).encode("utf-8"))
        except OSError:
            pass

    # -- resolution --------------------------------------------------------
    # The instance-attribute form of the module-global resolve-once idiom:
    # the slow path (env pin, smoke, micro-bench, one log line each) runs
    # at most once per process, at or before the first trace —
    # env-before-first-use is documented in the module docstring and
    # pinned by tests/test_kernel_election.py.
    # graftlint: eager -- resolve-once barrier memoised on self.result; the smoke/micro-bench/log slow path runs at most once per process
    def elect(self) -> str:
        """TPU kernel=auto election: measured pallas-vs-xla, not compile
        success. Cached per process and on disk so every supervised
        benchmark subprocess doesn't re-pay the two micro-bench compiles."""
        if self.result is not None:
            return self.result["kernel"]
        log = get_logger(self._log_child)
        forced = self.forced()
        if forced in ("pallas", "xla"):
            self.result = {"kernel": forced, "how": "env override"}
            return forced
        smoke_ok = False
        try:
            smoke_ok = bool(self._smoke())
        except Exception as e:  # noqa: BLE001 — any smoke crash degrades
            log.warning(
                "%s pallas smoke raised (%s: %s); kernel=auto degrades to "
                "xla", self.name, type(e).__name__, str(e)[:200])
        if not smoke_ok:
            self.result = {"kernel": "xla", "how": "pallas smoke failed"}
            return "xla"
        cache_key = self.cache_key()
        cached = self._load_cached(cache_key)
        if cached is not None:
            self.result = {**cached, "how": "disk cache"}
            log.info("%s kernel=auto -> %s (cached election: %s)",
                     self.name, cached["kernel"], cached.get("score"))
            return cached["kernel"]
        try:
            score = {k: round(float(self._measure(k)), 2)
                     for k in ("xla", "pallas")}
            kernel = max(score, key=score.get)
        except Exception as e:  # noqa: BLE001 — a bench failure must not
            # take down every gather/sample; fall back to the safe default
            log.warning("%s kernel election failed (%s: %s); auto -> xla",
                        self.name, type(e).__name__, str(e)[:200])
            self.result = {"kernel": "xla", "how": "election failed"}
            return "xla"
        self.result = {"kernel": kernel, "score": score,
                       "key": cache_key, "how": "measured"}
        log.info("%s kernel=auto -> %s (measured %s: %s)",
                 self.name, kernel, self.unit, score)
        self._store({"kernel": kernel, "score": score, "key": cache_key})
        return kernel

    def resolve_request(self, kernel: str) -> str:
        """Resolve a kernel request. Touches the backend, so callers defer
        this to first use (never the constructor)."""
        validate_kernel_arg(kernel)
        if kernel != "auto":
            return kernel
        try:
            backend = jax.default_backend()
        except RuntimeError:
            return "xla"
        if backend != "tpu":
            return "xla"
        return self.elect()

    def reset(self) -> None:
        """Test seam: forget the in-process decision and the pinned env
        force, as a fresh process would."""
        self.result = None
        self._forced = None
