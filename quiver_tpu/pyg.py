"""PyG-style import path parity: ``from quiver_tpu.pyg import GraphSageSampler``
mirrors the reference's ``quiver.pyg`` subpackage (pyg/sage_sampler.py)."""

from .sampling.sampler import Adj, GraphSageSampler

__all__ = ["Adj", "GraphSageSampler"]
