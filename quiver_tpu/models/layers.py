"""Message-passing primitives over padded Adj blocks.

The reference delegates all modeling to PyG (SAGEConv etc. in example
scripts, examples/pyg/reddit_quiver.py:42-65); quiver-tpu ships its own
TPU-native GNN layers because PyG/torch are out of the build. Edges arrive
as padded ``edge_index`` (2, E) with -1 sentinels (source = frontier-local
id, target = seed-local id).

Two aggregation paths, identical results:

* **dense** (``fanout`` set — every sampler-built Adj): the sampler's edge
  layout is regular (lane ``s*fanout + k`` targets seed ``s``), so
  aggregation is a masked ``(num_dst, fanout, F)`` reshape + axis-1
  reduction — zero scatters. XLA serializes general scatters on TPU
  (r3 link characterization, docs/TPU_MEASUREMENTS_R3.md), so on the
  training path this is the difference between VPU-speed reductions and a
  per-edge loop.
* **segment** (``fanout=None``): ``jax.ops.segment_sum`` with an overflow
  bucket for invalid lanes — kept for hand-built/irregular Adjs and as the
  differential-test oracle.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

__all__ = [
    "segment_mean_aggregate",
    "segment_softmax",
    "fanout_softmax",
    "fanout_sum_aggregate",
    "gather_src",
    "zero_scatter_counts",
    "occurrence_counts",
    "resolve_counts_strategy",
]


_counts_strategy: str | None = None


def resolve_counts_strategy() -> str:
    """The ``QUIVER_COUNTS`` histogram strategy, resolved ONCE per process.

    Resolution (env override, else platform default — see
    ``core.config.resolve_platform_strategy``) used to happen at trace time
    inside jitted model code, which implied an env var read on every
    retrace and made it look like ``QUIVER_COUNTS`` could flip a live
    model. It cannot: jit caches keep whatever strategy they were traced
    with. The first call — op construction / first model trace — pins the
    strategy for the process; set ``QUIVER_COUNTS`` BEFORE constructing or
    tracing any model that counts (chip-window forcing must precede the
    first trace)."""
    global _counts_strategy
    if _counts_strategy is None:
        from ..core.config import resolve_platform_strategy

        _counts_strategy = resolve_platform_strategy(
            "QUIVER_COUNTS", ("scan", "scatter"), tpu_default="scan",
            other_default="scatter",
        )
    return _counts_strategy


_check_cache: bool | None = None


def _check_enabled() -> bool:
    """QUIVER_CHECK=1 turns on the debug-mode layout assertions.

    Resolved ONCE per process (graftlint env-at-trace): the check gate is
    evaluated inside traced aggregation code, where a per-call env read
    would freeze at first trace anyway while looking like a live switch.
    Set QUIVER_CHECK before the first model trace; tests reset
    ``_check_cache`` to re-resolve."""
    global _check_cache
    if _check_cache is None:
        _check_cache = os.environ.get("QUIVER_CHECK", "0") not in (
            "", "0", "false", "False"
        )
    return _check_cache


def _raise_layout_violation(count):
    if int(count) > 0:
        raise AssertionError(
            f"QUIVER_CHECK: {int(count)} valid edge lanes violate the "
            "regular layout dst == repeat(arange(num_dst), fanout) that "
            "the dense aggregation path trusts; this Adj's fanout claim "
            "is wrong and the dense path would mis-aggregate"
        )


def _check_regular_layout(dst, valid, num_dst: int, fanout: int) -> None:
    """Debug-mode assertion of the regular-layout claim the dense-path
    gate trusts (ADVICE layers.py:93): lane ``s*fanout + k`` targets seed
    ``s`` on every valid lane. jit-composable via debug.callback; only
    traced when QUIVER_CHECK is set, so the default path pays nothing."""
    expected = jnp.repeat(
        jnp.arange(num_dst, dtype=dst.dtype), fanout
    )
    bad = jnp.sum(((dst != expected) & valid).astype(jnp.int32))
    jax.debug.callback(_raise_layout_violation, bad)


def gather_src(x, src):
    """Gather per-edge source features; invalid lanes (src == -1) give zeros."""
    valid = src >= 0
    h = x[jnp.clip(src, 0)]
    return jnp.where(valid[:, None], h, 0.0), valid


def zero_scatter_counts(ids, valid, n: int, dtype=jnp.float32):
    """Occurrence count of each value in [0, n) among ``ids[valid]`` —
    a histogram with no scatter: sort (invalid lanes to the sentinel n),
    then bucket edges via one vectorized binary search. The zero-scatter
    analogue of ``segment_sum(ones, ids)`` for backends where XLA
    serializes scatters (same rationale as ops.reindex dedup="scan")."""
    sv = jnp.sort(jnp.where(valid, ids, n))
    edges = jnp.searchsorted(sv, jnp.arange(n + 1, dtype=ids.dtype))
    return (edges[1:] - edges[:-1]).astype(dtype)


def occurrence_counts(ids, valid, n: int, dtype=jnp.float32):
    """Histogram of ``ids[valid]`` over [0, n), strategy picked per
    platform (the counts-shaped sibling of ops.reindex.resolve_dedup):
    zero-scatter sort+searchsorted on TPU, one scalar scatter-add
    elsewhere. ``QUIVER_COUNTS=scan|scatter`` overrides — resolved once
    per process at op construction (:func:`resolve_counts_strategy`), so
    the env force must be set before the first model trace."""
    how = resolve_counts_strategy()
    if how == "scan":
        return zero_scatter_counts(ids, valid, n, dtype)
    return jax.ops.segment_sum(
        valid.astype(dtype), jnp.where(valid, ids, n), num_segments=n + 1
    )[:n]


def fanout_sum_aggregate(messages, valid, num_dst: int, fanout: int):
    """Masked dense sum over the regular sampler layout: ``messages``
    (num_dst*fanout, ...) -> (num_dst, ...), zero scatters. The shared
    reduction behind every conv family's dense path."""
    validb = valid.reshape(valid.shape + (1,) * (messages.ndim - 1))
    m = jnp.where(validb, messages, 0)
    return m.reshape((num_dst, fanout) + messages.shape[1:]).sum(axis=1)


def segment_mean_aggregate(messages, dst, valid, num_dst: int,
                           fanout: int | None = None):
    """Mean-aggregate edge messages into target nodes.

    With ``fanout`` (regular sampler layout, ``E == num_dst * fanout``) the
    aggregate is a dense masked reduction; otherwise invalid lanes are
    routed to an overflow segment (index num_dst) and sliced off — the
    padded-shape analogue of skipping masked edges.
    """
    if fanout is not None and messages.shape[0] == num_dst * fanout:
        if _check_enabled():
            _check_regular_layout(dst, valid, num_dst, fanout)
        total = fanout_sum_aggregate(messages, valid, num_dst, fanout)
        cnt = valid.reshape(num_dst, fanout).sum(1).astype(messages.dtype)
        return total / jnp.maximum(cnt, 1.0)[:, None]
    if fanout is not None:
        from ..utils.trace import info_once

        # the gate failed on SHAPE: fanout promised the dense layout but
        # E != num_dst*fanout, so this aggregation silently reverts to the
        # segment-scatter path (XLA serializes scatters on TPU) — make the
        # perf regression visible (ADVICE layers.py:93)
        info_once(
            f"dense-gate-fallback-{messages.shape[0]}-{num_dst}-{fanout}",
            "Adj.fanout=%d set but E=%d != num_dst*fanout=%d; falling back "
            "to the segment-scatter aggregation path (slow on TPU)",
            fanout, messages.shape[0], num_dst * fanout,
        )
    seg = jnp.where(valid, dst, num_dst)
    total = jax.ops.segment_sum(messages, seg, num_segments=num_dst + 1)[:num_dst]
    cnt = jax.ops.segment_sum(valid.astype(messages.dtype), seg, num_segments=num_dst + 1)[:num_dst]
    return total / jnp.maximum(cnt, 1.0)[:, None]


def fanout_softmax(logits, valid, num_dst: int, fanout: int):
    """Dense counterpart of ``segment_softmax`` for the regular layout:
    per-edge softmax weights over each target's ``fanout`` lanes, no
    scatters. ``logits`` (E, ...) -> weights (E, ...)."""
    shape = logits.shape
    validb = valid.reshape(valid.shape + (1,) * (logits.ndim - 1))
    neg = jnp.finfo(logits.dtype).min
    g = jnp.where(validb, logits, neg).reshape((num_dst, fanout) + shape[1:])
    gmax = g.max(axis=1, keepdims=True)  # finite even for all-invalid rows
    # all-invalid rows are handled by the g > neg mask (their exp(0) lanes
    # are zeroed), not by the max
    expv = jnp.where(g > neg, jnp.exp(g - gmax), 0.0)
    denom = jnp.maximum(expv.sum(axis=1, keepdims=True),
                        jnp.finfo(logits.dtype).tiny)
    return (expv / denom).reshape(shape)


def segment_softmax(logits, seg, valid, num_seg: int):
    """Numerically-stable softmax over edges grouped by target segment.

    ``logits`` may be (E,) or (E, ...) — trailing dims (e.g. attention
    heads) are softmaxed independently. Exercises the pattern a GAT needs
    (BASELINE.json config 4: "attention aggregation, exercises
    segment-softmax").
    """
    validb = valid.reshape(valid.shape + (1,) * (logits.ndim - 1))
    seg_safe = jnp.where(valid, seg, num_seg)
    neg = jnp.finfo(logits.dtype).min
    masked = jnp.where(validb, logits, neg)
    seg_max = jax.ops.segment_max(masked, seg_safe, num_segments=num_seg + 1)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    shifted = jnp.where(validb, logits - seg_max[seg_safe], neg)
    expv = jnp.where(validb, jnp.exp(shifted), 0.0)
    denom = jax.ops.segment_sum(expv, seg_safe, num_segments=num_seg + 1)
    return expv / jnp.maximum(denom[seg_safe], jnp.finfo(logits.dtype).tiny)
