"""Message-passing primitives over padded Adj blocks.

The reference delegates all modeling to PyG (SAGEConv etc. in example
scripts, examples/pyg/reddit_quiver.py:42-65); quiver-tpu ships its own
TPU-native GNN layers because PyG/torch are out of the build. Edges arrive
as padded ``edge_index`` (2, E) with -1 sentinels (source = frontier-local
id, target = seed-local id); aggregation uses ``jax.ops.segment_sum`` with an
overflow bucket for invalid lanes — scatter-free, shape-static, MXU-friendly
(all matmuls are dense (N, F) x (F, F')).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["segment_mean_aggregate", "segment_softmax", "gather_src"]


def gather_src(x, src):
    """Gather per-edge source features; invalid lanes (src == -1) give zeros."""
    valid = src >= 0
    h = x[jnp.clip(src, 0)]
    return jnp.where(valid[:, None], h, 0.0), valid


def segment_mean_aggregate(messages, dst, valid, num_dst: int):
    """Mean-aggregate edge messages into target nodes.

    Invalid lanes are routed to an overflow segment (index num_dst) and
    sliced off — the padded-shape analogue of skipping masked edges.
    """
    seg = jnp.where(valid, dst, num_dst)
    total = jax.ops.segment_sum(messages, seg, num_segments=num_dst + 1)[:num_dst]
    cnt = jax.ops.segment_sum(valid.astype(messages.dtype), seg, num_segments=num_dst + 1)[:num_dst]
    return total / jnp.maximum(cnt, 1.0)[:, None]


def segment_softmax(logits, seg, valid, num_seg: int):
    """Numerically-stable softmax over edges grouped by target segment.

    ``logits`` may be (E,) or (E, ...) — trailing dims (e.g. attention
    heads) are softmaxed independently. Exercises the pattern a GAT needs
    (BASELINE.json config 4: "attention aggregation, exercises
    segment-softmax").
    """
    validb = valid.reshape(valid.shape + (1,) * (logits.ndim - 1))
    seg_safe = jnp.where(valid, seg, num_seg)
    neg = jnp.finfo(logits.dtype).min
    masked = jnp.where(validb, logits, neg)
    seg_max = jax.ops.segment_max(masked, seg_safe, num_segments=num_seg + 1)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    shifted = jnp.where(validb, logits - seg_max[seg_safe], neg)
    expv = jnp.where(validb, jnp.exp(shifted), 0.0)
    denom = jax.ops.segment_sum(expv, seg_safe, num_segments=num_seg + 1)
    return expv / jnp.maximum(denom[seg_safe], jnp.finfo(logits.dtype).tiny)
