"""Graph Attention Network over padded Adj blocks.

The reference delegates GAT to PyG (its ogbn-products GAT config is plain
``torch_geometric.nn.GATConv`` fed by quiver's sampler/feature — BASELINE
config 4 "attention aggregation, exercises segment-softmax"). quiver-tpu
ships a TPU-native GATConv: multi-head additive attention with a
segment-softmax over the padded edge list (-1 sentinel lanes excluded), all
dense matmuls batched over heads so the MXU sees (E, H*F)-shaped work.

Semantics follow PyG's GATConv (v1, Velickovic et al.):
  e_ij  = LeakyReLU(a_l . (W h_j) + a_r . (W h_i))
  alpha = softmax_i(e_ij)   (over j in N(i), per head)
  h_i'  = concat_heads( sum_j alpha_ij W h_j )   [+ mean over heads if
          ``concat=False``, as PyG does for the output layer]
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import flax.linen as nn

from .layers import fanout_softmax, fanout_sum_aggregate, segment_softmax

__all__ = ["GATConv", "GAT"]


class GATConv(nn.Module):
    """Multi-head graph attention over a padded edge block.

    Args:
      features: per-head output width F.
      heads: number of attention heads H.
      concat: concatenate heads (output H*F) or average them (output F).
      negative_slope: LeakyReLU slope for attention logits.
    """

    features: int
    heads: int = 1
    concat: bool = True
    negative_slope: float = 0.2
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    def setup(self):
        # setup-style (attribute/param names keep the original compact
        # module's tree: lin/att_l/att_r/bias) so full-graph layer-wise
        # inference (models/inference.py) can reuse trained weights through
        # the project/finish methods
        H, F = self.heads, self.features
        self.lin = nn.Dense(H * F, use_bias=False, dtype=self.dtype,
                            name="lin")
        self.att_l = self.param(
            "att_l", nn.initializers.glorot_uniform(), (H, F)
        )
        self.att_r = self.param(
            "att_r", nn.initializers.glorot_uniform(), (H, F)
        )
        self.bias = self.param(
            "bias", nn.initializers.zeros,
            (H * F,) if self.concat else (F,),
        )

    def project(self, x):
        """Node-level halves of the attention: per-head projections plus the
        a_l·Wh / a_r·Wh summands (per-edge logits are their sum) — avoids
        forming the (E, H, 2F) concat the naive formulation would need."""
        H, F = self.heads, self.features
        h_all = self.lin(x).reshape(x.shape[0], H, F)
        alpha_src = (h_all * self.att_l).sum(-1)  # (N, H)
        alpha_dst = (h_all * self.att_r).sum(-1)  # (N, H)
        return h_all, alpha_src, alpha_dst

    def finish(self, out):
        """(num_dst, H, F) aggregated messages -> layer output (concat or
        head-mean, + bias)."""
        num_dst = out.shape[0]
        if self.concat:
            return out.reshape(num_dst, self.heads * self.features) + self.bias
        return out.mean(axis=1) + self.bias

    def __call__(self, x, edge_index, num_dst: int, fanout: int | None = None):
        src, dst = edge_index[0], edge_index[1]
        valid = (src >= 0) & (dst >= 0)
        src_safe = jnp.clip(src, 0)
        dense = fanout is not None and src.shape[0] == num_dst * fanout

        h_all, alpha_src, alpha_dst = self.project(x)
        alpha_dst = alpha_dst[:num_dst]

        logits = alpha_src[src_safe] + alpha_dst[jnp.clip(dst, 0, num_dst - 1)]
        logits = nn.leaky_relu(logits, self.negative_slope)  # (E, H)
        # softmax over each destination's edges, all heads at once
        # (computed in f32 via the att-param promotion for stability, then
        # downcast so the big (E, H, F) message traffic runs at the compute
        # dtype rather than silently promoting back to f32)
        if dense:
            alpha = fanout_softmax(logits, valid, num_dst, fanout)  # (E, H)
        else:
            dst_safe = jnp.where(valid, dst, num_dst)  # overflow segment
            alpha = segment_softmax(logits, dst_safe, valid, num_dst)
        alpha = alpha.astype(h_all.dtype)

        msgs = h_all[src_safe] * alpha[:, :, None]  # (E, H, F)
        msgs = jnp.where(valid[:, None, None], msgs, 0.0)
        H, F = self.heads, self.features
        if dense:
            return self.finish(fanout_sum_aggregate(msgs, valid, num_dst, fanout))
        out = jnp.zeros((num_dst + 1, H, F), msgs.dtype).at[dst_safe].add(msgs)
        return self.finish(out[:num_dst])


class GAT(nn.Module):
    """Multi-layer GAT consuming sampler output (adjs deepest-first).

    Mirrors the PyG mini-batch GAT recipe: hidden layers concat heads + ELU;
    the output layer averages heads (concat=False) into ``num_classes``.
    """

    hidden: int
    num_classes: int
    num_layers: int = 2
    heads: int = 4
    dropout: float = 0.5
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    @nn.compact
    def __call__(self, x, adjs: Sequence, *, train: bool = False):
        if len(adjs) != self.num_layers:
            raise ValueError(
                f"model has {self.num_layers} layers but got {len(adjs)} adjs; "
                "sampler sizes and num_layers must match"
            )
        if self.dtype is not None:
            x = x.astype(self.dtype)
        for i, adj in enumerate(adjs):
            num_dst = adj.size[1]
            last = i == self.num_layers - 1
            x = GATConv(
                features=self.num_classes if last else self.hidden,
                heads=1 if last else self.heads,
                concat=not last,
                dtype=self.dtype,
                name=f"conv{i}",
            )(x, adj.edge_index, num_dst, getattr(adj, "fanout", None))
            if not last:
                x = nn.elu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        # log-softmax in f32: bf16 has too little mantissa for stable NLL
        return nn.log_softmax(x.astype(jnp.float32), axis=-1)
