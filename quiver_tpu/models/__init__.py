"""TPU-native GNN model layer (the reference delegates this to PyG/DGL).

Models consume the sampler's padded Adj contract directly; see
models/layers.py for the segment-op primitives and models/inference.py for
full-neighbor layer-wise inference (the reference's ``model.inference``
evaluation path, examples/pyg/reddit_quiver.py:68-92)."""

from .gat import GAT
from .gcn import GCN, GCNConv
from .gin import GIN, GINConv
from .inference import (
    full_neighbor_mean,
    gat_layerwise_inference,
    gcn_layerwise_inference,
    gin_layerwise_inference,
    rgcn_layerwise_inference,
    sage_layerwise_inference,
)
from .rgcn import RGCN
from .sage import GraphSAGE, SAGEConv

__all__ = [
    "GAT",
    "GCN",
    "GCNConv",
    "GIN",
    "GINConv",
    "GraphSAGE",
    "RGCN",
    "SAGEConv",
    "full_neighbor_mean",
    "gat_layerwise_inference",
    "gcn_layerwise_inference",
    "gin_layerwise_inference",
    "rgcn_layerwise_inference",
    "sage_layerwise_inference",
]
