"""GraphSAGE in flax over padded Adj blocks.

Functional parity with the SAGE model of the reference's acceptance example
(torch-quiver examples/pyg/reddit_quiver.py:42-65: per-layer SAGEConv, ReLU +
dropout between layers, log-softmax head; layers consumed deepest-first with
``x_target = x[:size[1]]``). PyG's SAGEConv(mean) is
``W_l · mean(neighbors) + W_r · x_self``; we keep that form so accuracy
comparisons carry over.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import flax.linen as nn

from .layers import gather_src, segment_mean_aggregate

__all__ = ["SAGEConv", "GraphSAGE"]


class SAGEConv(nn.Module):
    features: int
    # computation dtype (None = float32): "bfloat16" runs the matmuls and
    # aggregation in bf16 on the MXU while params stay float32 — the
    # standard TPU mixed-precision recipe. The reference is fp32-only.
    dtype: str | None = None

    def setup(self):
        # attribute names keep the original compact-module param tree
        # ("lin_l"/"lin_r"), so existing checkpoints/params stay valid
        self.lin_l = nn.Dense(self.features, dtype=self.dtype, name="lin_l")
        self.lin_r = nn.Dense(
            self.features, use_bias=False, dtype=self.dtype, name="lin_r"
        )

    def combine(self, agg, x_self):
        """W_l · aggregated-neighbors + W_r · x_self — exposed separately so
        full-graph layer-wise inference (models/inference.py) can reuse the
        trained weights on aggregates it computed itself."""
        return self.lin_l(agg) + self.lin_r(x_self)

    def __call__(self, x, edge_index, num_dst: int, fanout: int | None = None):
        src, dst = edge_index[0], edge_index[1]
        msgs, valid = gather_src(x, src)
        agg = segment_mean_aggregate(msgs, jnp.clip(dst, 0), valid, num_dst,
                                     fanout=fanout)
        return self.combine(agg, x[:num_dst])


class GraphSAGE(nn.Module):
    """Multi-layer GraphSAGE consuming sampler output (adjs deepest-first)."""

    hidden: int
    num_classes: int
    num_layers: int = 2
    dropout: float = 0.5
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    @nn.compact
    def __call__(self, x, adjs: Sequence, *, train: bool = False):
        if len(adjs) != self.num_layers:
            raise ValueError(
                f"model has {self.num_layers} layers but got {len(adjs)} adjs; "
                "sampler sizes and num_layers must match"
            )
        if self.dtype is not None:
            x = x.astype(self.dtype)
        for i, adj in enumerate(adjs):
            num_dst = adj.size[1]
            feats = self.num_classes if i == self.num_layers - 1 else self.hidden
            x = SAGEConv(feats, dtype=self.dtype, name=f"conv{i}")(
                x, adj.edge_index, num_dst, getattr(adj, "fanout", None)
            )
            if i != self.num_layers - 1:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        # log-softmax in f32: bf16 has too little mantissa for stable NLL
        return nn.log_softmax(x.astype(jnp.float32), axis=-1)
