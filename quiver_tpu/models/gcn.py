"""Graph Convolutional Network over padded Adj blocks.

The reference delegates modeling to PyG (its examples are SAGE/GAT configs);
quiver-tpu ships a TPU-native GCNConv for API breadth — GCN is the most
common GNN a torch-quiver user would bring along. Semantics follow Kipf &
Welling with the standard mini-batch adaptation (DGL GraphConv
``norm='both'`` on blocks): self-loops added per destination, symmetric
normalization by in-block degrees,

    h_i' = b + W · Σ_{j ∈ N(i) ∪ {i}}  h_j / sqrt(d_j · d_i)

where d are degrees of the self-loop-augmented block. On a block that
covers the full graph (every node a seed, full fanout) this is exactly
full-graph GCN, which is what :func:`gcn_layerwise_inference` computes
layer-wise with global degrees.

All shapes static: the self-loop edges are a fixed (num_dst,) append — the
seeds-first frontier contract guarantees destination i has source-local id
i — and degrees come from ``segment_sum`` with the usual overflow bucket.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn

from .layers import fanout_sum_aggregate, occurrence_counts

__all__ = ["GCNConv", "GCN"]


class GCNConv(nn.Module):
    features: int
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    def setup(self):
        # PyG GCNConv parameter shape: weight without bias + separate bias
        self.lin = nn.Dense(self.features, use_bias=False, dtype=self.dtype,
                            name="lin")
        self.bias = self.param("bias", nn.initializers.zeros,
                               (self.features,))

    def combine(self, agg):
        """W · (normalized aggregate) + b — exposed for layer-wise
        inference, which computes the normalized aggregate itself."""
        return self.lin(agg) + self.bias

    def __call__(self, x, edge_index, num_dst: int, fanout: int | None = None):
        N = x.shape[0]
        src, dst = edge_index[0], edge_index[1]
        valid = (src >= 0) & (dst >= 0)
        one = valid.astype(x.dtype)
        dense = fanout is not None and src.shape[0] == num_dst * fanout

        # in-block degrees of the self-loop-augmented graph: every dst gets
        # +1 (its loop), and a src that is also a dst carries that same loop
        # edge on its src side. src degrees have no regular layout (sources
        # land anywhere in the frontier), so they go through the
        # platform-resolved histogram either way.
        deg_src = occurrence_counts(src, valid, N, dtype=x.dtype)
        deg_src = deg_src.at[:num_dst].add(1.0)
        if dense:
            deg_dst = one.reshape(num_dst, fanout).sum(axis=1) + 1.0
        else:
            dst_safe = jnp.where(valid, dst, num_dst)
            deg_dst = jax.ops.segment_sum(
                one, dst_safe, num_segments=num_dst + 1)[:num_dst] + 1.0

        inv_s_src = jax.lax.rsqrt(jnp.maximum(deg_src, 1.0))
        inv_s_dst = jax.lax.rsqrt(deg_dst)  # >= 1 by the self loop

        h = x * inv_s_src[:, None]  # pre-scale once per node, not per edge
        msgs = jnp.where(valid[:, None], h[jnp.clip(src, 0)], 0.0)
        if dense:
            agg = fanout_sum_aggregate(msgs, valid, num_dst, fanout)
        else:
            agg = jax.ops.segment_sum(
                msgs, dst_safe, num_segments=num_dst + 1)[:num_dst]
        agg = agg + h[:num_dst]  # the self loop, already src-scaled
        agg = agg * inv_s_dst[:, None]
        return self.combine(agg)


class GCN(nn.Module):
    """Multi-layer GCN consuming sampler output (adjs deepest-first)."""

    hidden: int
    num_classes: int
    num_layers: int = 2
    dropout: float = 0.5
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    @nn.compact
    def __call__(self, x, adjs: Sequence, *, train: bool = False):
        if len(adjs) != self.num_layers:
            raise ValueError(
                f"model has {self.num_layers} layers but got {len(adjs)} adjs; "
                "sampler sizes and num_layers must match"
            )
        if self.dtype is not None:
            x = x.astype(self.dtype)
        for i, adj in enumerate(adjs):
            num_dst = adj.size[1]
            feats = self.num_classes if i == self.num_layers - 1 else self.hidden
            x = GCNConv(feats, dtype=self.dtype, name=f"conv{i}")(
                x, adj.edge_index, num_dst, getattr(adj, "fanout", None)
            )
            if i != self.num_layers - 1:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        # log-softmax in f32: bf16 has too little mantissa for stable NLL
        return nn.log_softmax(x.astype(jnp.float32), axis=-1)
