"""Graph Isomorphism Network over padded Adj blocks.

The reference delegates modeling to PyG (its examples are SAGE/GAT
configs); quiver-tpu ships a TPU-native GINConv for API breadth — GIN (Xu
et al., "How Powerful are Graph Neural Networks?") is the standard
expressiveness-maximal aggregator a torch-quiver user would bring along.
Semantics follow PyG ``GINConv``:

    h_i' = MLP( (1 + eps) · x_i  +  Σ_{j ∈ N(i)} x_j )

with SUM aggregation (no normalization — that is the point of GIN) and the
customary 2-layer MLP (Dense → ReLU → Dense). ``eps`` is 0 and fixed by
default (PyG's default); ``train_eps=True`` makes it a learnable scalar.

All shapes static: the self term is ``x[:num_dst]`` by the seeds-first
frontier contract (destination i has source-local id i), and the neighbor
sum is a ``segment_sum`` with the usual overflow bucket for padding lanes.
On a block that covers the full graph this is exactly full-graph GIN,
which :func:`quiver_tpu.models.inference.gin_layerwise_inference` computes
layer-wise with global degrees.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn

from .layers import fanout_sum_aggregate

__all__ = ["GINConv", "GIN"]


class GINConv(nn.Module):
    features: int
    mlp_hidden: int | None = None  # default: same as features
    train_eps: bool = False
    eps_init: float = 0.0
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    def setup(self):
        width = self.mlp_hidden or self.features
        self.lin1 = nn.Dense(width, dtype=self.dtype, name="lin1")
        self.lin2 = nn.Dense(self.features, dtype=self.dtype, name="lin2")
        if self.train_eps:
            self.eps = self.param("eps", nn.initializers.constant(self.eps_init), ())
        else:
            self.eps = self.eps_init

    def combine(self, z):
        """MLP((1+eps)x + Σ neighbors) — exposed for layer-wise inference,
        which builds the aggregate itself."""
        return self.lin2(nn.relu(self.lin1(z)))

    def __call__(self, x, edge_index, num_dst: int, fanout: int | None = None):
        src, dst = edge_index[0], edge_index[1]
        valid = (src >= 0) & (dst >= 0)

        msgs = jnp.where(valid[:, None], x[jnp.clip(src, 0)], 0.0)
        if fanout is not None and msgs.shape[0] == num_dst * fanout:
            # regular sampler layout: dense reduction, zero scatters
            agg = fanout_sum_aggregate(msgs, valid, num_dst, fanout)
        else:
            dst_safe = jnp.where(valid, dst, num_dst)  # padding -> overflow
            agg = jax.ops.segment_sum(
                msgs, dst_safe, num_segments=num_dst + 1)[:num_dst]
        z = agg + (1.0 + self.eps) * x[:num_dst]
        return self.combine(z)


class GIN(nn.Module):
    """Multi-layer GIN consuming sampler output (adjs deepest-first)."""

    hidden: int
    num_classes: int
    num_layers: int = 2
    dropout: float = 0.5
    train_eps: bool = False
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    @nn.compact
    def __call__(self, x, adjs: Sequence, *, train: bool = False):
        if len(adjs) != self.num_layers:
            raise ValueError(
                f"model has {self.num_layers} layers but got {len(adjs)} adjs; "
                "sampler sizes and num_layers must match"
            )
        if self.dtype is not None:
            x = x.astype(self.dtype)
        for i, adj in enumerate(adjs):
            num_dst = adj.size[1]
            feats = self.num_classes if i == self.num_layers - 1 else self.hidden
            x = GINConv(feats, mlp_hidden=self.hidden,
                        train_eps=self.train_eps, dtype=self.dtype,
                        name=f"conv{i}")(x, adj.edge_index, num_dst,
                                   getattr(adj, "fanout", None))
            if i != self.num_layers - 1:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        # log-softmax in f32: bf16 has too little mantissa for stable NLL
        return nn.log_softmax(x.astype(jnp.float32), axis=-1)
