"""Full-neighbor layer-wise inference over the whole graph.

The reference's acceptance examples evaluate with a layer-wise full-neighbor
pass — ``model.inference`` walks one layer at a time over ALL nodes using
*all* edges (torch-quiver examples/pyg/reddit_quiver.py:68-92, fed by a
``sizes=[-1]`` NeighborSampler). That is the path behind the published
Reddit accuracy, and it is cheaper than sampled k-hop evaluation because
each layer's embeddings are computed once and reused.

TPU redesign: a ``sizes=[-1]`` sampler is ragged and hub-hostile under
static shapes (one padded row per max-degree node). But full-neighbor mean
aggregation over every node at once is just a sparse matmul — so the
layer-wise pass becomes **chunked whole-graph segment aggregation**: walk the
CSR edge array in fixed-size chunks, gather source features, scatter-add
into a (N, F) accumulator, divide by degree, then apply the trained layer
weights via ``SAGEConv.combine``. Every chunk is one compiled program; no
sampling, no padding waste, no per-hub blowup.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.config import SampleMode
from ..core.memory import to_pinned_host
from ..ops.sample import staged_gather
from .sage import SAGEConv

__all__ = ["full_neighbor_mean", "sage_layerwise_inference"]


@functools.partial(
    jax.jit, donate_argnums=0, static_argnames=("chunk", "host")
)
def _accumulate_chunk(acc, x_all, indptr, indices, e0, chunk: int,
                      host: bool):
    """Scatter-add one edge chunk's source features into the accumulator.

    Row (destination) ids are recovered on device from ``indptr`` by binary
    search — no E-sized host-materialized row array. Out-of-range tail lanes
    (last chunk) are masked to a throwaway row. With ``host`` the edge
    array lives in pinned host memory and each chunk's ids stage through
    host compute (the beyond-HBM placement).
    """
    E = indices.shape[0]
    epos = e0 + jnp.arange(chunk, dtype=indptr.dtype)
    in_range = epos < E
    src = staged_gather(indices, jnp.where(in_range, epos, 0), host)
    dst = (
        jnp.searchsorted(indptr, epos, side="right").astype(jnp.int32) - 1
    )
    n = acc.shape[0] - 1  # last row is the mask bucket
    dst = jnp.where(in_range, jnp.clip(dst, 0, n - 1), n)
    msgs = x_all[src.astype(jnp.int32)]
    return acc.at[dst].add(msgs)


def _neighbor_mean_dev(indptr, indices, x_all, chunk: int,
                       host: bool = False):
    """full_neighbor_mean body on already-placed CSR arrays."""
    n, f = x_all.shape
    E = indices.shape[0]
    acc = jnp.zeros((n + 1, f), x_all.dtype)  # +1 = masked-lane bucket
    for e0 in range(0, max(E, 1), chunk):
        acc = _accumulate_chunk(
            acc, x_all, indptr, indices,
            jnp.asarray(e0, indptr.dtype), chunk, host,
        )
    deg = jnp.maximum(jnp.diff(indptr).astype(x_all.dtype), 1.0)
    return acc[:n] / deg[:, None]


def _place(topo, mode):
    """(indptr_dev, indices, host_flag): HBM puts everything on device;
    HOST keeps the big edge array in pinned host memory (falls back to
    device where the platform has no pinned_host space)."""
    mode = SampleMode.parse(mode)
    indptr = jnp.asarray(topo.indptr)
    if mode == SampleMode.HOST:
        indices, host = to_pinned_host(topo.indices)
        return indptr, indices, host
    return indptr, jnp.asarray(topo.indices), False


def full_neighbor_mean(topo, x_all, chunk: int = 1 << 21,
                       mode: str | SampleMode = SampleMode.HBM):
    """Mean of ALL neighbors' features for every node: (N, F) -> (N, F).

    ``topo`` is a host CSRTopo. ``mode="HBM"`` places the edge array on
    device (needs HBM alongside two (N, F) buffers); ``mode="HOST"`` keeps
    it in pinned host memory and stages each chunk's ids through host
    compute — graphs beyond HBM stay evaluable. Equivalent to ``D^-1 A X``
    with mean over incoming CSR neighbors; zero-degree rows aggregate to
    zeros, matching segment_mean_aggregate's empty-segment convention.
    """
    indptr, indices, host = _place(topo, mode)
    return _neighbor_mean_dev(indptr, indices, jnp.asarray(x_all), chunk,
                              host)


def sage_layerwise_inference(model, params, topo, x_all,
                             chunk: int = 1 << 21,
                             mode: str | SampleMode = SampleMode.HBM):
    """Layer-wise full-neighbor GraphSAGE inference (reference
    reddit_quiver.py:68-92 parity): returns (N, num_classes) log-probs for
    EVERY node, using all edges at every layer.

    Args:
      model: the trained GraphSAGE module (its hidden/num_classes/num_layers
        fields drive the pass).
      params: the trained parameter tree (``conv{i}`` children).
      topo: host CSRTopo.
      x_all: (N, F) input features (will be placed on device).
      chunk: edges per aggregation program.
      mode: "HBM" or "HOST" (pinned-host edge array for beyond-HBM graphs).
    """
    x = jnp.asarray(x_all)
    # place the (possibly multi-GB) CSR arrays once, not once per layer
    indptr, indices, host = _place(topo, mode)
    for i in range(model.num_layers):
        feats = (
            model.num_classes if i == model.num_layers - 1 else model.hidden
        )
        agg = _neighbor_mean_dev(indptr, indices, x, chunk, host)
        conv = SAGEConv(feats)
        x = conv.apply(
            {"params": params[f"conv{i}"]}, agg, x, method=SAGEConv.combine
        )
        if i != model.num_layers - 1:
            x = jax.nn.relu(x)
    return jax.nn.log_softmax(x, axis=-1)
