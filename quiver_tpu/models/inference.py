"""Full-neighbor layer-wise inference over the whole graph.

The reference's acceptance examples evaluate with a layer-wise full-neighbor
pass — ``model.inference`` walks one layer at a time over ALL nodes using
*all* edges (torch-quiver examples/pyg/reddit_quiver.py:68-92, fed by a
``sizes=[-1]`` NeighborSampler). That is the path behind the published
Reddit accuracy, and it is cheaper than sampled k-hop evaluation because
each layer's embeddings are computed once and reused.

TPU redesign: a ``sizes=[-1]`` sampler is ragged and hub-hostile under
static shapes (one padded row per max-degree node). But full-neighbor mean
aggregation over every node at once is just a sparse matmul — so the
layer-wise pass becomes **chunked whole-graph segment aggregation**: walk the
CSR edge array in fixed-size chunks, gather source features, scatter-add
into a (N, F) accumulator, divide by degree, then apply the trained layer
weights via ``SAGEConv.combine``. Every chunk is one compiled program; no
sampling, no padding waste, no per-hub blowup.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.config import SampleMode
from ..core.memory import to_pinned_host
from ..ops.sample import staged_gather
from .gat import GATConv
from .gcn import GCNConv
from .sage import SAGEConv

__all__ = [
    "full_neighbor_mean",
    "sage_layerwise_inference",
    "gat_layerwise_inference",
    "gcn_layerwise_inference",
    "gin_layerwise_inference",
    "rgcn_layerwise_inference",
]


def _edge_chunk(indptr, indices, e0, chunk: int, n: int, host: bool):
    """(src, dst, in_range) for edges [e0, e0+chunk).

    Row (destination) ids are recovered on device from ``indptr`` by binary
    search — no E-sized host-materialized row array. Out-of-range tail
    lanes (last chunk) are masked to the bucket row ``n``. With ``host``
    the edge array lives in pinned host memory and each chunk's ids stage
    through host compute (the beyond-HBM placement).
    """
    E = indices.shape[0]
    epos = e0 + jnp.arange(chunk, dtype=indptr.dtype)
    in_range = epos < E
    src = staged_gather(indices, jnp.where(in_range, epos, 0), host)
    dst = jnp.searchsorted(indptr, epos, side="right").astype(jnp.int32) - 1
    dst = jnp.where(in_range, jnp.clip(dst, 0, n - 1), n)
    return src.astype(jnp.int32), dst, in_range


@functools.partial(
    jax.jit, donate_argnums=0, static_argnames=("chunk", "host")
)
def _accumulate_chunk(acc, x_all, indptr, indices, e0, chunk: int,
                      host: bool):
    """Scatter-add one edge chunk's source features into the accumulator.

    ``dst`` comes from a searchsorted over ascending edge positions, so it
    is non-decreasing (the mask bucket n sorts last) — the scatter gets the
    sorted-indices hint."""
    n = acc.shape[0] - 1  # last row is the mask bucket
    src, dst, _ = _edge_chunk(indptr, indices, e0, chunk, n, host)
    return acc.at[dst].add(x_all[src], indices_are_sorted=True)


@functools.partial(
    jax.jit, donate_argnums=0, static_argnames=("chunk", "span", "host")
)
def _accumulate_chunk_scan(acc, x_all, indptr, indices, e0, chunk: int,
                           span: int, host: bool):
    """Zero-scatter chunk aggregation (the TPU path, where XLA serializes
    general scatters — same diagnosis as ops.reindex dedup="scan").

    CSR edge order makes each chunk's destinations a sorted run over a
    CONTIGUOUS row window, so the segmented sum is exact dense algebra:
    cumsum the chunk's messages, difference the prefix at each window row's
    clipped [indptr[v], indptr[v+1]) span, and add the (span, F) result
    into the accumulator with one dynamic windowed update. ``span`` is a
    host-precomputed static bound on rows any aligned chunk intersects.
    """
    n = acc.shape[0] - 1
    f = x_all.shape[1]
    src, _, in_range = _edge_chunk(indptr, indices, e0, chunk, n, host)
    msgs = jnp.where(in_range[:, None], x_all[src], 0)
    # Precision: differencing a prefix sum loses ~eps*|prefix| absolutely,
    # and same-sign features (post-ReLU activations) grow the prefix to
    # ~chunk*mean — ~10% row-sum error at chunk=2^21. Mean-centering keeps
    # the prefix at random-walk magnitude (~sqrt(chunk)*sigma) and the
    # exact (hi-lo)*mean term restores the row sums losslessly. The prefix
    # is always carried in f32 so bf16 tables keep their low bits too.
    pdt = jnp.promote_types(msgs.dtype, jnp.float32)
    mean = msgs.astype(pdt).mean(axis=0)  # (f,)
    centered = jnp.where(in_range[:, None], msgs.astype(pdt) - mean, 0)
    prefix = jnp.concatenate(
        [jnp.zeros((1, f), pdt), jnp.cumsum(centered, axis=0)]
    )
    r0 = (jnp.searchsorted(indptr, e0, side="right") - 1).astype(jnp.int32)
    # any window covering [r0, last-row-in-chunk] works: rows whose spans
    # end before e0 (or start after the chunk) difference to zero
    r0 = jnp.clip(r0, 0, max(n + 1 - span, 0))
    rows = r0 + jnp.arange(span, dtype=jnp.int32)
    rows_c = jnp.clip(rows, 0, n - 1)
    lo = jnp.clip(indptr[rows_c] - e0, 0, chunk).astype(jnp.int32)
    hi = jnp.clip(indptr[rows_c + 1] - e0, 0, chunk).astype(jnp.int32)
    contrib = prefix[hi] - prefix[lo] + (hi - lo).astype(pdt)[:, None] * mean
    contrib = jnp.where((rows < n)[:, None], contrib.astype(acc.dtype), 0)
    window = jax.lax.dynamic_slice(acc, (r0, 0), (span, f))
    return jax.lax.dynamic_update_slice(acc, window + contrib, (r0, 0))


def _chunk_row_span(indptr_host, chunk: int) -> int:
    """Static bound on the rows any aligned edge chunk intersects —
    host-side numpy over the CSR offsets (zero-degree runs make this graph-
    dependent, so it cannot be derived from ``chunk`` alone)."""
    import numpy as np

    ip = np.asarray(indptr_host)
    E = int(ip[-1])
    n = ip.shape[0] - 1
    if E == 0 or n == 0:
        return 1
    starts = np.arange(0, E, chunk)
    r0 = np.searchsorted(ip, starts, side="right") - 1
    r1 = np.searchsorted(ip, np.minimum(starts + chunk - 1, E - 1),
                         side="right") - 1
    span = int((r1 - r0).max()) + 1
    return min(-(-span // 8) * 8, n + 1)  # pad to 8 rows, cap at all rows


def _use_scan_agg() -> bool:
    """Platform-resolved chunk-aggregation strategy with env override
    (``QUIVER_INFER_AGG=scan|scatter``), mirroring resolve_dedup."""
    from ..core.config import resolve_platform_strategy

    return resolve_platform_strategy(
        "QUIVER_INFER_AGG", ("scan", "scatter"), tpu_default="scan",
        other_default="scatter",
    ) == "scan"


def _neighbor_mean_dev(indptr, indices, x_all, chunk: int,
                       host: bool = False, span: int | None = None):
    """full_neighbor_mean body on already-placed CSR arrays.

    Output row count comes from ``indptr`` (not ``x_all``), so rectangular
    relation CSRs — rows in a dst-type id space, columns in a src-type id
    space (hetero RelCSR) — aggregate correctly too. ``span`` (static,
    from _chunk_row_span) selects the zero-scatter scan path; None keeps
    the scatter path.
    """
    f = x_all.shape[1]
    n_out = indptr.shape[0] - 1
    E = indices.shape[0]
    acc = jnp.zeros((n_out + 1, f), x_all.dtype)  # +1 = masked-lane bucket
    for e0 in range(0, max(E, 1), chunk):
        if span is not None:
            acc = _accumulate_chunk_scan(
                acc, x_all, indptr, indices,
                jnp.asarray(e0, indptr.dtype), chunk, span, host,
            )
        else:
            acc = _accumulate_chunk(
                acc, x_all, indptr, indices,
                jnp.asarray(e0, indptr.dtype), chunk, host,
            )
    deg = jnp.maximum(jnp.diff(indptr).astype(x_all.dtype), 1.0)
    return acc[:n_out] / deg[:, None]


def _place(topo, mode):
    """(indptr_dev, indices, host_flag): HBM puts everything on device;
    HOST keeps the big edge array in pinned host memory (falls back to
    device where the platform has no pinned_host space)."""
    mode = SampleMode.parse(mode)
    indptr = jnp.asarray(topo.indptr)
    if mode == SampleMode.HOST:
        indices, host = to_pinned_host(topo.indices)
        return indptr, indices, host
    return indptr, jnp.asarray(topo.indices), False


def full_neighbor_mean(topo, x_all, chunk: int = 1 << 21,
                       mode: str | SampleMode = SampleMode.HBM):
    """Mean of ALL neighbors' features for every node: (N, F) -> (N, F).

    ``topo`` is a host CSRTopo. ``mode="HBM"`` places the edge array on
    device (needs HBM alongside two (N, F) buffers); ``mode="HOST"`` keeps
    it in pinned host memory and stages each chunk's ids through host
    compute — graphs beyond HBM stay evaluable. Equivalent to ``D^-1 A X``
    with mean over incoming CSR neighbors; zero-degree rows aggregate to
    zeros, matching segment_mean_aggregate's empty-segment convention.
    """
    indptr, indices, host = _place(topo, mode)
    span = _chunk_row_span(topo.indptr, chunk) if _use_scan_agg() else None
    return _neighbor_mean_dev(indptr, indices, jnp.asarray(x_all), chunk,
                              host, span=span)


def _edge_logits(alpha_src, alpha_dst, src, dst, negative_slope):
    logit = alpha_src[src] + alpha_dst[jnp.clip(dst, 0, alpha_dst.shape[0] - 1)]
    return jax.nn.leaky_relu(logit, negative_slope)


@functools.partial(
    jax.jit, donate_argnums=0, static_argnames=("chunk", "host", "slope")
)
def _gat_max_chunk(seg_max, a_s, a_d, indptr, indices, e0, chunk, host,
                   slope):
    n = seg_max.shape[0] - 1
    src, dst, _ = _edge_chunk(indptr, indices, e0, chunk, n, host)
    return seg_max.at[dst].max(_edge_logits(a_s, a_d, src, dst, slope))


@functools.partial(
    jax.jit, donate_argnums=(0, 1),
    static_argnames=("chunk", "host", "slope"),
)
def _gat_denom_accum_chunk(num, denom, h_all, seg_max, a_s, a_d, indptr,
                           indices, e0, chunk, host, slope):
    """One fused pass updating BOTH the softmax denominator and the
    weighted-message numerator — the per-edge work (staged gather,
    searchsorted, logits, exp) is identical, so splitting them would sweep
    the (possibly pinned-host multi-GB) edge array twice for nothing."""
    n = num.shape[0] - 1
    src, dst, _ = _edge_chunk(indptr, indices, e0, chunk, n, host)
    logit = _edge_logits(a_s, a_d, src, dst, slope)
    w = jnp.exp(logit - seg_max[dst])  # (chunk, H)
    return (
        num.at[dst].add(w[:, :, None] * h_all[src]),
        denom.at[dst].add(w),
    )


def gat_layerwise_inference(model, params, topo, x_all,
                            chunk: int = 1 << 20,
                            mode: str | SampleMode = SampleMode.HBM):
    """Layer-wise full-neighbor GAT inference — attention over ALL edges.

    Beyond-reference capability (the reference ships layer-wise inference
    only for SAGE): per layer, two chunked edge passes realize an exact
    whole-graph segment softmax — (1) per-destination logit max, (2) a
    fused pass accumulating both the shifted-exp denominator and the
    weighted-message numerator — then the trained head combine/bias applies
    via GATConv.finish. Matches the sampled model at full fanout (tested).
    Zero-in-degree nodes output bias-only rows, the sampled path's
    convention.
    """
    x = jnp.asarray(x_all)
    indptr, indices, host = _place(topo, mode)
    n = topo.node_count
    E = int(topo.edge_count)
    slope = None
    for i in range(model.num_layers):
        last = i == model.num_layers - 1
        conv = GATConv(
            features=model.num_classes if last else model.hidden,
            heads=1 if last else model.heads,
            concat=not last,
        )
        slope = conv.negative_slope
        p_i = {"params": params[f"conv{i}"]}
        h_all, a_s, a_d = conv.apply(p_i, x, method=GATConv.project)
        H = h_all.shape[1]

        e0s = [jnp.asarray(e0, indptr.dtype)
               for e0 in range(0, max(E, 1), chunk)]
        seg_max = jnp.full((n + 1, H), -jnp.inf, h_all.dtype)
        for e0 in e0s:
            seg_max = _gat_max_chunk(seg_max, a_s, a_d, indptr, indices, e0,
                                     chunk, host, slope)
        # empty destinations: keep the shift finite (their denom stays 0)
        seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
        denom = jnp.zeros((n + 1, H), h_all.dtype)
        num = jnp.zeros((n + 1, H, h_all.shape[2]), h_all.dtype)
        for e0 in e0s:
            num, denom = _gat_denom_accum_chunk(
                num, denom, h_all, seg_max, a_s, a_d, indptr, indices, e0,
                chunk, host, slope,
            )
        out = num[:n] / jnp.maximum(
            denom[:n], jnp.finfo(h_all.dtype).tiny
        )[:, :, None]
        x = conv.apply(p_i, out, method=GATConv.finish)
        if not last:
            x = jax.nn.elu(x)
    return jax.nn.log_softmax(x, axis=-1)


def gcn_layerwise_inference(model, params, topo, x_all,
                            chunk: int = 1 << 21,
                            mode: str | SampleMode = SampleMode.HBM):
    """Layer-wise full-neighbor GCN inference: symmetric-normalized
    aggregation over the self-loop-augmented FULL graph,
    ``D^-1/2 (A + I) D^-1/2 X`` per layer, with global degrees — exactly
    what GCNConv computes on a block that covers the whole graph.

    Reuses the chunked mean machinery: sum = mean · deg, with the feature
    matrix pre-scaled by rsqrt(deg+1) and the result post-scaled the same
    way (plus the self term). Assumes the usual undirected/symmetrized
    topology (CSR row degree = both sides' degree), like full-graph GCN
    itself; matches GCNConv exactly on such graphs.
    """
    x = jnp.asarray(x_all)
    indptr, indices, host = _place(topo, mode)
    span = _chunk_row_span(topo.indptr, chunk) if _use_scan_agg() else None
    deg = jnp.diff(indptr).astype(x.dtype)
    inv_s = jax.lax.rsqrt(deg + 1.0)  # self-loop-augmented degrees
    for i in range(model.num_layers):
        feats = (
            model.num_classes if i == model.num_layers - 1 else model.hidden
        )
        h = x * inv_s[:, None]
        agg = _neighbor_mean_dev(indptr, indices, h, chunk, host, span=span)
        agg = (agg * deg[:, None] + h) * inv_s[:, None]
        conv = GCNConv(feats)
        x = conv.apply(
            {"params": params[f"conv{i}"]}, agg, method=GCNConv.combine
        )
        if i != model.num_layers - 1:
            x = jax.nn.relu(x)
    return jax.nn.log_softmax(x, axis=-1)


def gin_layerwise_inference(model, params, topo, x_all,
                            chunk: int = 1 << 21,
                            mode: str | SampleMode = SampleMode.HBM):
    """Layer-wise full-neighbor GIN inference: SUM aggregation over the
    full graph, ``MLP((1+eps)·x + A·x)`` per layer — exactly what GINConv
    computes on a block covering every node (sum = mean · degree, reusing
    the chunked mean machinery)."""
    from .gin import GINConv

    x = jnp.asarray(x_all)
    indptr, indices, host = _place(topo, mode)
    span = _chunk_row_span(topo.indptr, chunk) if _use_scan_agg() else None
    deg = jnp.diff(indptr).astype(x.dtype)
    for i in range(model.num_layers):
        last = i == model.num_layers - 1
        conv = GINConv(
            features=model.num_classes if last else model.hidden,
            mlp_hidden=model.hidden,
            train_eps=model.train_eps,
        )
        agg = _neighbor_mean_dev(indptr, indices, x, chunk, host,
                                 span=span)
        agg = agg * deg[:, None]
        p_i = {"params": params[f"conv{i}"]}
        eps = p_i["params"]["eps"] if model.train_eps else conv.eps_init
        z = agg + (1.0 + eps) * x
        x = conv.apply(p_i, z, method=GINConv.combine)
        if not last:
            x = jax.nn.relu(x)
    return jax.nn.log_softmax(x, axis=-1)


def rgcn_layerwise_inference(model, params, topo, x_dict,
                             chunk: int = 1 << 20,
                             mode: str | SampleMode = SampleMode.HBM):
    """Layer-wise full-neighbor R-GCN inference over a typed graph.

    Beyond-reference capability (no hetero exists there at all): per layer,
    every node type's self-transform plus, per relation, a chunked
    whole-relation mean aggregation of the relation-projected source
    features — the rectangular analogue of the SAGE pass, walked over each
    relation's own CSR. Trained weights are read straight from the
    ``conv{i}`` param tree (``self_{type}``, ``rel_{s}__{r}__{d}`` or the
    basis-decomposition ``bases_{dim}``/``coef_*`` pair), matching
    RGCNLayer's math exactly (tested against the sampled model at full
    fanout).

    Args:
      model: trained RGCN module.
      params: its parameter tree.
      topo: HeteroCSRTopo.
      x_dict: {node_type: (N_t, F_t)} full feature tables.
      chunk / mode: as in sage_layerwise_inference.

    Returns (N_target, num_classes) log-probs for every target-type node.
    """
    x_dict = {t: jnp.asarray(v) for t, v in x_dict.items()}
    placed = {
        et: _place(rel, mode) for et, rel in topo.relations.items()
    }
    scan_agg = _use_scan_agg()
    spans = {
        et: _chunk_row_span(rel.indptr, chunk) if scan_agg else None
        for et, rel in topo.relations.items()
    }
    for i in range(model.num_layers):
        p = params[f"conv{i}"]
        # the sampled model creates weights only for types/relations active
        # at that hop (e.g. the final layer serves seed types alone) — the
        # param tree is the source of truth for what this layer computes
        out = {}
        for t, x in x_dict.items():
            if f"self_{t}" not in p:
                continue
            w = p[f"self_{t}"]
            out[t] = x @ w["kernel"] + w["bias"]
        for et in sorted(topo.relations, key=str):
            s_t, _, d_t = et
            name = f"{s_t}__{et[1]}__{d_t}"
            if d_t not in out or s_t not in x_dict:
                continue
            if model.num_bases > 0:
                if f"coef_{name}" not in p:
                    continue
                in_dim = x_dict[s_t].shape[-1]
                wmat = jnp.einsum(
                    "b,bif->if", p[f"coef_{name}"], p[f"bases_{in_dim}"]
                )
            else:
                if f"rel_{name}" not in p:
                    continue
                wmat = p[f"rel_{name}"]["kernel"]
            h = x_dict[s_t] @ wmat
            indptr, indices, host = placed[et]
            out[d_t] = out[d_t] + _neighbor_mean_dev(
                indptr, indices, h, chunk, host, span=spans[et]
            )
        if i != model.num_layers - 1:
            out = {t: jax.nn.relu(v) for t, v in out.items()}
        x_dict = out
    return jax.nn.log_softmax(x_dict[model.target_type], axis=-1)


def sage_layerwise_inference(model, params, topo, x_all,
                             chunk: int = 1 << 21,
                             mode: str | SampleMode = SampleMode.HBM):
    """Layer-wise full-neighbor GraphSAGE inference (reference
    reddit_quiver.py:68-92 parity): returns (N, num_classes) log-probs for
    EVERY node, using all edges at every layer.

    Args:
      model: the trained GraphSAGE module (its hidden/num_classes/num_layers
        fields drive the pass).
      params: the trained parameter tree (``conv{i}`` children).
      topo: host CSRTopo.
      x_all: (N, F) input features (will be placed on device).
      chunk: edges per aggregation program.
      mode: "HBM" or "HOST" (pinned-host edge array for beyond-HBM graphs).
    """
    x = jnp.asarray(x_all)
    # place the (possibly multi-GB) CSR arrays once, not once per layer
    indptr, indices, host = _place(topo, mode)
    span = _chunk_row_span(topo.indptr, chunk) if _use_scan_agg() else None
    for i in range(model.num_layers):
        feats = (
            model.num_classes if i == model.num_layers - 1 else model.hidden
        )
        agg = _neighbor_mean_dev(indptr, indices, x, chunk, host,
                                 span=span)
        conv = SAGEConv(feats)
        x = conv.apply(
            {"params": params[f"conv{i}"]}, agg, x, method=SAGEConv.combine
        )
        if i != model.num_layers - 1:
            x = jax.nn.relu(x)
    return jax.nn.log_softmax(x, axis=-1)
