"""Relational GCN (R-GCN) over padded hetero layers.

The model family for the heterogeneous configs (BASELINE.json config 5:
MAG240M-style R-GCN). Schlichtkrull et al.'s R-GCN layer, adapted to the
typed padded-Adj format of sampling/hetero.py:

    h'_v = act( W_self^{type(v)} h_v
                + sum_rel mean_{u in N_rel(v)} W_rel h_u )

Per-relation weights support optional basis decomposition (num_bases > 0,
the paper's regularization for many-relation graphs): W_rel = sum_b
a_{rel,b} B_b, with the bases shared across relations of the same layer.

Each layer consumes one HeteroLayer (deepest first) and shrinks every
type's frontier to its dst capacity, exactly like the homogeneous models'
``x[:num_dst]`` convention.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import flax.linen as nn

from .layers import gather_src, segment_mean_aggregate

__all__ = ["RGCNLayer", "RGCN"]


def _rel_name(et) -> str:
    s, r, d = et
    return f"{s}__{r}__{d}"


class RGCNLayer(nn.Module):
    features: int
    num_bases: int = 0  # 0 = full per-relation weights
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    @nn.compact
    def __call__(self, x_dict: dict, layer) -> dict:
        """x_dict: {type: (src_cap_t, F)}; layer: HeteroLayer."""
        if self.dtype is not None:
            x_dict = {t: v.astype(self.dtype) for t, v in x_dict.items()}
        out = {}
        for t, cap in layer.dst_caps.items():
            if t in x_dict:
                out[t] = nn.Dense(
                    self.features, dtype=self.dtype, name=f"self_{t}"
                )(x_dict[t][:cap])

        rel_keys = sorted(layer.adjs, key=str)
        # one basis set per distinct source feature width (node types may
        # carry different-dimensional features)
        bases_by_dim: dict[int, jnp.ndarray] = {}
        for et in rel_keys:
            s_t, _, d_t = et
            adj = layer.adjs[et]
            if self.num_bases > 0:
                in_dim = x_dict[s_t].shape[-1]
                if in_dim not in bases_by_dim:
                    bases_by_dim[in_dim] = self.param(
                        f"bases_{in_dim}",
                        nn.initializers.lecun_normal(),
                        (self.num_bases, in_dim, self.features),
                    )
                coef = self.param(
                    f"coef_{_rel_name(et)}",
                    nn.initializers.normal(1.0 / max(self.num_bases, 1)),
                    (self.num_bases,),
                )
                w = jnp.einsum("b,bif->if", coef, bases_by_dim[in_dim])
                if self.dtype is not None:
                    # the basis combination stays f32 (params), but the big
                    # per-relation matmul must hit the MXU in bf16 like the
                    # Dense branch does
                    w = w.astype(self.dtype)
                h = x_dict[s_t] @ w
            else:
                h = nn.Dense(
                    self.features, use_bias=False, dtype=self.dtype,
                    name=f"rel_{_rel_name(et)}",
                )(x_dict[s_t])
            src, dst = adj.edge_index
            msgs, valid = gather_src(h, src)
            agg = segment_mean_aggregate(
                msgs, jnp.clip(dst, 0), valid, layer.dst_caps[d_t],
                fanout=getattr(adj, "fanout", None),
            )
            out[d_t] = out[d_t] + agg
        return out


class RGCN(nn.Module):
    """Multi-layer R-GCN consuming HeteroGraphSampler output.

    Produces log-probabilities for the first ``dst_cap`` rows of
    ``target_type`` after the last layer (the seed rows, by the
    seeds-first frontier contract).
    """

    hidden: int
    num_classes: int
    target_type: str
    num_layers: int = 2
    num_bases: int = 0
    dropout: float = 0.5
    dtype: str | None = None  # "bfloat16" = mixed-precision compute

    @nn.compact
    def __call__(self, x_dict: dict, layers: Sequence, *, train: bool = False):
        if len(layers) != self.num_layers:
            raise ValueError(
                f"model has {self.num_layers} layers but got {len(layers)} "
                "hetero layers; sampler sizes and num_layers must match"
            )
        for i, layer in enumerate(layers):
            feats = (
                self.num_classes if i == self.num_layers - 1 else self.hidden
            )
            x_dict = RGCNLayer(
                feats, num_bases=self.num_bases, dtype=self.dtype,
                name=f"conv{i}",
            )(x_dict, layer)
            if i != self.num_layers - 1:
                x_dict = {t: nn.relu(v) for t, v in x_dict.items()}
                drop = nn.Dropout(self.dropout, deterministic=not train)
                x_dict = {t: drop(v) for t, v in x_dict.items()}
        # log-softmax in f32: bf16 has too little mantissa for stable NLL
        return nn.log_softmax(
            x_dict[self.target_type].astype(jnp.float32), axis=-1
        )
