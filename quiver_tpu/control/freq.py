"""quiver-ctl access-frequency sketch — measured heat over the row space.

The reference's hot/cold placement is planned ONCE from node degree
(utils.py:213-231 ``reindex_by_config``) — a static graph statistic that
GNNSampler (arxiv 2108.11571) argues should be replaced by the *measured*
access distribution of the running workload. This module is the measuring
half of that loop, two complementary structures:

* an **in-program positional histogram** (:func:`row_heat_histogram`):
  every tiered-gather id lands one count in a bounded ``(num_bins,)``
  vector binned over the store's TRANSLATED row order. Binning is
  monotone in the translated index (bin = row // rows_per_bin), so the
  cumulative mass below any candidate L0/L1 boundary reads straight off
  the histogram — exactly the cost-model input
  (:func:`~quiver_tpu.control.cost.predicted_hit_rates`). The vector
  rides the trainer's MetricsTape pytree through ``shard_map`` /
  ``epoch_scan`` (psum'd once per step like ``feature.tier_hits``) and
  costs zero collectives when ``collect_metrics=False``.
* an **exact top-K heavy-hitter set** (host side, SpaceSaving-style):
  original node ids with estimated hit counts, fed from every
  host-visible id stream — serve batches, eager gathers, replayed
  traces, degree priors. This is what names the rows a
  :meth:`~quiver_tpu.feature.shard.ShardedFeature.repin` pins into L0.

Both decay with an EMA between epochs (:meth:`FreqSketch.decay`) so heat
tracks the *current* traffic mix instead of the run's whole history.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = ["FreqSketch", "row_heat_histogram", "heat_num_bins"]


def heat_num_bins(num_rows: int, num_bins: int = 256) -> int:
    """The histogram width for an ``num_rows``-row store: ``num_bins``
    capped at the row count (a 10-row toy store gets 10 exact bins, not
    246 empty ones)."""
    return max(1, min(int(num_bins), int(num_rows)))


def row_heat_histogram(n_id, feature_order, num_rows: int, num_bins: int):
    """Traced per-row access-heat histogram over the translated row space.

    ``n_id`` are the gather's ORIGINAL node ids (-1 = invalid lane, the
    tiered-gather padding convention — contributes nothing);
    ``feature_order`` the store's node-id -> translated-row map (None =
    identity). Bin ``b`` covers translated rows
    ``[b * rpb, (b + 1) * rpb)`` with ``rpb = ceil(num_rows/num_bins)``
    — positional, monotone binning, so prefix sums of the result are
    exact hit masses below candidate tier boundaries. Returns int32
    ``(num_bins,)``; callers inside ``shard_map`` psum it at the same
    axes as their tier-hit vector.
    """
    n_id = jnp.asarray(n_id)
    valid = n_id >= 0
    ids = jnp.where(valid, n_id, 0)
    if feature_order is not None:
        ids = feature_order[ids]
    rpb = -(-num_rows // num_bins)  # ceil; bins stay < num_bins
    bins = jnp.clip(ids // rpb, 0, num_bins - 1)
    return jnp.zeros((num_bins,), jnp.int32).at[bins].add(
        valid.astype(jnp.int32)
    )


class FreqSketch:
    """Host-side access-heat state: EMA'd positional histogram + exact
    top-K heavy hitters.

    Args:
      num_rows: the store's row count (fixes the bin -> row mapping).
      num_bins: histogram width (capped at ``num_rows``).
      top_k: heavy-hitter capacity. SpaceSaving eviction: a new id
        replaces the current minimum and inherits its count (classic
        overestimate-never-underestimate guarantee), so the top of the
        set is exact once an id is genuinely frequent.
      decay: EMA factor applied by :meth:`decay` — ``heat *= decay`` —
        so between-epoch heat tracks the current traffic mix.
    """

    def __init__(self, num_rows: int, num_bins: int = 256,
                 top_k: int = 1024, decay: float = 0.5):
        if num_rows < 1:
            raise ValueError(f"num_rows must be >= 1, got {num_rows}")
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.num_rows = int(num_rows)
        self.num_bins = heat_num_bins(num_rows, num_bins)
        self.rows_per_bin = -(-self.num_rows // self.num_bins)
        self.top_k = int(top_k)
        self.decay_factor = float(decay)
        # EMA'd translated-bin heat (float64: the EMA makes counts fractional)
        self.heat = np.zeros(self.num_bins, np.float64)
        # heavy hitters: original node id -> estimated hit count
        self._hitters: dict[int, float] = {}
        self.observed = 0  # raw hits ever folded in (pre-decay)

    # -- feeding -------------------------------------------------------------

    def observe_histogram(self, hist) -> None:
        """Fold one program-produced heat histogram in (``(num_bins,)``,
        or an epoch_scan stack ``(steps, num_bins)`` — summed over
        steps). This is the trainer-path feed: binned, translated-space,
        no individual ids."""
        arr = np.asarray(hist, np.float64)
        if arr.ndim == 2:
            arr = arr.sum(axis=0)
        if arr.shape != (self.num_bins,):
            raise ValueError(
                f"histogram shape {arr.shape} != ({self.num_bins},)"
            )
        self.heat += arr
        self.observed += int(arr.sum())

    def observe_ids(self, ids, weight: float = 1.0) -> None:
        """Fold a host-visible ORIGINAL-node-id stream in (serve batches,
        eager gathers, replayed traces). Updates the heavy-hitter set;
        the histogram is fed by the in-program path, not here (ids at
        this boundary are pre-translation, and double-counting the
        trainer's own gathers would skew the bins)."""
        ids = np.asarray(ids).reshape(-1)
        ids = ids[ids >= 0]
        if ids.size == 0:
            return
        uniq, counts = np.unique(ids, return_counts=True)
        self.observed += int(counts.sum())
        for i, c in zip(uniq.tolist(), counts.tolist()):
            self._bump(int(i), float(c) * weight)

    def observe_prior(self, weights) -> None:
        """Fold a per-node prior in — e.g. post-mutation degrees from the
        streaming path's ``note_degree_update``. The prior seeds the
        heavy-hitter set at LOW weight (one synthetic hit scaled by the
        node's share of the total), so it breaks ties before traffic is
        measured but measured heat quickly dominates it."""
        w = np.asarray(weights, np.float64).reshape(-1)
        if w.size == 0 or w.sum() <= 0:
            return
        top = np.argsort(-w, kind="stable")[: self.top_k]
        scale = float(w[top].max())
        for i in top.tolist():
            if w[i] > 0:
                self._bump(int(i), float(w[i]) / scale)

    def _bump(self, node: int, weight: float) -> None:
        if node in self._hitters:
            self._hitters[node] += weight
        elif len(self._hitters) < self.top_k:
            self._hitters[node] = weight
        else:
            # SpaceSaving: evict the minimum, inherit its count
            victim = min(self._hitters, key=self._hitters.__getitem__)
            floor = self._hitters.pop(victim)
            self._hitters[node] = floor + weight

    # -- reading -------------------------------------------------------------

    def top_rows(self, k: int) -> np.ndarray:
        """The ``k`` hottest ORIGINAL node ids, hottest first (fewer when
        fewer have been observed) — the row set a ``repin`` pins."""
        items = sorted(
            self._hitters.items(), key=lambda kv: (-kv[1], kv[0])
        )
        return np.array([i for i, _ in items[:k]], np.int64)

    def bin_mass_below(self, row: int) -> float:
        """EMA'd hit mass at translated rows ``[0, row)`` — fractional
        inside the boundary bin (uniform-within-bin assumption)."""
        row = max(0, min(int(row), self.num_rows))
        full, part = divmod(row, self.rows_per_bin)
        mass = float(self.heat[:full].sum())
        if part and full < self.num_bins:
            mass += float(self.heat[full]) * part / self.rows_per_bin
        return mass

    @property
    def total_mass(self) -> float:
        return float(self.heat.sum())

    def decay(self) -> None:
        """Between-epoch EMA decay of both structures."""
        self.heat *= self.decay_factor
        for node in self._hitters:
            self._hitters[node] *= self.decay_factor

    def state(self) -> dict:
        """Snapshot for audit records / tests (copies, not views)."""
        return {
            "num_bins": self.num_bins,
            "observed": self.observed,
            "total_mass": self.total_mass,
            "hitters": dict(self._hitters),
        }
