"""quiver-ctl CacheController — telemetry-driven placement & routing.

Closes the loop from graftscope telemetry to the three knobs the store
exposes, between batches/epochs (never inside a compiled program):

* **L0 membership** — re-tier the replicated tier to the MEASURED
  hottest rows via :meth:`~quiver_tpu.feature.shard.ShardedFeature
  .repin` (arbitrary hot sets; the reference could only take a
  degree-order prefix);
* **L0/L1 boundary** — move ``rep_rows`` toward the measured hit mass
  (:class:`SplitTuner`, generalizing the store's ``auto_split`` rules
  with a reversal dead-band);
* **routed_alpha** — grow on overflow AND shrink on sustained slack
  (:class:`AlphaTuner`; the legacy tuners only ever doubled, so one
  transient skew burst inflated comm for the rest of the run).

Every decision is emitted as an audited JSONL record through the obs
exporters (``read_jsonl``-round-trippable — each line is a real metric
snapshot of the matching ``ctrl.*`` counter with the decision's inputs
and outputs merged in) and counted on the controller's own registry
(``ctrl.decisions`` / ``ctrl.repins`` / ``ctrl.split_moves`` /
``ctrl.alpha_changes``).

``frozen=True`` keeps the controller observing but returns no decisions
— the differential tests' parity mode (attached-but-frozen must be
bitwise-identical to no controller at all).

All controller state is host-side (the sketch, the tuners' hysteresis
counters, the decision counters), so it survives ``trainer.refresh()``,
``replan``, and streaming commits by construction — the seam the future
DCN fourth tier plugs its tier policy into (ROADMAP item 5).
"""

from __future__ import annotations

import numpy as np

from ..obs.export import write_jsonl
from ..obs.registry import (
    CTRL_ALPHA_CHANGES,
    CTRL_DECISIONS,
    CTRL_OOC_PROMOTIONS,
    CTRL_REPINS,
    CTRL_SPLIT_MOVES,
    MetricsRegistry,
)
from ..utils.trace import get_logger
from .cost import CostModel, predicted_hit_rates
from .freq import FreqSketch

__all__ = ["AlphaTuner", "CacheController", "SplitTuner"]


class AlphaTuner:
    """Two-sided ``routed_alpha`` tuner with a convergence floor.

    Grow: any fallback-served overflow doubles alpha (capped at F —
    full-length buckets), exactly the legacy one-sided rule. Shrink:
    ``shrink_after`` CONSECUTIVE clean batches halve it — overflow lanes
    are exact-but-slower, so slack is the only safe shrink signal.

    No-oscillation: when a shrink is punished (the very next signal is
    overflow), the regrown alpha becomes a FLOOR — the tuner never
    shrinks below a value the workload has already proven too small, so
    a constant workload converges instead of cycling shrink/regrow
    (pinned by tests/test_controller.py).
    """

    def __init__(self, shrink_after: int = 4, floor: float = 0.25):
        self.shrink_after = int(shrink_after)
        self.floor = float(floor)
        self._clean = 0
        self._shrunk_from: float | None = None

    def decide(self, overflow: int, alpha: float,
               ceiling: float) -> float | None:
        """New alpha, or None to keep. ``overflow`` is the previous
        batch's fallback-served lane total; ``ceiling`` the feature-axis
        size F (alpha >= F means full-length buckets)."""
        if overflow > 0:
            self._clean = 0
            if self._shrunk_from is not None:
                # a shrink was immediately punished: regrow AND pin the
                # floor there — this workload needs at least that alpha
                self.floor = max(self.floor, self._shrunk_from)
                self._shrunk_from = None
            if alpha >= ceiling:
                return None
            return min(alpha * 2.0, ceiling)
        self._clean += 1
        self._shrunk_from = None
        if self._clean >= self.shrink_after and alpha / 2.0 >= self.floor:
            self._clean = 0
            self._shrunk_from = alpha
            return alpha / 2.0
        return None


class SplitTuner:
    """L0/L1 boundary tuner: the store's measured-hit-mass rules plus a
    reversal dead-band.

    Signals (h0/h1 = replicated/sharded hits, dev = h0 + h1) are the
    proven ``_maybe_auto_split`` rules: shrink (halve ``rep_rows``) when
    ``h0 * 8 < dev`` (L0 not earning its F× HBM), grow (double, up to
    the budget ceiling) when ``h1 > h0`` (hit mass just beyond the
    boundary). The band between them is the existing dead band.

    New here: a REVERSAL dead-band — changing direction (grow after
    shrink or vice versa) requires the reversed signal on two
    consecutive invocations, while same-direction moves stay immediate.
    At the budget ceiling the legacy grow rule could alternate
    grow/shrink every batch on a workload sitting near the h1 == h0
    edge; one noisy batch can no longer turn the boundary around.
    """

    def __init__(self, confirm: int = 2):
        self.confirm = int(confirm)
        self._last_dir = 0   # -1 shrink, +1 grow, 0 none yet
        self._pending = 0    # consecutive sightings of a reversal signal

    def reset(self) -> None:
        """Forget direction history (a manual resplit moved the boundary
        out from under the tuner)."""
        self._last_dir = 0
        self._pending = 0

    def decide(self, h0: int, h1: int, rep_rows: int,
               ceiling: int) -> int | None:
        """New ``rep_rows``, or None to keep."""
        dev = h0 + h1
        if dev <= 0:
            return None
        if h0 * 8 < dev and rep_rows > 0:
            direction, new = -1, rep_rows // 2
        elif h1 > h0 and 0 < rep_rows < ceiling:
            direction, new = +1, min(rep_rows * 2, ceiling)
        else:
            self._pending = 0
            return None
        if self._last_dir and direction != self._last_dir:
            self._pending += 1
            if self._pending < self.confirm:
                return None
        self._pending = 0
        self._last_dir = direction
        return new if new != rep_rows else None


class CacheController:
    """Between-batch/epoch control plane over one feature store.

    Args:
      sketch: a :class:`~quiver_tpu.control.freq.FreqSketch` (built
        lazily from the store's row count when omitted).
      cost: a :class:`~quiver_tpu.control.cost.CostModel` (optional —
        decisions degrade to the raw telemetry rules without it; when
        present its predictions ride every audit record).
      frozen: observe but never decide (the parity/differential mode).
      decision_log: path (or writable file object) for the audited JSONL
        decision records; None = audit to counters/log only.
      heat_bins: width of the in-program row-heat histogram a trainer
        registers for this controller; 0 disables the traced feed (the
        sketch then only sees host-visible id streams).
      alpha_tuner / split_tuner: override the tuners.
      repin_min_gain: hysteresis for :meth:`maybe_repin` — re-tier only
        when the measured-hot set's predicted L0 hit share beats the
        current occupancy by at least this fraction (a repin republishes
        every tier, so marginal wins are not worth the retrace).
    """

    def __init__(self, sketch: FreqSketch | None = None,
                 cost: CostModel | None = None, *, frozen: bool = False,
                 decision_log=None, heat_bins: int = 256,
                 alpha_tuner: AlphaTuner | None = None,
                 split_tuner: SplitTuner | None = None,
                 repin_min_gain: float = 0.02, tracer=None,
                 recorder=None):
        self.sketch = sketch
        self.cost = cost
        self.frozen = bool(frozen)
        self.decision_log = decision_log
        # grafttrace/recorder seams: every audited decision lands as a
        # zero-duration span (subsystem "control") and a flight-recorder
        # ring note, so a postmortem bundle shows the placement decisions
        # leading up to the fault
        self.tracer = tracer
        self.recorder = recorder
        self.heat_bins = int(heat_bins)
        self.alpha_tuner = alpha_tuner if alpha_tuner is not None \
            else AlphaTuner()
        self.split_tuner = split_tuner if split_tuner is not None \
            else SplitTuner()
        self.repin_min_gain = float(repin_min_gain)
        self.metrics = MetricsRegistry()
        self.metrics.counter(
            CTRL_DECISIONS, unit="decisions",
            doc="control-plane decisions emitted (repins + boundary "
                "moves + alpha changes)",
        )
        self.metrics.counter(
            CTRL_REPINS, unit="repins",
            doc="L0 re-tiers to a measured-hottest row set",
        )
        self.metrics.counter(
            CTRL_SPLIT_MOVES, unit="moves",
            doc="L0/L1 boundary moves decided from measured hit mass",
        )
        self.metrics.counter(
            CTRL_ALPHA_CHANGES, unit="changes",
            doc="routed_alpha changes (grow on overflow OR shrink on "
                "sustained slack)",
        )
        self.metrics.counter(
            CTRL_OOC_PROMOTIONS, unit="restages",
            doc="disk-tier host-cache restages to a measured-hottest "
                "row set (out-of-core stores)",
        )
        self._counts = {CTRL_DECISIONS: 0, CTRL_REPINS: 0,
                        CTRL_SPLIT_MOVES: 0, CTRL_ALPHA_CHANGES: 0,
                        CTRL_OOC_PROMOTIONS: 0}
        self.decisions: list[dict] = []  # in-memory audit trail

    # -- construction helpers ------------------------------------------------

    @classmethod
    def for_store(cls, feature, **kwargs) -> "CacheController":
        """A controller sized to ``feature`` and attached to it — what
        the ``auto_split``/``auto_alpha`` compat shims build."""
        ctl = cls(**kwargs)
        ctl.attach(feature)
        return ctl

    def attach(self, feature) -> "CacheController":
        """Bind to a feature store: size the sketch to its row count and
        register as its split-decision delegate."""
        if self.sketch is None and getattr(feature, "shape", None):
            self.sketch = FreqSketch(
                feature.shape[0],
                num_bins=self.heat_bins if self.heat_bins > 0 else 256,
            )
        feature._controller = self
        return self

    @property
    def wants_heat(self) -> bool:
        """Whether a trainer should compile the in-program row-heat
        histogram feed for this controller."""
        return self.heat_bins > 0

    def _ensure_sketch(self, num_rows: int) -> FreqSketch:
        if self.sketch is None:
            self.sketch = FreqSketch(
                num_rows, num_bins=self.heat_bins if self.heat_bins > 0
                else 256,
            )
        return self.sketch

    # -- observation (always on, frozen or not) ------------------------------

    def observe_histogram(self, hist) -> None:
        """Fold an in-program heat histogram in (``feature.row_heat``
        from a step's recorded metrics pytree)."""
        if self.sketch is not None and hist is not None:
            self.sketch.observe_histogram(np.asarray(hist))

    def observe_serve(self, ids) -> None:
        """Fold a serve batch's gathered node ids in — the seam that
        lets the store re-tier under SERVING traffic."""
        if self.sketch is not None:
            self.sketch.observe_ids(ids)

    def observe_ids(self, ids, weight: float = 1.0) -> None:
        if self.sketch is not None:
            self.sketch.observe_ids(ids, weight)

    def observe_prior(self, weights) -> None:
        """Fold a per-node prior in (the streaming path's post-mutation
        degrees arrive here via ``note_degree_update``)."""
        w = np.asarray(weights).reshape(-1)
        if w.size:
            self._ensure_sketch(w.size).observe_prior(w)

    # -- decisions ------------------------------------------------------------

    def decide_alpha(self, overflow: int, alpha: float,
                     ceiling: float) -> float | None:
        """Alpha decision from the previous batch's overflow total;
        audited when it changes anything."""
        if self.frozen:
            return None
        new = self.alpha_tuner.decide(int(overflow), float(alpha),
                                      float(ceiling))
        if new is None or new == alpha:
            return None
        self._audit(
            CTRL_ALPHA_CHANGES, "alpha",
            {"from": float(alpha), "to": float(new),
             "overflow": int(overflow),
             "direction": "grow" if new > alpha else "shrink",
             "floor": self.alpha_tuner.floor},
        )
        return new

    def decide_split(self, h0: int, h1: int, rep_rows: int,
                     ceiling: int) -> int | None:
        """L0/L1 boundary decision from measured tier hits; audited when
        it moves the boundary."""
        if self.frozen:
            return None
        new = self.split_tuner.decide(int(h0), int(h1), int(rep_rows),
                                      int(ceiling))
        if new is None:
            return None
        record = {"from": int(rep_rows), "to": int(new),
                  "h0": int(h0), "h1": int(h1)}
        if self.cost is not None and self.sketch is not None:
            record["predicted"] = self.cost.predict(
                self.sketch, new, rep_rows - new if new < rep_rows
                else 0, None,
            )
        self._audit(CTRL_SPLIT_MOVES, "split", record)
        return new

    def maybe_repin(self, feature, trainer=None) -> bool:
        """Re-tier L0 to the sketch's measured-hottest rows when the
        predicted hit-share gain clears the hysteresis band.

        Compares the heavy hitters' mass currently landing in L0 (their
        translated rows < ``rep_rows``) against the mass the top
        ``rep_rows`` hitters would land after a repin; repins — and
        refreshes ``trainer`` (a repin bumps the store version) — only
        when the gain exceeds ``repin_min_gain`` of the observed mass.
        Returns True when a repin was applied.
        """
        if self.frozen or self.sketch is None:
            return False
        rep_rows = int(getattr(feature, "rep_rows", 0))
        if rep_rows <= 0:
            return False
        hitters = self.sketch.state()["hitters"]
        if not hitters:
            return False
        total = sum(hitters.values())
        if total <= 0:
            return False
        order = feature.feature_order
        order = None if order is None else np.asarray(order)
        ids = np.fromiter(hitters.keys(), np.int64, len(hitters))
        mass = np.fromiter(hitters.values(), np.float64, len(hitters))
        t = ids if order is None else order[ids].astype(np.int64)
        current = float(mass[t < rep_rows].sum())
        top = np.argsort(-mass, kind="stable")[:rep_rows]
        target = float(mass[top].sum())
        gain = (target - current) / total
        if gain < self.repin_min_gain:
            return False
        rows = ids[top]
        feature.repin(rows)
        self.split_tuner.reset()  # the boundary's contents moved
        if trainer is not None:
            trainer.refresh()
        self._audit(
            CTRL_REPINS, "repin",
            {"rep_rows": rep_rows, "pinned": int(rows.size),
             "hit_share_before": current / total,
             "hit_share_after": target / total, "gain": gain},
        )
        return True

    def maybe_promote(self, store) -> bool:
        """Restage an out-of-core store's host cold cache to the
        sketch's measured-hottest DISK rows.

        The disk-tier analogue of :meth:`maybe_repin`, one level down:
        heavy hitters whose translated rows fall past ``hot_rows`` live
        on disk; the top ``host_cache_rows`` of them by measured mass
        earn promotion into host RAM (:meth:`~quiver_tpu.ooc.store
        .MmapFeatureStore.restage`), and rows that lost their heat spill
        back to disk-only by dropping out of the set (their bytes were
        never mutated — forgetting the copy IS the demotion). Same
        ``repin_min_gain`` hysteresis: the cache only moves when the
        promoted set's predicted hit mass beats the currently staged
        set's by the threshold, so noise cannot thrash the disk. Audited
        under ``ctrl.ooc_promotions``. Returns True when a restage was
        applied.
        """
        if self.frozen or self.sketch is None:
            return False
        budget = int(getattr(store, "host_cache_rows", 0))
        if budget <= 0 or not hasattr(store, "restage"):
            return False
        hitters = self.sketch.state()["hitters"]
        if not hitters:
            return False
        total = sum(hitters.values())
        if total <= 0:
            return False
        hot_rows = int(getattr(store, "hot_rows", 0))
        order = store.feature_order
        order = None if order is None else np.asarray(order)
        ids = np.fromiter(hitters.keys(), np.int64, len(hitters))
        mass = np.fromiter(hitters.values(), np.float64, len(hitters))
        t = ids if order is None else order[ids].astype(np.int64)
        disk = t >= hot_rows  # hitters whose rows live past the HBM tier
        if not disk.any():
            return False
        cold_local = t[disk] - hot_rows
        cold_mass = mass[disk]
        top = np.argsort(-cold_mass, kind="stable")[:budget]
        target = float(cold_mass[top].sum())
        staged = store.staged_ids
        current = (
            float(cold_mass[np.isin(cold_local, staged)].sum())
            if staged.size else 0.0
        )
        gain = (target - current) / total
        if staged.size and gain < self.repin_min_gain:
            return False
        resident = store.restage(cold_local[top])
        record = {
            "budget": budget, "staged": resident,
            "hit_share_before": current / total,
            "hit_share_after": target / total, "gain": gain,
        }
        if self.cost is not None:
            record["predicted"] = self.cost.predict_disk(
                self.sketch, hot_rows, resident
            )
        self._audit(CTRL_OOC_PROMOTIONS, "ooc_promote", record)
        return True

    def end_epoch(self, feature=None, trainer=None) -> None:
        """Epoch-boundary hook: consider a re-tier on the epoch's
        accumulated heat — an L0 repin for in-RAM stores, a disk-to-host
        promotion for out-of-core ones — then EMA-decay the sketch
        toward the current traffic mix."""
        if feature is not None:
            if hasattr(feature, "restage"):
                self.maybe_promote(feature)
            else:
                self.maybe_repin(feature, trainer)
        if self.sketch is not None:
            self.sketch.decay()

    # -- audit ----------------------------------------------------------------

    def _audit(self, counter: str, decision: str, record: dict) -> None:
        for name in (counter, CTRL_DECISIONS):
            self._counts[name] += 1
            self.metrics.set(name, np.int32(self._counts[name]))
        entry = {"decision": decision, **record}
        self.decisions.append(entry)
        get_logger("ctrl").info("decision %s: %s", decision, record)
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.event(
                f"ctrl.{decision}", subsystem="control", **record
            )
        if self.recorder is not None:
            self.recorder.note(f"ctrl.{decision}", **record)
        if self.decision_log is not None:
            snap = self.metrics.snapshot(counter)
            write_jsonl([snap], self.decision_log, extra=entry)

    def stats(self) -> dict:
        """Host-side decision counters + sketch summary."""
        out = {name.split(".", 1)[1]: c for name, c in self._counts.items()}
        if self.sketch is not None:
            out["observed"] = self.sketch.observed
            out["heat_mass"] = self.sketch.total_mass
        return out
