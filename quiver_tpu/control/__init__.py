"""quiver-ctl — telemetry-driven cache & routing control plane.

Closes the loop from graftscope telemetry (tier hits, routed overflow,
the in-program row-heat histogram, StepTimeline stage times) to the
store's placement and routing knobs:

* :mod:`~quiver_tpu.control.freq` — the measuring half: a traced
  positional heat histogram riding the MetricsTape pytree plus an exact
  host-side top-K heavy-hitter set, EMA-decayed between epochs;
* :mod:`~quiver_tpu.control.cost` — an analytic cost model (predicted
  lanes/hop and tier hit rates as a function of L0 split and
  ``routed_alpha``) calibrated from measured StepTimeline stages, using
  the same formulas the benchmarks emit;
* :mod:`~quiver_tpu.control.controller` — :class:`CacheController`:
  between-batch/epoch decisions with hysteresis and dead-bands that
  re-tier L0 to the measured-hottest rows (``ShardedFeature.repin``),
  move the L0/L1 boundary toward measured hit mass, and adjust
  ``routed_alpha`` in BOTH directions — every decision audited as a
  JSONL record through the obs exporters.

The store's ``auto_split`` and the trainer's ``auto_alpha`` remain as
thin compat shims delegating to a default controller; pass
``DistributedTrainer(controller=...)`` / ``InferenceServer(controller=
...)`` to share one across training and serving traffic.
"""

from .controller import AlphaTuner, CacheController, SplitTuner
from .cost import CostModel, predicted_hit_rates, routed_lanes_per_hop
from .freq import FreqSketch, heat_num_bins, row_heat_histogram

__all__ = [
    "AlphaTuner",
    "CacheController",
    "CostModel",
    "FreqSketch",
    "SplitTuner",
    "heat_num_bins",
    "predicted_hit_rates",
    "routed_lanes_per_hop",
    "row_heat_histogram",
]
