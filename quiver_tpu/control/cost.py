"""quiver-ctl analytic cost model — predicted comm/hit-rate surfaces.

The controller's decisions (L0 split, ``routed_alpha``) trade HBM bytes
against interconnect lanes. This module predicts both sides of that
trade from the measured heat histogram, using the SAME lanes-per-hop
formulas ``bench_feature``/``bench_sampler`` emit (so a predicted number
and a scoreboard number are directly comparable), calibrated against
measured :class:`~quiver_tpu.obs.timeline.StepTimeline` stage times:

* comm: a capped routed gather moves ``F * cap`` lanes per all_to_all
  hop with ``cap = ceil(alpha_eff * L / F)`` and
  ``alpha_eff = alpha * (1 - h0)`` — the measured L0 hit rate tightens
  the cap because L0 lanes enter the routed gather as -1 and occupy no
  bucket capacity (feature/shard.py comm model);
* hit rates: the positional heat histogram is monotone in the translated
  row index, so the mass below a candidate boundary IS the predicted
  tier hit mass (:func:`predicted_hit_rates`).

The model is deliberately analytic (closed-form, auditable — every
decision record carries its inputs) rather than learned; it only has to
RANK candidate configurations, and the ranking inputs are exact.
"""

from __future__ import annotations

import math

import numpy as np

__all__ = ["CostModel", "predicted_hit_rates", "routed_lanes_per_hop"]


def routed_lanes_per_hop(local_len: int, num_shards: int,
                         alpha: float | None, h0: float = 0.0) -> dict:
    """Interconnect lanes one capped routed gather moves per all_to_all
    hop — the exact model ``bench_feature`` emits (lanes_per_hop =
    ``F * cap``, uncapped = ``F * L``, effective = ``alpha * L * (1-h0)``).

    ``alpha=None`` means the uncapped full-length buckets. ``h0`` is the
    measured (or predicted) L0 hit rate; L0 lanes are -1 in the routed
    gather and occupy no bucket capacity, so the planned cap tightens by
    ``(1 - h0)``.
    """
    L = int(local_len)
    F = max(int(num_shards), 1)
    uncapped = F * L
    if alpha is None:
        return {
            "cap": L, "lanes_per_hop": uncapped,
            "lanes_per_hop_uncapped": uncapped,
            "effective_lanes_per_hop": float(uncapped),
        }
    alpha_eff = max(float(alpha) * (1.0 - float(h0)), 1e-6)
    cap = max(1, min(int(math.ceil(alpha_eff * L / F)), L))
    return {
        "cap": cap,
        "lanes_per_hop": F * cap,
        "lanes_per_hop_uncapped": uncapped,
        "effective_lanes_per_hop": float(alpha) * L * (1.0 - float(h0)),
    }


def predicted_hit_rates(sketch, rep_rows: int, hot_rows: int) -> dict:
    """Per-tier hit-rate prediction at a CANDIDATE (rep_rows, hot_rows)
    boundary from the sketch's positional heat histogram.

    Because the histogram bins are monotone in the translated row index,
    the mass below ``rep_rows`` is the L0 hit mass that boundary WOULD
    have captured — no replay needed. Returns ``{hit_rep, hit_sharded,
    hit_cold}`` fractions (all zero before any observation).
    """
    total = sketch.total_mass
    if total <= 0:
        return {"hit_rep": 0.0, "hit_sharded": 0.0, "hit_cold": 0.0}
    m0 = sketch.bin_mass_below(rep_rows)
    m01 = sketch.bin_mass_below(rep_rows + hot_rows)
    return {
        "hit_rep": m0 / total,
        "hit_sharded": (m01 - m0) / total,
        "hit_cold": (total - m01) / total,
    }


class CostModel:
    """Predicted step cost as a function of (L0 split, routed_alpha).

    Decomposes a step into a comm-proportional part and a fixed part:
    ``t(split, alpha) ~= t_fixed + t_lane * lanes(split, alpha)``.
    :meth:`calibrate` anchors the two coefficients to a measured
    StepTimeline stage mean at the CURRENT configuration (the controller
    re-calibrates whenever it changes something, so the anchor tracks
    the store); :meth:`predict` evaluates candidates against the anchor.

    Args:
      local_len: per-device gather request length L (static lane width).
      num_shards: feature-axis size F.
      comm_fraction: share of the anchored stage time attributed to the
        routed gather's collectives at calibration time. The default is
        deliberately conservative (overlap and fusion hide comm; see
        the pipelined-epoch overlap_efficiency gauge) — the model only
        ranks candidates, and ranking is monotone in this knob.
    """

    def __init__(self, local_len: int, num_shards: int,
                 comm_fraction: float = 0.3):
        self.local_len = int(local_len)
        self.num_shards = max(int(num_shards), 1)
        self.comm_fraction = float(np.clip(comm_fraction, 0.0, 1.0))
        self._t_fixed = 0.0
        self._t_lane = 0.0
        self.calibrated = False
        self._t_window = 0.0    # exposed seconds per disk window read
        self._window_rows = 1
        self.disk_calibrated = False
        self._hbm_peaks = {}    # target name -> static per-device peak bytes
        self.hbm_calibrated = False

    def calibrate(self, timeline, stage: str = "step",
                  alpha: float | None = None, h0: float = 0.0) -> bool:
        """Anchor the coefficients to ``timeline``'s measured mean for
        ``stage`` at the current (alpha, h0) operating point. Returns
        False (model unchanged) when the stage has no samples yet."""
        stats = timeline.summary().get(stage)
        if stats is None or getattr(stats, "count", 0) == 0:
            return False
        mean_s = float(stats.mean)
        lanes = routed_lanes_per_hop(
            self.local_len, self.num_shards, alpha, h0
        )["lanes_per_hop"]
        self._t_lane = self.comm_fraction * mean_s / max(lanes, 1)
        self._t_fixed = mean_s - self._t_lane * lanes
        self.calibrated = True
        return True

    def predict(self, sketch, rep_rows: int, hot_rows: int,
                alpha: float | None) -> dict:
        """Predicted hit rates, lanes/hop, and (when calibrated) step
        seconds for a candidate ``(rep_rows, hot_rows, alpha)``."""
        hits = predicted_hit_rates(sketch, rep_rows, hot_rows)
        lanes = routed_lanes_per_hop(
            self.local_len, self.num_shards, alpha, hits["hit_rep"]
        )
        out = {**hits, **lanes, "rep_rows": int(rep_rows),
               "hot_rows": int(hot_rows),
               "alpha": None if alpha is None else float(alpha)}
        if self.calibrated:
            out["est_step_s"] = (
                self._t_fixed + self._t_lane * lanes["lanes_per_hop"]
            )
        return out

    # -- disk tier (quiver-ooc) ----------------------------------------------

    def calibrate_disk(self, timeline, stager,
                       stage: str = "ooc.stage_wait") -> bool:
        """Anchor the disk-read coefficient: EXPOSED seconds per window
        read, from the measured ``ooc.stage_wait`` stage total over the
        stager's issued window reads. Exposed (not raw read) time is the
        right unit — reads the :class:`~quiver_tpu.ooc.stager
        .AsyncStager` hid under compute cost the step nothing, and the
        controller is ranking promotions by step-time saved. Returns
        False (model unchanged) until a wait has been observed."""
        stats = timeline.summary().get(stage)
        reads = int(getattr(stager, "page_reads_total", 0))
        if stats is None or getattr(stats, "count", 0) == 0 or reads == 0:
            return False
        self._t_window = float(stats.total) / reads
        self._window_rows = max(int(getattr(stager, "window_rows", 1)), 1)
        self.disk_calibrated = True
        return True

    def predict_disk(self, sketch, hot_rows: int,
                     resident_rows: int = 0) -> dict:
        """Predicted per-step disk exposure for a candidate host-cache
        size. The sketch's heat mass ABOVE ``hot_rows + resident_rows``
        (translated row space: rows neither in HBM nor promoted to the
        host cache) is the miss mass that must come off disk; when
        :meth:`calibrate_disk` has run, that converts to estimated
        exposed seconds per observed step via the measured
        window-read cost."""
        total = sketch.total_mass
        resident = int(hot_rows) + int(resident_rows)
        if total <= 0:
            return {"miss_mass": 0.0, "hit_disk": 0.0,
                    "resident_rows": resident}
        below = sketch.bin_mass_below(resident)
        miss = max(total - below, 0.0)
        out = {
            "miss_mass": miss,
            "hit_disk": miss / total,
            "resident_rows": resident,
        }
        if self.disk_calibrated:
            # miss rows -> window reads (each window amortizes
            # window_rows rows in the best — staged-layout — case)
            out["est_disk_s_per_obs"] = (
                self._t_window * miss / total / self._window_rows
            )
        return out

    # -- static HBM peaks (graftmem) -----------------------------------------

    def calibrate_hbm(self, peaks: dict) -> bool:
        """Anchor the per-target static peak-HBM surface from graftmem's
        liveness-walk estimates (``{target_name: peak_bytes}`` — e.g. the
        ``peak_bytes`` column of :func:`quiver_tpu.tools.audit.mem
        .peak_table`). Unlike the timing coefficients these are not
        measured: they are PROVEN upper-shape bounds over the lowered IR,
        so a candidate the controller is ranking can be rejected for not
        fitting before anything executes. Returns False (model
        unchanged) on an empty mapping."""
        clean = {str(k): int(v) for k, v in dict(peaks).items()
                 if int(v) >= 0}
        if not clean:
            return False
        self._hbm_peaks.update(clean)
        self.hbm_calibrated = True
        return True

    def predict_hbm(self, target: str, budget_bytes: int | None = None
                    ) -> dict:
        """Predicted per-device peak bytes for ``target`` against an
        optional budget. ``known`` is False for a target the model has
        not been calibrated with (``fits`` stays None rather than
        guessing); with a budget, ``headroom_bytes`` < 0 means the
        static walk already proves the candidate cannot fit."""
        peak = self._hbm_peaks.get(str(target))
        out = {
            "target": str(target),
            "known": peak is not None,
            "peak_bytes": peak,
            "budget_bytes": None if budget_bytes is None
            else int(budget_bytes),
            "headroom_bytes": None,
            "fits": None,
        }
        if peak is not None and budget_bytes is not None:
            out["headroom_bytes"] = int(budget_bytes) - peak
            out["fits"] = peak <= int(budget_bytes)
        return out
