"""Transactional streaming graph mutation (ROADMAP item 3).

Production graphs mutate under traffic; the resident state this framework
keeps per chip — the (sharded) CSR topology, the three-tier feature store,
the trainer's captured operands — must evolve WITHOUT a full rebuild and
without ever exposing a half-applied or corrupt update. This package is
that machinery:

* :class:`DeltaBatch` + admission validation (``delta.py``) — the
  ingestion boundary; malformed batches are quarantined whole with a
  reason (``streaming.deltas_quarantined``), never partially applied.
* :class:`StreamingGraph` (``commit.py``) — staging, atomic
  epoch-boundary commits (merge aside → verify invariants → publish with
  ONE version bump), bit-identical rollback on any failure.
* Versioned invalidation — committed versions thread through
  ``CSRTopo``/``ShardedTopology``/``ShardedFeature`` and their consumers
  (samplers, ``DistributedTrainer``), which raise
  :class:`VersionMismatchError` instead of serving stale reads until
  their ``refresh`` seams re-place.

The drillable failure modes live in ``benchmarks/chaos.py`` (``mutate``
drill); the incremental-vs-rebuild bit-parity differential in
``tests/test_streaming.py``.
"""

from ..core.topology import VersionMismatchError
from .commit import (
    CommitAborted,
    CommitResult,
    QuarantineRecord,
    StreamingGraph,
    merge_csr,
    verify_merged_csr,
)
from .delta import DeltaBatch, DeltaRejected, validate_delta

__all__ = [
    "CommitAborted",
    "CommitResult",
    "DeltaBatch",
    "DeltaRejected",
    "QuarantineRecord",
    "StreamingGraph",
    "VersionMismatchError",
    "merge_csr",
    "validate_delta",
    "verify_merged_csr",
]
