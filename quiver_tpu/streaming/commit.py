"""Atomic epoch-boundary commits of staged deltas.

The commit protocol (the transactional half of ROADMAP item 3):

1. **Merge aside** — :func:`merge_csr` builds the post-mutation
   ``indptr``/``indices`` as FRESH arrays; the committed CSR is never
   touched. Per row, surviving old neighbors keep their slot order and
   inserts append in ingestion order — exactly the layout a full
   ``CSRTopo(edge_index=final_coo)`` rebuild produces (stable argsort),
   which is what makes the incremental path bit-identical to a rebuild
   (the acceptance differential). Deletes remove the EARLIEST matching
   occurrences (old slots first, then staged inserts in order).
2. **Verify** — :func:`verify_merged_csr` re-derives every post-merge
   invariant from scratch: indptr starts at 0 and is monotone, the edge
   arithmetic ``E' = E + inserts - deletes`` holds, every neighbor id is
   in range, the node count is unchanged (so the contiguous owner map
   ``v // rows_per_shard`` of every sharded consumer covers every row by
   construction), and the UNTOUCHED rows' adjacency bytes checksum
   (CRC32) identically to the pre-merge arrays — a merge bug cannot
   corrupt rows the deltas never named.
3. **Publish** — one call into ``CSRTopo._publish_mutation`` (a handful
   of reference assignments) swaps the verified arrays in and bumps the
   version ONCE; prepared feature-row updates publish through
   ``ShardedFeature.apply_row_updates`` under the same transaction.
   Consumers holding device placements of the old version
   (samplers, trainers) raise
   :class:`~quiver_tpu.core.topology.VersionMismatchError` instead of
   serving stale reads, until their ``refresh`` seams re-place.

ANY failure before publish aborts the whole transaction: the staged
batches are quarantined with the reason (``streaming.deltas_quarantined``
on the graftscope registry), the committed state is untouched
bit-identically, and :class:`CommitAborted` propagates to the caller.
``commit(inject_failure=)`` is the deterministic chaos seam (the
FaultPlan discipline): it forces the abort path at a named stage so the
rollback contract is drillable (benchmarks/chaos.py ``mutate``).
"""

from __future__ import annotations

import dataclasses
import zlib

import numpy as np

from ..core.topology import CSRTopo, VersionMismatchError
from ..obs.registry import (
    DELTAS_COMMITTED,
    DELTAS_QUARANTINED,
    STREAMING_COMMITS,
    MetricsRegistry,
)
from ..utils.trace import get_logger
from .delta import DeltaBatch, DeltaRejected, encode_pairs, validate_delta

__all__ = [
    "CommitAborted",
    "CommitResult",
    "QuarantineRecord",
    "StreamingGraph",
    "merge_csr",
    "verify_merged_csr",
]


class CommitAborted(RuntimeError):
    """A commit failed before publish. The pre-commit state is intact
    bit-identically (nothing was applied); the staged batches were
    quarantined with the failure reason."""


@dataclasses.dataclass(frozen=True)
class QuarantineRecord:
    """One quarantined ingestion/commit failure: where it failed
    (``stage``: "ingest" or "commit"), why, and the offending batches."""

    stage: str
    reason: str
    deltas: tuple[DeltaBatch, ...]


@dataclasses.dataclass(frozen=True)
class CommitResult:
    """Summary of one published commit."""

    version: int
    batches: int
    edges_inserted: int
    edges_deleted: int
    rows_updated: int
    edge_count: int


def merge_csr(indptr: np.ndarray, indices: np.ndarray,
              inserts: np.ndarray | None, deletes: np.ndarray | None,
              attrs: dict | None = None):
    """Merge COO edge inserts/deletes into fresh CSR arrays.

    Returns ``(new_indptr, new_indices, touched)`` where ``touched`` is
    the boolean per-row mask of rows whose adjacency changed. The input
    arrays are read-only; untouched rows are copied verbatim in
    contiguous runs.

    ``attrs`` (optional) threads per-edge attribute columns through the
    merge: ``{name: (old_column, insert_column)}`` where ``old_column``
    is ``(E,)`` in the committed CSR's slot order and ``insert_column``
    is one value per ``inserts`` column (or None when there are no
    inserts). Every kept slot keeps its attribute, every appended insert
    brings its own, and a deleted slot's attribute is dropped with it —
    so the columns stay aligned with ``new_indices`` slot for slot. With
    ``attrs`` the return gains a fourth element ``{name: new_column}``.
    """
    indptr = np.asarray(indptr, dtype=np.int64)
    indices = np.asarray(indices)
    n = int(indptr.shape[0] - 1)
    deg = np.diff(indptr)

    # per destination row: (neighbor id, insert column index) — the column
    # index is the provenance attribute columns are gathered by
    ins_by_row: dict[int, list[tuple[int, int]]] = {}
    if inserts is not None and inserts.shape[1]:
        for i, (s, d) in enumerate(
                zip(inserts[0].tolist(), inserts[1].tolist())):
            ins_by_row.setdefault(int(s), []).append((int(d), i))
    del_by_row: dict[int, dict[int, int]] = {}
    if deletes is not None and deletes.shape[1]:
        for s, d in zip(deletes[0].tolist(), deletes[1].tolist()):
            cnt = del_by_row.setdefault(int(s), {})
            cnt[int(d)] = cnt.get(int(d), 0) + 1

    touched = np.zeros(n, dtype=bool)
    for r in ins_by_row:
        touched[r] = True
    for r in del_by_row:
        touched[r] = True

    new_deg = deg.copy()
    for r in ins_by_row:
        new_deg[r] += len(ins_by_row[r])
    for r, cnt in del_by_row.items():
        new_deg[r] -= sum(cnt.values())
    if (new_deg < 0).any():
        bad = int(np.argwhere(new_deg < 0)[0, 0])
        raise DeltaRejected(
            f"row {bad} would end with negative degree after deletes — "
            f"more deletes than live edges (admission should have caught "
            f"this; the staged set is inconsistent)"
        )
    new_indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(new_deg, out=new_indptr[1:])
    new_indices = np.empty(int(new_indptr[-1]), dtype=np.int64)
    new_attrs = None
    if attrs is not None:
        new_attrs = {
            name: np.empty(int(new_indptr[-1]), dtype=old.dtype)
            for name, (old, _) in attrs.items()
        }

    touched_rows = np.flatnonzero(touched)
    # copy untouched spans between consecutive touched rows in single
    # slices; rebuild only the touched rows in Python (O(touched))
    prev = 0
    for r in touched_rows.tolist():
        if r > prev:  # untouched run [prev, r)
            new_indices[new_indptr[prev]:new_indptr[r]] = \
                indices[indptr[prev]:indptr[r]]
            if attrs is not None:
                for name, (old_col, _) in attrs.items():
                    new_attrs[name][new_indptr[prev]:new_indptr[r]] = \
                        old_col[indptr[prev]:indptr[r]]
        old = indices[indptr[r]:indptr[r + 1]].tolist()
        pending = dict(del_by_row.get(r, {}))
        kept = []
        src = []  # provenance: old slot position >= 0, insert col -(i+1)
        for j, v in enumerate(old):
            if pending.get(v, 0) > 0:
                pending[v] -= 1  # earliest occurrence removed first
            else:
                kept.append(v)
                src.append(int(indptr[r]) + j)
        # inserts append, ingestion order
        for v, i in ins_by_row.get(r, ()):
            if pending.get(v, 0) > 0:
                pending[v] -= 1  # delete staged after the insert it names
            else:
                kept.append(v)
                src.append(-(i + 1))
        new_indices[new_indptr[r]:new_indptr[r + 1]] = kept
        if attrs is not None and kept:
            src = np.asarray(src, dtype=np.int64)
            old_slot = src >= 0
            for name, (old_col, ins_col) in attrs.items():
                seg = np.empty(len(kept), dtype=old_col.dtype)
                seg[old_slot] = old_col[src[old_slot]]
                if (~old_slot).any():
                    seg[~old_slot] = ins_col[-src[~old_slot] - 1]
                new_attrs[name][new_indptr[r]:new_indptr[r + 1]] = seg
        prev = r + 1
    if prev < n:
        new_indices[new_indptr[prev]:] = indices[indptr[prev]:]
        if attrs is not None:
            for name, (old_col, _) in attrs.items():
                new_attrs[name][new_indptr[prev]:] = old_col[indptr[prev]:]
    if attrs is None:
        return new_indptr, new_indices, touched
    return new_indptr, new_indices, touched, new_attrs


def _untouched_crc(indptr: np.ndarray, indices: np.ndarray,
                   touched: np.ndarray) -> int:
    """CRC32 over the concatenated adjacency bytes of untouched rows
    (canonical int64), streamed span by span."""
    crc = 0
    n = int(indptr.shape[0] - 1)
    prev = 0
    for r in np.flatnonzero(touched).tolist():
        if r > prev:
            span = np.ascontiguousarray(
                indices[int(indptr[prev]):int(indptr[r])], dtype=np.int64
            )
            crc = zlib.crc32(span.tobytes(), crc)
        prev = r + 1
    if prev < n:
        span = np.ascontiguousarray(
            indices[int(indptr[prev]):], dtype=np.int64
        )
        crc = zlib.crc32(span.tobytes(), crc)
    return crc & 0xFFFFFFFF


def verify_merged_csr(old_indptr, old_indices, new_indptr, new_indices,
                      touched: np.ndarray, inserted: int,
                      deleted: int) -> None:
    """Re-derive every post-merge invariant; raise :class:`DeltaRejected`
    naming the first violation. Independent of :func:`merge_csr`'s
    internals on purpose — it re-checks the OUTPUT arrays from scratch,
    so a merge bug is caught here rather than published."""
    old_indptr = np.asarray(old_indptr, dtype=np.int64)
    new_indptr = np.asarray(new_indptr, dtype=np.int64)
    n = int(old_indptr.shape[0] - 1)
    if int(new_indptr.shape[0] - 1) != n:
        raise DeltaRejected(
            f"post-merge node count {int(new_indptr.shape[0] - 1)} != {n}: "
            f"the owner map of every sharded consumer would break"
        )
    if int(new_indptr[0]) != 0:
        raise DeltaRejected("post-merge indptr does not start at 0")
    if (np.diff(new_indptr) < 0).any():
        bad = int(np.argwhere(np.diff(new_indptr) < 0)[0, 0])
        raise DeltaRejected(
            f"post-merge indptr is not monotone at row {bad}"
        )
    if int(new_indptr[-1]) != new_indices.shape[0]:
        raise DeltaRejected(
            f"post-merge indptr[-1]={int(new_indptr[-1])} != "
            f"len(indices)={new_indices.shape[0]}"
        )
    expected = int(old_indptr[-1]) + int(inserted) - int(deleted)
    if int(new_indptr[-1]) != expected:
        raise DeltaRejected(
            f"edge-count arithmetic failed: {int(old_indptr[-1])} + "
            f"{inserted} - {deleted} = {expected}, merge produced "
            f"{int(new_indptr[-1])}"
        )
    if new_indices.size:
        lo, hi = int(new_indices.min()), int(new_indices.max())
        if lo < 0 or hi >= n:
            raise DeltaRejected(
                f"post-merge indices reference node ids outside "
                f"[0, {n}) (range [{lo}, {hi}])"
            )
    # untouched rows: degree AND content byte-identical to pre-merge
    un = ~np.asarray(touched, dtype=bool)
    if not np.array_equal(np.diff(old_indptr)[un], np.diff(new_indptr)[un]):
        bad = int(np.flatnonzero(
            un & (np.diff(old_indptr) != np.diff(new_indptr))
        )[0])
        raise DeltaRejected(
            f"untouched row {bad} changed degree — the merge leaked "
            f"outside the delta's footprint"
        )
    old_crc = _untouched_crc(old_indptr, old_indices, touched)
    new_crc = _untouched_crc(new_indptr, new_indices, touched)
    if old_crc != new_crc:
        raise DeltaRejected(
            f"untouched-range checksum mismatch (pre {old_crc:#x} vs "
            f"post {new_crc:#x}) — the merge corrupted rows the deltas "
            f"never named"
        )


_FAIL_STAGES = ("merge", "verify", "features")


class StreamingGraph:
    """Transactional mutation coordinator for resident graph state.

    Owns the staging buffer, the admission boundary, the quarantine log,
    and the atomic commit of staged deltas into a :class:`CSRTopo` (and,
    when attached, a :class:`~quiver_tpu.feature.shard.ShardedFeature`'s
    rows). Device-side consumers (samplers, trainers) are NOT mutated
    here — they detect the published version bump through their own
    version checks and re-place via their ``refresh`` seams; see the
    module docstring for the protocol.

    Args:
      csr_topo: the committed host CSR. A weighted and/or timestamped
        topology is supported: its attribute columns ride the merge slot
        for slot (kept edges keep theirs, inserts must supply their own
        through ``DeltaBatch.edge_weights``/``edge_times`` — admission
        rejects attribute-less inserts whole with a named reason — and a
        deleted slot's attribute is dropped with it). ``eid`` provenance
        does not survive mutation (``with_eid`` consumers re-place
        against the rebuilt CSR).
      feature: optional ShardedFeature whose rows feature deltas update
        (row updates publish in the same transaction as the topology
        merge; its ``note_degree_update`` re-tiering hook runs after a
        commit that changed degrees).
      duplicates: admission duplicate policy — ``"error"`` (default)
        rejects duplicate edge inserts / update ids per batch;
        ``"allow"`` admits parallel edges and collapses duplicate update
        ids last-wins.
    """

    def __init__(self, csr_topo: CSRTopo, feature=None,
                 duplicates: str = "error", recorder=None):
        if duplicates not in ("error", "allow"):
            raise ValueError(
                f"duplicates must be 'error' or 'allow', got {duplicates!r}"
            )
        self.csr_topo = csr_topo
        # flight-recorder seam: an aborted commit or an admission
        # quarantine dumps a postmortem bundle naming the stage
        self.recorder = recorder
        # the admission schema mirrors the committed topology's edge
        # attributes: inserts must carry exactly these (validate_delta
        # rejects mismatches whole, both directions)
        self.needs_weights = csr_topo.edge_weight is not None
        self.needs_times = csr_topo.edge_time is not None
        self.feature = feature
        if feature is not None and not hasattr(feature, "apply_row_updates"):
            raise ValueError(
                "feature must support transactional row updates "
                "(ShardedFeature.apply_row_updates); got "
                f"{type(feature).__name__}"
            )
        self.duplicates = duplicates
        self._staged: list[DeltaBatch] = []
        self.quarantined: list[QuarantineRecord] = []
        self._quarantined_total = 0
        self._committed_total = 0
        self._commits_total = 0
        self.metrics = MetricsRegistry()
        self.metrics.counter(
            DELTAS_QUARANTINED, unit="batches",
            doc="delta batches rejected at admission or by a failed "
                "commit (quarantined with a reason, never applied)",
        )
        self.metrics.counter(
            DELTAS_COMMITTED, unit="batches",
            doc="delta batches merged by a published commit",
        )
        self.metrics.counter(
            STREAMING_COMMITS, unit="commits",
            doc="published commits (= version bumps)",
        )

    # -- staging ------------------------------------------------------------

    @property
    def staged(self) -> tuple[DeltaBatch, ...]:
        """The admitted, not-yet-committed batches (read-only view)."""
        return tuple(self._staged)

    def staged_counts(self) -> tuple[int, int, int]:
        """Total staged (edge inserts, edge deletes, row updates)."""
        ei = ed = u = 0
        for d in self._staged:
            a, b, c = d.counts()
            ei, ed, u = ei + a, ed + b, u + c
        return ei, ed, u

    def _live_pair_counts(self) -> dict[int, int]:
        """Encoded-pair multiset of live edges: the committed CSR
        adjusted by the already-staged inserts/deletes — what a new
        batch's deletes must exist in."""
        n = self.csr_topo.node_count
        indptr = np.asarray(self.csr_topo.indptr, dtype=np.int64)
        indices = np.asarray(self.csr_topo.indices, dtype=np.int64)
        src = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
        keys, cnts = np.unique(
            encode_pairs(src, indices, n), return_counts=True
        )
        live = dict(zip(keys.tolist(), cnts.tolist()))
        for d in self._staged:
            if d.edge_inserts is not None and d.edge_inserts.shape[1]:
                for k in encode_pairs(
                        d.edge_inserts[0], d.edge_inserts[1], n).tolist():
                    live[k] = live.get(k, 0) + 1
            if d.edge_deletes is not None and d.edge_deletes.shape[1]:
                for k in encode_pairs(
                        d.edge_deletes[0], d.edge_deletes[1], n).tolist():
                    live[k] = live.get(k, 0) - 1
        return live

    def _quarantine(self, stage: str, reason: str,
                    deltas: tuple[DeltaBatch, ...]) -> None:
        self.quarantined.append(QuarantineRecord(stage, reason, deltas))
        self._quarantined_total += len(deltas)
        self.metrics.set(
            DELTAS_QUARANTINED, np.int32(self._quarantined_total)
        )
        get_logger("streaming").warning(
            "quarantined %d delta batch(es) at %s: %s",
            len(deltas), stage, reason,
        )
        if self.recorder is not None:
            self.recorder.trigger(
                "commit_abort" if stage == "commit" else "quarantine",
                stage=stage, cause=reason, batches=len(deltas),
            )

    def ingest(self, delta: DeltaBatch) -> bool:
        """Admission-validate ``delta`` and stage it for the next commit.

        Returns True when staged; on ANY failing check the batch is
        quarantined whole with the reason (``quarantined`` log +
        ``streaming.deltas_quarantined``) and False returns — a rejected
        batch is never partially staged, and the duplicate/existence
        accounting already includes earlier staged batches."""
        try:
            if not isinstance(delta, DeltaBatch):
                raise DeltaRejected(
                    f"expected a DeltaBatch, got {type(delta).__name__}"
                )
            fs = None
            if self.feature is not None:
                fs = self.feature.shape
            normalized = validate_delta(
                delta, self.csr_topo.node_count, fs,
                live_pair_counts=self._live_pair_counts(),
                duplicates=self.duplicates,
                needs_weights=self.needs_weights,
                needs_times=self.needs_times,
            )
        except DeltaRejected as e:
            self._quarantine("ingest", str(e), (delta,))
            return False
        self._staged.append(normalized)
        return True

    # -- commit -------------------------------------------------------------

    def _collapse_updates(self):
        """Fold the staged batches' feature updates into one last-wins
        (id, rows) pair in first-touch order — the same outcome as
        applying the batches sequentially."""
        merged: dict[int, np.ndarray] = {}
        for d in self._staged:
            if d.update_ids is None:
                continue
            for i, node in enumerate(d.update_ids.tolist()):
                merged[int(node)] = d.update_rows[i]
        if not merged:
            return None, None
        ids = np.fromiter(merged.keys(), dtype=np.int64, count=len(merged))
        rows = np.stack([merged[int(i)] for i in ids])
        return ids, rows

    def commit(self, inject_failure: str | None = None) -> CommitResult | None:
        """Atomically publish every staged batch; returns the
        :class:`CommitResult` (or None when nothing is staged).

        All-or-nothing: the merged CSR and the collapsed feature updates
        are built and VERIFIED aside, then published with one version
        bump each (topology, feature). Any failure before publish
        quarantines the whole staged set with the reason, leaves the
        committed state bit-identical, and raises :class:`CommitAborted`.
        After a successful commit the updated degrees feed the attached
        store's re-tiering hook (``note_degree_update``), and stale
        consumers raise ``VersionMismatchError`` until refreshed.

        ``inject_failure`` is the deterministic chaos seam (FaultPlan
        discipline, drilled by ``benchmarks/chaos.py mutate``): force the
        abort path at stage ``"merge"``, ``"verify"``, or ``"features"``
        — i.e. a crash at ANY point before publish — and observe the old
        version intact.
        """
        if inject_failure is not None and inject_failure not in _FAIL_STAGES:
            raise ValueError(
                f"inject_failure must be one of {_FAIL_STAGES}, "
                f"got {inject_failure!r}"
            )
        if not self._staged:
            return None
        staged = tuple(self._staged)
        topo = self.csr_topo
        try:
            ins_parts = [d.edge_inserts for d in staged
                         if d.edge_inserts is not None
                         and d.edge_inserts.shape[1]]
            del_parts = [d.edge_deletes for d in staged
                         if d.edge_deletes is not None
                         and d.edge_deletes.shape[1]]
            inserts = np.concatenate(ins_parts, axis=1) if ins_parts else None
            deletes = np.concatenate(del_parts, axis=1) if del_parts else None
            n_ins = 0 if inserts is None else int(inserts.shape[1])
            n_del = 0 if deletes is None else int(deletes.shape[1])
            old_indptr = np.asarray(topo.indptr, dtype=np.int64)
            old_indices = np.asarray(topo.indices)
            topo_changed = bool(n_ins or n_del)
            # the topology's attribute columns ride the merge: one insert
            # column per staged batch (admission guaranteed alignment),
            # concatenated in the same order as the inserts themselves
            attrs = None
            if self.needs_weights or self.needs_times:
                attrs = {}
                for name, needed, old_col in (
                    ("edge_weight", self.needs_weights, topo.edge_weight),
                    ("edge_time", self.needs_times, topo.edge_time),
                ):
                    if not needed:
                        continue
                    parts = [
                        getattr(d, name + "s") for d in staged
                        if d.edge_inserts is not None
                        and d.edge_inserts.shape[1]
                    ]
                    attrs[name] = (
                        np.asarray(old_col),
                        np.concatenate(parts) if parts else None,
                    )
            if inject_failure == "merge":
                raise DeltaRejected(
                    "injected commit failure at stage 'merge' (chaos seam)"
                )
            new_attrs = {}
            if topo_changed:
                merged = merge_csr(
                    old_indptr, old_indices, inserts, deletes, attrs
                )
                if attrs is None:
                    new_indptr, new_indices, touched = merged
                else:
                    new_indptr, new_indices, touched, new_attrs = merged
            else:
                new_indptr, new_indices = old_indptr, old_indices
                touched = np.zeros(topo.node_count, dtype=bool)
            if inject_failure == "verify":
                raise DeltaRejected(
                    "injected commit failure at stage 'verify' (chaos seam)"
                )
            if topo_changed:
                verify_merged_csr(
                    old_indptr, old_indices, new_indptr, new_indices,
                    touched, n_ins, n_del,
                )
            upd_ids, upd_rows = self._collapse_updates()
            if inject_failure == "features":
                raise DeltaRejected(
                    "injected commit failure at stage 'features' "
                    "(chaos seam)"
                )
        except (DeltaRejected, ValueError, VersionMismatchError) as e:
            self._staged.clear()
            self._quarantine("commit", str(e), staged)
            raise CommitAborted(
                f"commit of {len(staged)} staged batch(es) aborted before "
                f"publish: {e} (pre-commit state intact; batches "
                f"quarantined)"
            ) from e
        # ---- publish: everything above is verified and aside ----
        if topo_changed:
            topo._publish_mutation(
                new_indptr, new_indices,
                edge_weight=new_attrs.get("edge_weight"),
                edge_time=new_attrs.get("edge_time"),
            )
        if upd_ids is not None:
            self.feature.apply_row_updates(upd_ids, upd_rows)
        self._staged.clear()
        self._committed_total += len(staged)
        self._commits_total += 1
        self.metrics.set(DELTAS_COMMITTED, np.int32(self._committed_total))
        self.metrics.set(STREAMING_COMMITS, np.int32(self._commits_total))
        if topo_changed and self.feature is not None:
            # re-tiering follows mutation: the new degree distribution
            # feeds the store's existing split tuner
            self.feature.note_degree_update(topo.degree)
        result = CommitResult(
            version=topo.version,
            batches=len(staged),
            edges_inserted=n_ins,
            edges_deleted=n_del,
            rows_updated=0 if upd_ids is None else int(upd_ids.shape[0]),
            edge_count=topo.edge_count,
        )
        get_logger("streaming").info(
            "committed v%d: %d batch(es), +%d/-%d edges (E=%d), %d row "
            "update(s); stale consumers must refresh",
            result.version, result.batches, n_ins, n_del,
            result.edge_count, result.rows_updated,
        )
        return result
