"""Delta batches and their admission validation.

A :class:`DeltaBatch` is the unit of streaming graph mutation: edge
inserts, edge deletes, and feature row updates that arrived together and
must land together. Admission (:func:`validate_delta`) is the ingestion
boundary of the transactional layer: every structural and semantic check
runs BEFORE a batch is staged, so a malformed batch is quarantined whole —
it can never be partially applied, and nothing downstream (the commit
merge, the device placements) ever sees invalid state. The checks, in
order:

* **structure** — edge arrays are ``(2, E)`` integer COO, update ids are
  1-D integers with a matching ``(U, feature_dim)`` float row block;
* **range** — every edge endpoint and update id lies in
  ``[0, node_count)`` (streaming deltas never add or remove nodes — the
  owner map ``v // rows_per_shard`` of every sharded consumer stays valid
  by construction, the invariant Zeng et al. (arXiv:2010.03166) scale-out
  partitioning assumes);
* **non-finite scan** — a NaN/Inf feature row is rejected here, not
  cached and served;
* **edge attributes** — when the committed topology carries per-edge
  weights and/or timestamps, every inserted edge must supply a matching
  attribute (``edge_weights``/``edge_times`` aligned to the
  ``edge_inserts`` columns), validated with the SAME rules as
  ``CSRTopo.set_edge_weight``/``set_edge_time`` (finite; weights
  non-negative); a batch that omits them — or supplies them to a
  topology that doesn't carry the attribute — is rejected whole with a
  named reason (``missing-edge-weights`` / ``unexpected-edge-times`` /
  ...), so a commit can never publish a weighted/timestamped CSR with
  attribute-less rows;
* **duplicate policy** — WITHIN one batch, duplicate edge inserts and
  duplicate update ids are rejected under ``duplicates="error"`` (the
  default) or collapsed/allowed under ``"allow"`` (updates: last wins).
  Inserts that parallel an edge already in the graph are always
  admitted — COO-built reference graphs are multigraphs;
* **delete existence** — every delete must match a live edge in the
  current committed CSR plus the already-staged deltas (multiset
  accounting, so an insert staged earlier in the same window can be
  deleted later in it).

A failing check raises :class:`DeltaRejected` with the reason; the
:class:`~quiver_tpu.streaming.commit.StreamingGraph` catches it, records
a quarantine entry, and counts ``streaming.deltas_quarantined``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["DeltaBatch", "DeltaRejected", "validate_delta", "encode_pairs"]


class DeltaRejected(ValueError):
    """A delta batch failed admission (or its commit failed verification)
    and was quarantined with this reason. The batch was never — and will
    never be — applied, in whole or in part."""


@dataclasses.dataclass(frozen=True)
class DeltaBatch:
    """One atomic unit of streaming mutation.

    ``edge_inserts`` / ``edge_deletes`` are ``(2, E)`` COO arrays
    (``[0]`` = source row, ``[1]`` = destination) over the EXISTING node
    id space; ``update_ids``/``update_rows`` are the feature rows to
    overwrite (original node ids + their new ``(U, feature_dim)``
    values). ``edge_weights``/``edge_times`` are per-inserted-edge
    attributes aligned to the ``edge_inserts`` columns — REQUIRED when
    the committed topology is weighted/timestamped, inadmissible when it
    is not (admission enforces both directions). Any field may be
    ``None``. ``tag`` labels the batch in quarantine records and logs.
    """

    edge_inserts: np.ndarray | None = None
    edge_deletes: np.ndarray | None = None
    update_ids: np.ndarray | None = None
    update_rows: np.ndarray | None = None
    edge_weights: np.ndarray | None = None
    edge_times: np.ndarray | None = None
    tag: str = ""

    def counts(self) -> tuple[int, int, int]:
        """(edge inserts, edge deletes, feature row updates)."""
        ei = 0 if self.edge_inserts is None else self.edge_inserts.shape[1]
        ed = 0 if self.edge_deletes is None else self.edge_deletes.shape[1]
        u = 0 if self.update_ids is None else self.update_ids.shape[0]
        return int(ei), int(ed), int(u)

    def __repr__(self):
        ei, ed, u = self.counts()
        tag = f" tag={self.tag!r}" if self.tag else ""
        return f"DeltaBatch(+{ei}e, -{ed}e, ~{u}rows{tag})"


def encode_pairs(src: np.ndarray, dst: np.ndarray, n: int) -> np.ndarray:
    """Encode (src, dst) edge endpoints as single int64 keys for multiset
    accounting (``src * n + dst`` — exact for ``n`` up to the int32 node
    id ceiling, since ``n**2 < 2**63``)."""
    return src.astype(np.int64) * np.int64(n) + dst.astype(np.int64)


def _as_edge_array(arr, what: str) -> np.ndarray:
    arr = np.asarray(arr)
    if arr.ndim != 2 or arr.shape[0] != 2:
        raise DeltaRejected(
            f"{what} must be a (2, E) COO array, got shape {arr.shape}"
        )
    if not np.issubdtype(arr.dtype, np.integer):
        raise DeltaRejected(
            f"{what} must carry integer node ids, got dtype {arr.dtype}"
        )
    return arr.astype(np.int64, copy=False)


def _check_range(arr: np.ndarray, n: int, what: str) -> None:
    if arr.size == 0:
        return
    lo, hi = int(arr.min()), int(arr.max())
    if lo < 0 or hi >= n:
        raise DeltaRejected(
            f"{what} reference node ids outside [0, {n}) "
            f"(range [{lo}, {hi}]); streaming deltas never add nodes"
        )


def _admit_edge_attr(vals, n_ins: int, needed: bool, name: str, *,
                     nonneg: bool) -> np.ndarray | None:
    """Admission-check one per-inserted-edge attribute column against the
    committed topology's schema (``needed``). Returns the normalized f32
    column (or None) or raises :class:`DeltaRejected` with a named
    reason (``missing-``/``unexpected-``/``bad-`` + ``name``)."""
    attr = name.replace("-", "_")  # DeltaBatch field name in messages
    if needed and n_ins and vals is None:
        raise DeltaRejected(
            f"missing-{name}: the committed topology carries per-edge "
            f"{name.split('-')[1]}; every inserted edge must supply one "
            f"(DeltaBatch.{attr} aligned to the edge_inserts columns)"
        )
    if vals is None:
        return None
    if not needed:
        raise DeltaRejected(
            f"unexpected-{name}: the committed topology carries no "
            f"per-edge {name.split('-')[1]}; attach them to the CSR "
            f"before streaming attributed deltas"
        )
    vals = np.asarray(vals).reshape(-1)
    if vals.shape[0] != n_ins:
        raise DeltaRejected(
            f"bad-{name}: need one entry per inserted edge ({n_ins}), "
            f"got {vals.shape[0]}"
        )
    if not np.issubdtype(vals.dtype, np.number) or np.issubdtype(
            vals.dtype, np.complexfloating):
        raise DeltaRejected(
            f"bad-{name}: must be real numbers, got dtype {vals.dtype}"
        )
    vals = vals.astype(np.float32)
    if vals.size and not np.isfinite(vals).all():
        raise DeltaRejected(f"bad-{name}: values must be finite")
    if nonneg and vals.size and vals.min() < 0:
        # the same rule as CSRTopo.set_edge_weight: a negative weight
        # would silently degenerate the CDF search
        raise DeltaRejected(f"bad-{name}: values must be non-negative")
    return vals


def validate_delta(
    delta: DeltaBatch,
    node_count: int,
    feature_shape: tuple[int, int] | None,
    *,
    live_pair_counts: dict[int, int] | None = None,
    duplicates: str = "error",
    needs_weights: bool = False,
    needs_times: bool = False,
) -> DeltaBatch:
    """Admission-validate ``delta``; return the normalized batch or raise
    :class:`DeltaRejected` naming the first failing check.

    ``feature_shape`` is the attached store's ``(n, feature_dim)`` (None
    = no feature store, so feature updates are inadmissible).
    ``live_pair_counts`` is the encoded-pair multiset of live edges
    (committed CSR adjusted by already-staged deltas) that delete
    existence is checked against; None skips the existence check (the
    caller owns it). ``duplicates`` is the duplicate policy: ``"error"``
    rejects duplicate edge inserts and duplicate update ids; ``"allow"``
    admits parallel edges and collapses duplicate update ids last-wins.
    ``needs_weights``/``needs_times`` mirror the committed topology's
    attributes: inserted edges must supply exactly the attributes the
    topology carries (named rejections both ways — see the module
    docstring).
    """
    if duplicates not in ("error", "allow"):
        raise ValueError(
            f"duplicates must be 'error' or 'allow', got {duplicates!r}"
        )
    n = int(node_count)
    ins = dele = ids = rows = None

    if delta.edge_inserts is not None:
        ins = _as_edge_array(delta.edge_inserts, "edge_inserts")
        _check_range(ins, n, "edge_inserts")
        if duplicates == "error" and ins.shape[1]:
            keys = encode_pairs(ins[0], ins[1], n)
            uniq, cnt = np.unique(keys, return_counts=True)
            if (cnt > 1).any():
                k = int(uniq[np.argmax(cnt)])
                raise DeltaRejected(
                    f"duplicate edge insert ({k // n}, {k % n}) in one "
                    f"batch (duplicates='error'; pass 'allow' for "
                    f"parallel edges)"
                )

    # edge attributes must mirror the committed topology exactly: a
    # weighted/timestamped CSR can never gain attribute-less rows, and an
    # attribute on an unattributed topology is a schema error, not noise
    n_ins = 0 if ins is None else int(ins.shape[1])
    wts = _admit_edge_attr(
        delta.edge_weights, n_ins, needs_weights, "edge-weights",
        nonneg=True,
    )
    tms = _admit_edge_attr(
        delta.edge_times, n_ins, needs_times, "edge-times", nonneg=False,
    )

    if delta.edge_deletes is not None:
        dele = _as_edge_array(delta.edge_deletes, "edge_deletes")
        _check_range(dele, n, "edge_deletes")

    if (delta.update_ids is None) != (delta.update_rows is None):
        raise DeltaRejected(
            "update_ids and update_rows must be passed together"
        )
    if delta.update_ids is not None:
        if feature_shape is None:
            raise DeltaRejected(
                "delta carries feature row updates but no feature store "
                "is attached to the streaming graph"
            )
        ids = np.asarray(delta.update_ids).reshape(-1)
        if not np.issubdtype(ids.dtype, np.integer):
            raise DeltaRejected(
                f"update_ids must be integers, got dtype {ids.dtype}"
            )
        ids = ids.astype(np.int64, copy=False)
        _check_range(ids, min(n, int(feature_shape[0])), "update_ids")
        rows = np.asarray(delta.update_rows)
        f = int(feature_shape[1])
        if rows.ndim != 2 or rows.shape != (ids.shape[0], f):
            raise DeltaRejected(
                f"update_rows must be ({ids.shape[0]}, {f}) to match "
                f"update_ids and the store's feature dim, got {rows.shape}"
            )
        if not np.issubdtype(rows.dtype, np.floating):
            raise DeltaRejected(
                f"update_rows must be float rows, got dtype {rows.dtype}"
            )
        if rows.size and not np.isfinite(rows).all():
            bad = int(np.argwhere(~np.isfinite(rows).all(axis=1))[0, 0])
            raise DeltaRejected(
                f"update_rows contain non-finite values (first bad row: "
                f"update index {bad}, node {int(ids[bad])}); a poisoned "
                f"row is rejected at the boundary, not cached and served"
            )
        if ids.size and np.unique(ids).shape[0] != ids.shape[0]:
            if duplicates == "error":
                raise DeltaRejected(
                    "duplicate update_ids in one batch "
                    "(duplicates='error'; pass 'allow' for last-wins)"
                )
            # last-wins collapse: keep the LAST occurrence of each id
            _, last = np.unique(ids[::-1], return_index=True)
            keep = np.sort(ids.shape[0] - 1 - last)
            ids, rows = ids[keep], rows[keep]

    # delete existence against the committed-plus-staged multiset: every
    # delete must name a live edge; over-deleting is a whole-batch reject
    if dele is not None and dele.shape[1] and live_pair_counts is not None:
        keys = encode_pairs(dele[0], dele[1], n)
        avail = dict(live_pair_counts)
        if ins is not None and ins.shape[1]:
            for k in encode_pairs(ins[0], ins[1], n).tolist():
                avail[k] = avail.get(k, 0) + 1
        for k in keys.tolist():
            have = avail.get(k, 0)
            if have <= 0:
                raise DeltaRejected(
                    f"edge delete ({k // n}, {k % n}) does not match a "
                    f"live edge (committed + staged); deletes must name "
                    f"existing edges"
                )
            avail[k] = have - 1

    return DeltaBatch(
        edge_inserts=ins, edge_deletes=dele,
        update_ids=ids, update_rows=rows,
        edge_weights=wts, edge_times=tms, tag=delta.tag,
    )
