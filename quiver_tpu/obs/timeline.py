"""Host-side per-stage step timeline with streaming percentiles.

The fused step hides the sample/gather/train split inside one XLA program,
but the *host* loop still has stages worth attributing: eager tuners, seed
packing, H2D, dispatch, readbacks, prefetch waits. :class:`StepTimeline`
times named stages (``with timeline.stage("sample", sync=out.n_id):``),
keeps streaming p50/p95/p99 per stage via the P² algorithm (O(1) memory —
a long run never stores every sample), and each stage also enters
``trace_scope(name)`` so a ``jax.profiler`` capture (see
``obs.profile_epoch``) carries the SAME stage names on the device timeline
as the host report.

``sync=`` takes any array/pytree to ``block_until_ready`` before the clock
stops — without it a stage measures dispatch latency, not work (the same
contract as ``utils.trace.Timer``, which can feed a timeline directly via
its ``registry=`` argument).
"""

from __future__ import annotations

import contextlib
import math
import time

import jax

from ..utils.trace import trace_scope

__all__ = ["P2Quantile", "StageStats", "StepTimeline"]


class P2Quantile:
    """Streaming quantile estimate (Jain & Chlamtac's P² algorithm).

    Five markers track the running quantile without storing observations;
    until five samples arrive the estimate is exact (sorted buffer).
    """

    def __init__(self, q: float):
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = q
        self._heights: list[float] = []  # marker heights (first 5: buffer)
        self._pos = [1, 2, 3, 4, 5]  # marker positions (1-based)
        self._want = [1.0, 1 + 2 * q, 1 + 4 * q, 3 + 2 * q, 5.0]
        self._dpos = [0.0, q / 2, q, (1 + q) / 2, 1.0]
        self.count = 0

    def update(self, x: float) -> None:
        self.count += 1
        h = self._heights
        if len(h) < 5:
            h.append(float(x))
            h.sort()
            return
        # locate the cell k with h[k] <= x < h[k+1]
        if x < h[0]:
            h[0] = float(x)
            k = 0
        elif x >= h[4]:
            h[4] = float(x)
            k = 3
        else:
            k = 0
            while x >= h[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            self._pos[i] += 1
        for i in range(5):
            self._want[i] += self._dpos[i]
        # adjust the three interior markers toward their desired positions
        for i in (1, 2, 3):
            d = self._want[i] - self._pos[i]
            if (d >= 1 and self._pos[i + 1] - self._pos[i] > 1) or (
                d <= -1 and self._pos[i - 1] - self._pos[i] < -1
            ):
                s = 1 if d >= 0 else -1
                cand = self._parabolic(i, s)
                if not h[i - 1] < cand < h[i + 1]:
                    cand = self._linear(i, s)
                h[i] = cand
                self._pos[i] += s

    def _parabolic(self, i: int, s: int) -> float:
        h, p = self._heights, self._pos
        return h[i] + s / (p[i + 1] - p[i - 1]) * (
            (p[i] - p[i - 1] + s) * (h[i + 1] - h[i]) / (p[i + 1] - p[i])
            + (p[i + 1] - p[i] - s) * (h[i] - h[i - 1]) / (p[i] - p[i - 1])
        )

    def _linear(self, i: int, s: int) -> float:
        h, p = self._heights, self._pos
        return h[i] + s * (h[i + s] - h[i]) / (p[i + s] - p[i])

    @property
    def value(self) -> float | None:
        h = self._heights
        if not h:
            return None
        if self.count < 5:
            # exact nearest-rank order statistic while the buffer is
            # small: ceil(q*n) 1-based (round()-based indexing returned
            # interpolated-garbage picks, e.g. p99 of {1,2} -> 1)
            idx = max(0, math.ceil(self.q * len(h)) - 1)
            return h[idx]
        return h[2]


class StageStats:
    """Aggregate for one named stage: count/total/min/max + p50/p95/p99."""

    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        self._q = {q: P2Quantile(q) for q in self.QUANTILES}

    def observe(self, seconds: float) -> None:
        s = float(seconds)
        self.count += 1
        self.total += s
        self.min = min(self.min, s)
        self.max = max(self.max, s)
        for est in self._q.values():
            est.update(s)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float | None:
        est = self._q.get(q)
        return None if est is None else est.value

    def as_dict(self) -> dict:
        return {
            "stage": self.name,
            "count": self.count,
            "total_s": self.total,
            "mean_ms": self.mean * 1e3,
            "min_ms": (0.0 if self.count == 0 else self.min * 1e3),
            "max_ms": self.max * 1e3,
            **{
                f"p{int(q * 100)}_ms": (v * 1e3 if v is not None else None)
                for q, v in ((q, self.quantile(q)) for q in self.QUANTILES)
            },
        }


class StepTimeline:
    """Named-stage wall-clock aggregation for the host training loop."""

    def __init__(self):
        self._stages: dict[str, StageStats] = {}

    @contextlib.contextmanager
    def stage(self, name: str, sync=None):
        """Time a stage; ``sync`` blocks on the given array/pytree before
        the clock stops. Also a ``trace_scope`` — under a profiler capture
        the device timeline shows the same stage name."""
        with trace_scope(name):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                if sync is not None:
                    jax.block_until_ready(sync)
                self.observe(name, time.perf_counter() - t0)

    def observe(self, name: str, seconds: float) -> None:
        """Record one duration for ``name`` (the ``Timer(registry=...)``
        feed point)."""
        stats = self._stages.get(name)
        if stats is None:
            stats = self._stages[name] = StageStats(name)
        stats.observe(seconds)

    def stats(self, name: str) -> StageStats | None:
        return self._stages.get(name)

    def overlap_efficiency(self, serial_stages, measured: str,
                           q: float = 0.5) -> float | None:
        """Derived pipeline-attribution metric: the sum of the SERIAL
        stage quantiles divided by the quantile of the overlapped
        (measured) step stage — i.e. how much latency the schedule hides.
        1.0 = no overlap (the pipelined step costs the full stage sum);
        values above 1.0 mean sample/gather time is running under
        compute; the upper bound is stage-sum / max-stage (a perfectly
        hidden pipeline is bounded by its slowest stage).

        ``serial_stages``: stage names timed by a serial estimator (e.g.
        ``("sample", "gather", "train_step")``); ``measured``: the stage
        holding per-step times of the overlapped schedule. Returns None
        when any stage is missing or untimed — a partial sum would
        silently understate the baseline.
        """
        total = 0.0
        for name in serial_stages:
            st = self._stages.get(name)
            v = None if st is None else st.quantile(q)
            if v is None:
                return None
            total += v
        st = self._stages.get(measured)
        v = None if st is None else st.quantile(q)
        if not v:
            return None
        return total / v

    def summary(self) -> dict[str, StageStats]:
        return dict(self._stages)

    def clear(self) -> None:
        self._stages.clear()

    def report(self) -> str:
        """Fixed-width per-stage table (count, mean, p50/p95/p99, max)."""
        if not self._stages:
            return "(no stages timed)"
        hdr = (f"{'stage':<16} {'count':>6} {'mean ms':>9} {'p50 ms':>9} "
               f"{'p95 ms':>9} {'p99 ms':>9} {'max ms':>9}")
        lines = [hdr, "-" * len(hdr)]
        for st in self._stages.values():
            d = st.as_dict()

            def ms(v):
                return "-" if v is None else f"{v:9.2f}"

            lines.append(
                f"{st.name:<16} {st.count:>6d} {d['mean_ms']:9.2f} "
                f"{ms(d['p50_ms'])} {ms(d['p95_ms'])} {ms(d['p99_ms'])} "
                f"{d['max_ms']:9.2f}"
            )
        return "\n".join(lines)
