"""Structured export of :class:`MetricSnapshot` streams.

Two formats, both round-trippable (tests/test_obs.py proves
``parse(emit(snaps))`` reproduces the values, including epoch_scan-shaped
``(steps, k)`` metrics):

* **JSON lines** — one self-describing object per snapshot
  (``{"name", "kind", "steps", "shape", "dtype", "value", ...}``) for
  long-run artifacts (``metrics.jsonl``) and offline analysis;
* **Prometheus-style text exposition** — ``# HELP``/``# TYPE`` plus one
  sample per element (vector metrics carry an ``idx="i,j"`` label) for
  scraping live runs. A ``# QUIVER`` metadata comment per metric (ignored
  by scrapers — ``#`` lines that are not HELP/TYPE are comments) carries
  the original dotted name, dtype, steps and shape so the exposition
  parses back losslessly.
"""

from __future__ import annotations

import io
import json
import re

import numpy as np

from .registry import MetricSnapshot

__all__ = [
    "snapshot_to_dict",
    "snapshot_from_dict",
    "write_jsonl",
    "read_jsonl",
    "to_prometheus",
    "from_prometheus",
    "prometheus_name",
    "escape_label_value",
]


# -- JSON lines ---------------------------------------------------------------

def snapshot_to_dict(snap: MetricSnapshot) -> dict:
    arr = snap.numpy
    return {
        "name": snap.name,
        "kind": snap.kind,
        "steps": snap.steps,
        "shape": list(arr.shape),
        "dtype": arr.dtype.name,
        "value": arr.tolist(),
        "unit": snap.unit,
        "doc": snap.doc,
    }


def snapshot_from_dict(d: dict) -> MetricSnapshot:
    arr = np.asarray(d["value"], dtype=np.dtype(d["dtype"]))
    arr = arr.reshape(tuple(d["shape"]))
    return MetricSnapshot(
        d["name"], d["kind"], arr, d.get("steps"),
        d.get("unit", ""), d.get("doc", ""),
    )


def write_jsonl(snapshots, path_or_file, extra: dict | None = None) -> int:
    """Append one JSON line per snapshot; ``extra`` fields (run identity —
    job key, platform, timestamp) are merged into every line. Returns the
    number of lines written."""
    rows = []
    for snap in snapshots:
        d = snapshot_to_dict(snap)
        if extra:
            d.update(extra)
        rows.append(json.dumps(d))
    if not rows:
        return 0
    if hasattr(path_or_file, "write"):
        path_or_file.write("\n".join(rows) + "\n")
    else:
        with open(path_or_file, "a", encoding="utf-8") as fh:
            fh.write("\n".join(rows) + "\n")
    return len(rows)


def read_jsonl(path_or_text) -> list[MetricSnapshot]:
    """Parse a metrics.jsonl file (path, file object, or text) back into
    snapshots; non-metric lines are skipped."""
    if hasattr(path_or_text, "read"):
        text = path_or_text.read()
    elif "\n" in path_or_text or path_or_text.lstrip().startswith("{"):
        text = path_or_text
    else:
        with open(path_or_text, encoding="utf-8") as fh:
            text = fh.read()
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and {"name", "kind", "value"} <= d.keys():
            out.append(snapshot_from_dict(d))
    return out


# -- Prometheus-style exposition ----------------------------------------------

def prometheus_name(name: str) -> str:
    """Dotted metric name -> a legal exposition metric name."""
    return "quiver_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def escape_label_value(value: str) -> str:
    """Escape a label value per the exposition format: backslash, double
    quote, and newline must be escaped or a hostile name breaks the line
    out of its sample (label injection)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(value: str) -> str:
    # HELP text: backslash and newline escape; quotes are legal verbatim
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def to_prometheus(snapshots) -> str:
    """Text exposition of the snapshots (one sample per array element).

    Hygiene: dotted/hostile metric names sanitize via
    :func:`prometheus_name` (distinct names that sanitize to the same
    exposition name get a ``_2``/``_3`` suffix instead of silently
    merging); every metric emits ``# HELP`` (escaped) and ``# TYPE``;
    the original name rides both as an escaped ``name=""`` label on each
    sample and in the ``# QUIVER`` JSON metadata comment, which is what
    makes :func:`from_prometheus` a lossless inverse even for names
    containing ``\\``, ``"`` or newlines."""
    out = io.StringIO()
    assigned: dict[str, str] = {}  # dotted name -> exposition name
    for snap in snapshots:
        arr = snap.numpy
        pname = assigned.get(snap.name)
        if pname is None:
            base = prometheus_name(snap.name)
            pname, n = base, 1
            taken = set(assigned.values())
            while pname in taken:
                n += 1
                pname = f"{base}_{n}"
            assigned[snap.name] = pname
        meta = {
            "pname": pname,
            "name": snap.name,
            "kind": snap.kind,
            "dtype": arr.dtype.name,
            "steps": snap.steps,
            "shape": list(arr.shape),
            "unit": snap.unit,
            "doc": snap.doc,
        }
        out.write(f"# QUIVER {json.dumps(meta, sort_keys=True)}\n")
        out.write(f"# HELP {pname} {_escape_help(snap.doc)}\n")
        out.write(f"# TYPE {pname} {snap.kind}\n")
        name_lbl = escape_label_value(snap.name)
        if arr.ndim == 0:
            out.write(f'{pname}{{name="{name_lbl}"}} {_fmt(arr[()])}\n')
        else:
            for idx in np.ndindex(arr.shape):
                lbl = ",".join(str(i) for i in idx)
                out.write(
                    f'{pname}{{name="{name_lbl}",idx="{lbl}"}} '
                    f"{_fmt(arr[idx])}\n"
                )
    return out.getvalue()


def _fmt(v) -> str:
    if np.issubdtype(np.asarray(v).dtype, np.integer):
        return str(int(v))
    return repr(float(v))


_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?\s+(?P<val>\S+)$'
)
# idx label anchored at the END of the label block — a hostile name label
# (escaped, quoted, emitted first) cannot spoof it
_IDX = re.compile(r'(?:^|,)idx="(?P<idx>[0-9,]*)"$')
# legacy space-separated metadata comment (pre-hygiene expositions)
_META = re.compile(
    r"^# QUIVER (?P<pname>\S+) name=(?P<name>\S+) kind=(?P<kind>\S+) "
    r"dtype=(?P<dtype>\S+) steps=(?P<steps>\S+) shape=(?P<shape>\S+)$"
)


def _parse_meta(line: str) -> dict | None:
    body = line[len("# QUIVER "):]
    if body.startswith("{"):
        try:
            d = json.loads(body)
        except ValueError:
            return None
        if isinstance(d, dict) and "pname" in d:
            d["shape"] = tuple(d.get("shape") or ())
            return d
        return None
    m = _META.match(line)
    if not m:
        return None
    d = m.groupdict()
    d["steps"] = None if d["steps"] == "None" else int(d["steps"])
    d["shape"] = (
        () if d["shape"] == "-"
        else tuple(int(s) for s in d["shape"].split(","))
    )
    return d


def from_prometheus(text: str) -> list[MetricSnapshot]:
    """Parse an exposition produced by :func:`to_prometheus` back into
    snapshots (the ``# QUIVER`` metadata lines make the round trip
    lossless — original name, dtype, steps axis, shape, unit and doc are
    all recovered, hostile names included). Legacy (pre-hygiene)
    expositions parse too."""
    meta: dict[str, dict] = {}
    samples: dict[str, dict[tuple, str]] = {}
    order: list[str] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# QUIVER "):
            d = _parse_meta(line)
            if d is not None:
                meta[d["pname"]] = d
                if d["pname"] not in order:
                    order.append(d["pname"])
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        pname = m.group("name")
        labels = m.group("labels")
        idx = None
        if labels is not None:
            mi = _IDX.search(labels)
            if mi is not None:
                idx = mi.group("idx")
        key = () if idx is None else tuple(
            int(i) for i in idx.split(",") if i != ""
        )
        samples.setdefault(pname, {})[key] = m.group("val")
        if pname not in order:
            order.append(pname)
    out = []
    for pname in order:
        vals = samples.get(pname, {})
        md = meta.get(pname)
        if md is None or not vals:
            continue
        dtype = np.dtype(md["dtype"])
        shape = tuple(md["shape"])
        arr = np.zeros(shape, dtype)
        for key, raw in vals.items():
            v = int(raw) if np.issubdtype(dtype, np.integer) else float(raw)
            arr[key] = v
        out.append(
            MetricSnapshot(
                md["name"], md["kind"], arr, md["steps"],
                md.get("unit", ""), md.get("doc", ""),
            )
        )
    return out
