"""grafttrace — end-to-end causal span tracing across train + serve.

graftscope's counters and P² stage quantiles answer "how slow is stage X
on average"; they cannot answer "what happened to THIS request/step and
why was it slow" — the aggregates have no causal chain. :class:`Tracer`
adds that chain as pure HOST-side bookkeeping riding the seams the
subsystems already expose:

* the serving path opens one trace per admitted request and attributes
  its six batch stages (``queue_wait``/``pad``/``sample``/``gather``/
  ``forward``/``readback``) as child spans of that trace — propagated
  across :class:`~quiver_tpu.serving.fleet.ServingFleet` routing, so a
  failover request shows BOTH replicas under one trace id;
* the trainer opens one deterministic trace per epoch
  (``train.epoch.<n>``) so a preempt/resume run naturally stitches its
  chunk spans across the restart;
* host actors (Prefetcher, AsyncStager, EmbeddingRefresher,
  Checkpointer, CacheController) tag their work with the trace/step that
  caused it.

Discipline (the ``collect_metrics=False`` contract, applied to tracing):
spans are wall-clock observations taken OUTSIDE every traced program —
a disabled tracer performs no work beyond one attribute check and
returns a shared no-op span, and enabling it cannot change a single
program's inputs, so losses, params, and serve responses are bitwise
identical either way (proven by differential test).

Export is Chrome trace-event JSON (:func:`to_chrome_trace`), loadable in
Perfetto / ``chrome://tracing`` — every span becomes a complete
``"ph": "X"`` event carrying its trace/span/parent ids in ``args``.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

from .registry import TRACE_SPANS, MetricsRegistry

__all__ = ["Span", "Tracer", "to_chrome_trace", "write_chrome_trace"]


class Span:
    """One finished unit of attributed work.

    Fields: ``name`` (dotted stage name), ``trace_id`` (the causal chain
    this span belongs to), ``span_id`` / ``parent_id`` (tracer-unique;
    parent ``""`` = a root span), ``t0`` / ``dur`` (seconds on the
    tracer's monotonic clock; ``t0`` is relative to the tracer's epoch so
    exports start near zero), ``tid`` (small stable per-thread id), and
    free-form ``attrs`` (``subsystem`` is the conventional grouping key:
    serve / fleet / trainer / prefetch / stager / resilience / control).
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "dur",
                 "tid", "attrs")

    def __init__(self, name, trace_id, span_id, parent_id, t0, dur, tid,
                 attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.dur = dur
        self.tid = tid
        self.attrs = attrs

    def set(self, key: str, value) -> None:
        """Attach/overwrite one attribute (live spans: inside the
        ``with tracer.span(...)`` block; the no-op span accepts and
        drops it)."""
        self.attrs[key] = value

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t0_s": self.t0,
            "dur_s": self.dur,
            "tid": self.tid,
            "attrs": dict(self.attrs),
        }

    def __repr__(self) -> str:
        return (f"Span({self.name!r}, trace={self.trace_id!r}, "
                f"dur={self.dur * 1e3:.3f}ms)")


class _NullSpan:
    """The shared no-op span a disabled tracer hands out: accepts the
    full :class:`Span` surface, allocates nothing, records nothing."""

    __slots__ = ()
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = ""
    t0 = 0.0
    dur = 0.0
    tid = 0
    attrs: dict = {}

    def set(self, key, value) -> None:
        pass

    def as_dict(self) -> dict:
        return {}


_NULL_SPAN = _NullSpan()


class _NullScope:
    """Reusable disabled-path context manager — ``tracer.span(...)`` with
    ``enabled=False`` returns this singleton: zero allocation, zero
    clock reads."""

    __slots__ = ()

    def __enter__(self):
        return _NULL_SPAN

    def __exit__(self, *exc):
        return False


_NULL_SCOPE = _NullScope()


class _SpanScope:
    """Live-path context manager: clocks the block and records one span
    on exit (even when the block raises — a failing stage still lands on
    the timeline, tagged by the caller if it wants to)."""

    __slots__ = ("_tracer", "_span", "_t0")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._t0 = time.perf_counter()
        return self._span

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        s = self._span
        s.t0 = self._t0 - self._tracer._epoch
        s.dur = t1 - self._t0
        if exc_type is not None:
            s.attrs["error"] = exc_type.__name__
        self._tracer._record(s)
        return False


class Tracer:
    """Issues :class:`Span` s and keeps the last ``max_spans`` of them.

    Args:
      enabled: the zero-overhead switch — ``False`` makes every call a
        cheap no-op returning shared null objects (the
        ``collect_metrics=False`` discipline; bitwise-identical results
        are structural, not best-effort).
      max_spans: bounded ring of finished spans (oldest evicted).
      metrics: optional graftscope :class:`MetricsRegistry` to land the
        lifetime ``trace.spans`` counter on.

    Ids are deterministic per tracer: trace ids count up (``t1``,
    ``t2``, ...) unless the caller supplies an explicit one
    (:meth:`trace` with a name — how the trainer pins
    ``train.epoch.<n>`` so resume stitches); span ids count up (``s1``,
    ``s2``, ...). All methods are thread-safe — host actors record from
    their worker threads.
    """

    def __init__(self, enabled: bool = True, max_spans: int = 4096,
                 metrics: MetricsRegistry | None = None):
        if max_spans < 1:
            raise ValueError(f"max_spans must be >= 1, got {max_spans}")
        self.enabled = bool(enabled)
        self.max_spans = int(max_spans)
        self.metrics = metrics
        if metrics is not None:
            metrics.counter(
                TRACE_SPANS, unit="spans",
                doc="finished trace spans recorded by the grafttrace "
                    "tracer (lifetime total; bounded ring keeps the "
                    "last max_spans of them)",
            )
        self._epoch = time.perf_counter()
        self._spans: list[Span] = []
        self._lock = threading.Lock()
        self._next_trace = 0
        self._next_span = 0
        self._tids: dict[int, int] = {}
        self.spans_total = 0

    # -- ids -----------------------------------------------------------------

    def trace(self, name: str | None = None) -> str:
        """A trace id: the explicit ``name`` when given (deterministic
        stitching — e.g. ``train.epoch.3`` survives a restart), else the
        next counter id. ``""`` when disabled."""
        if not self.enabled:
            return ""
        if name is not None:
            return str(name)
        with self._lock:
            self._next_trace += 1
            return f"t{self._next_trace}"

    def _span_id(self) -> str:
        self._next_span += 1
        return f"s{self._next_span}"

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = self._tids[ident] = len(self._tids) + 1
        return tid

    # -- recording -----------------------------------------------------------

    def _record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self.max_spans:
                del self._spans[: len(self._spans) - self.max_spans]
            self.spans_total += 1
            total = self.spans_total
        if self.metrics is not None:
            self.metrics.set(TRACE_SPANS, np.int32(total))

    def _make(self, name, trace, parent, subsystem, attrs) -> Span:
        a = dict(attrs) if attrs else {}
        if subsystem is not None:
            a["subsystem"] = subsystem
        parent_id = parent.span_id if isinstance(parent, Span) else (
            parent or ""
        )
        with self._lock:
            sid = self._span_id()
            tid = self._tid()
        return Span(str(name), trace or "", sid, parent_id, 0.0, 0.0,
                    tid, a)

    def span(self, name: str, trace: str | None = None, parent=None,
             subsystem: str | None = None, **attrs):
        """Context manager timing one unit of work; yields the live
        :class:`Span` (callers may ``.set()`` attrs inside the block).
        ``parent`` is a parent :class:`Span` or span-id string."""
        if not self.enabled:
            return _NULL_SCOPE
        return _SpanScope(self, self._make(name, trace, parent,
                                           subsystem, attrs))

    def record(self, name: str, t0: float, dur: float,
               trace: str | None = None, parent=None,
               subsystem: str | None = None, **attrs) -> Span | None:
        """Record an already-measured span: ``t0`` on the tracer's
        relative clock (see :meth:`now`), ``dur`` in seconds. Returns the
        span (None when disabled) so callers can parent children on it."""
        if not self.enabled:
            return None
        s = self._make(name, trace, parent, subsystem, attrs)
        s.t0 = float(t0)
        s.dur = float(dur)
        self._record(s)
        return s

    def observe(self, name: str, seconds: float, trace: str | None = None,
                parent=None, subsystem: str | None = None,
                **attrs) -> Span | None:
        """Record a span of duration ``seconds`` ending NOW — for work
        whose start the caller measured on another clock (queue waits,
        externally-timed stages)."""
        if not self.enabled:
            return None
        dur = max(float(seconds), 0.0)
        return self.record(name, self.now() - dur, dur, trace=trace,
                           parent=parent, subsystem=subsystem, **attrs)

    def event(self, name: str, trace: str | None = None, parent=None,
              subsystem: str | None = None, **attrs) -> Span | None:
        """A zero-duration marker span (enqueue, failover, decision)."""
        if not self.enabled:
            return None
        return self.record(name, self.now(), 0.0, trace=trace,
                           parent=parent, subsystem=subsystem, **attrs)

    def now(self) -> float:
        """Seconds on the tracer's relative monotonic clock (0 at
        construction) — the ``t0`` base for :meth:`record`."""
        return time.perf_counter() - self._epoch

    # -- inspection / export -------------------------------------------------

    def spans(self) -> list[Span]:
        """Snapshot of the retained spans, oldest first."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def subsystems(self) -> set[str]:
        """Distinct ``subsystem`` attrs across retained spans."""
        return {s.attrs["subsystem"] for s in self.spans()
                if "subsystem" in s.attrs}

    def to_chrome(self) -> dict:
        return to_chrome_trace(self.spans())

    def write_chrome(self, path) -> int:
        return write_chrome_trace(self.spans(), path)


# -- Chrome trace-event / Perfetto export -------------------------------------

def to_chrome_trace(spans) -> dict:
    """Chrome trace-event JSON for ``spans`` — one complete (``"X"``)
    event per span, timestamps/durations in microseconds, trace/span/
    parent ids and attrs in ``args``. Loads directly in Perfetto and
    ``chrome://tracing``."""
    events = []
    for s in spans:
        events.append({
            "name": s.name,
            "cat": s.attrs.get("subsystem", "quiver"),
            "ph": "X",
            "ts": round(s.t0 * 1e6, 3),
            "dur": round(s.dur * 1e6, 3),
            "pid": 1,
            "tid": s.tid,
            "args": {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                **{k: _jsonable(v) for k, v in s.attrs.items()},
            },
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def write_chrome_trace(spans, path) -> int:
    """Write the Chrome trace-event JSON for ``spans`` to ``path``;
    returns the event count."""
    doc = to_chrome_trace(spans)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return len(doc["traceEvents"])
