"""Live telemetry endpoint: a stdlib ``http.server`` thread exposing
graftscope metrics, grafttrace spans, and a health summary.

Three routes, all read-only snapshots of host-side state:

* ``/metrics`` — Prometheus exposition text
  (:func:`~quiver_tpu.obs.export.to_prometheus` over the attached
  registry's snapshots);
* ``/traces`` — the tracer's retained spans as Chrome trace-event JSON
  (save the body to a file, open in Perfetto);
* ``/healthz`` — JSON summary from the owner's ``health`` callable
  (breaker states, queue depth, bound versions) merged over
  ``{"status": "ok"}``.

Off by default everywhere: trainers and fleets construct NOTHING here
unless ``serve_telemetry()`` is called, and the server thread is a
daemon bound to ``127.0.0.1`` on an ephemeral port — observability must
never hold a process alive or accept off-host traffic by accident. The
handlers read the same locked snapshots tests read, so serving telemetry
cannot perturb a traced program (the ``collect_metrics=False``
discipline, applied to the wire).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .export import to_prometheus
from .registry import MetricsRegistry
from .tracing import to_chrome_trace

__all__ = ["TelemetryEndpoint"]


class TelemetryEndpoint:
    """Background HTTP server over a (metrics, tracer, health) triple.

    Args:
      metrics: optional :class:`MetricsRegistry` backing ``/metrics``
        (absent → empty exposition body).
      tracer: optional :class:`~quiver_tpu.obs.tracing.Tracer` backing
        ``/traces`` (absent → empty ``traceEvents``).
      health: optional zero-arg callable returning a JSON-able dict
        merged into the ``/healthz`` body.
      host / port: bind address; ``port=0`` (default) picks an ephemeral
        port, read it back from :attr:`port` / :attr:`url` after
        :meth:`start`.

    Usable as a context manager (``with TelemetryEndpoint(...) as ep:``)
    — stops the server thread on exit.
    """

    def __init__(self, metrics: MetricsRegistry | None = None, tracer=None,
                 health=None, host: str = "127.0.0.1", port: int = 0):
        self.metrics = metrics
        self.tracer = tracer
        self.health = health
        self._host = host
        self._port = int(port)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TelemetryEndpoint":
        """Bind and serve on a daemon thread; idempotent."""
        if self._httpd is not None:
            return self
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self._host, self._port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="quiver-telemetry",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Shut the server down and join its thread; idempotent."""
        httpd, thread = self._httpd, self._thread
        self._httpd = None
        self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> "TelemetryEndpoint":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def running(self) -> bool:
        return self._httpd is not None

    @property
    def port(self) -> int:
        """The bound port (the ephemeral one when constructed with 0)."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    # -- route bodies --------------------------------------------------------

    def metrics_text(self) -> str:
        if self.metrics is None:
            return ""
        return to_prometheus(self.metrics.snapshots())

    def traces_json(self) -> dict:
        spans = self.tracer.spans() if self.tracer is not None else []
        return to_chrome_trace(spans)

    def healthz_json(self) -> dict:
        body = {"status": "ok"}
        if self.health is not None:
            body.update(self.health())
        return body


def _make_handler(endpoint: TelemetryEndpoint):
    """Handler class closed over ``endpoint`` — BaseHTTPRequestHandler's
    API forces per-class (not per-instance) configuration."""

    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):  # noqa: N802 (http.server API name)
            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    body = endpoint.metrics_text().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/traces":
                    body = json.dumps(endpoint.traces_json()).encode("utf-8")
                    ctype = "application/json"
                elif path == "/healthz":
                    body = json.dumps(endpoint.healthz_json()).encode("utf-8")
                    ctype = "application/json"
                else:
                    self._reply(404, "application/json",
                                b'{"error": "not found"}')
                    return
            except Exception as e:  # surface, don't kill the thread
                msg = json.dumps({"error": f"{type(e).__name__}: {e}"})
                self._reply(500, "application/json", msg.encode("utf-8"))
                return
            self._reply(200, ctype, body)

        def _reply(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet: telemetry, not access logs
            pass

    return _Handler
