"""graftscope — unified in-program metrics, step timeline, and export.

The observability layer the three hand-threaded ``last_*`` telemetry
streams grew into (SURVEY §0/§5: sampling throughput and cache hit rates
are the signals that drove the reference's design). One discipline, four
pieces:

* :class:`MetricsRegistry` / :class:`MetricsTape` — named counters/gauges
  that ride a single metrics pytree through ``shard_map``/``lax.scan``/
  cond-gated fallbacks, psum'd once per step, landing as typed
  :class:`MetricSnapshot` objects (``registry.py``);
* :class:`StepTimeline` — host-side per-stage wall clock with streaming
  p50/p95/p99 (``timeline.py``);
* JSONL + Prometheus-style exporters, both parse-back round-trippable
  (``export.py``);
* :func:`profile_epoch` — ``jax.profiler`` capture bracketing with the
  same stage names on the device timeline (``profile.py``).

``DistributedTrainer.metrics_report()`` is the one-call summary over all
of it.

grafttrace extends the layer with causal chains and crash forensics:

* :class:`Tracer` / :class:`Span` — per-request/per-step causal spans
  riding the serve, fleet, trainer, host-actor, and control seams,
  exported as Chrome trace-event JSON (``tracing.py``);
* :class:`FlightRecorder` — bounded black-box ring dumping atomic,
  integrity-checksummed postmortem bundles on fault triggers
  (``recorder.py``);
* :class:`TelemetryEndpoint` — opt-in stdlib HTTP thread serving
  ``/metrics``, ``/traces``, ``/healthz`` (``endpoint.py``).
"""

from .endpoint import TelemetryEndpoint
from .export import (
    from_prometheus,
    prometheus_name,
    read_jsonl,
    snapshot_from_dict,
    snapshot_to_dict,
    to_prometheus,
    write_jsonl,
)
from .profile import profile_epoch
from .recorder import (
    FlightRecorder,
    TornBundle,
    list_bundles,
    verify_bundle,
)
from .registry import (
    GUARD_NONFINITE,
    GUARD_SKIPPED,
    RECORDER_BUNDLES,
    RECORDER_EVENTS,
    ROUTED_OVERFLOW,
    SAMPLE_OVERFLOW,
    TIER_HITS,
    TRACE_SPANS,
    MetricSnapshot,
    MetricSpec,
    MetricsRegistry,
    MetricsTape,
)
from .timeline import P2Quantile, StageStats, StepTimeline
from .tracing import Span, Tracer, to_chrome_trace, write_chrome_trace

__all__ = [
    "MetricSpec",
    "MetricSnapshot",
    "MetricsRegistry",
    "MetricsTape",
    "ROUTED_OVERFLOW",
    "TIER_HITS",
    "SAMPLE_OVERFLOW",
    "GUARD_SKIPPED",
    "GUARD_NONFINITE",
    "P2Quantile",
    "StageStats",
    "StepTimeline",
    "snapshot_to_dict",
    "snapshot_from_dict",
    "write_jsonl",
    "read_jsonl",
    "to_prometheus",
    "from_prometheus",
    "prometheus_name",
    "profile_epoch",
    "Span",
    "Tracer",
    "TRACE_SPANS",
    "RECORDER_BUNDLES",
    "RECORDER_EVENTS",
    "to_chrome_trace",
    "write_chrome_trace",
    "FlightRecorder",
    "TornBundle",
    "verify_bundle",
    "list_bundles",
    "TelemetryEndpoint",
]
