"""Black-box flight recorder: bounded telemetry ring + atomic postmortem
bundles.

When a nonfinite guard trips, a circuit breaker opens, a streaming commit
aborts, or a serving queue sheds a burst, the telemetry that explains the
failure is exactly what the live process is about to overwrite or lose.
:class:`FlightRecorder` keeps a bounded ring of recent decision/audit
events, and on a trigger dumps a self-describing POSTMORTEM BUNDLE: the
tracer's recent spans (Chrome trace-event JSON — open it in Perfetto),
every attached registry's metric snapshots, the event ring, and a
manifest naming the trigger reason and the faulting stage.

Bundles follow the PR 7 checkpoint durability discipline
(``utils/checkpoint.py`` / ``resilience/integrity.py``): every file is
written into a temp directory and fsynced, a per-file CRC32 manifest is
written next, the ``COMMIT`` marker is the LAST write, and one
``os.replace`` publishes the directory — a crash mid-dump leaves only an
invisible temp dir, never a half-readable bundle. :func:`verify_bundle`
re-derives every checksum; :func:`list_bundles` quarantine-renames any
torn directory it finds (the same "a corrupt bundle does not exist"
stance the checkpoint restore path takes).

``trigger(..., inject_failure=)`` is the chaos seam (the streaming
``commit(inject_failure=)`` idiom): ``"crash"`` dies before the COMMIT
marker (leaving the invisible temp), ``"torn"`` publishes a bundle with
a corrupted payload and no marker — what a kernel crash that lost
unflushed pages would leave — so the quarantine path is drillable.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import zlib

import numpy as np

from ..resilience.integrity import COMMIT_NAME, quarantine_name
from .export import snapshot_to_dict
from .registry import RECORDER_BUNDLES, RECORDER_EVENTS, MetricsRegistry
from .tracing import to_chrome_trace

__all__ = [
    "BUNDLE_FORMAT",
    "BUNDLE_MANIFEST",
    "FlightRecorder",
    "TornBundle",
    "list_bundles",
    "verify_bundle",
]

BUNDLE_FORMAT = "quiver-postmortem-v1"
BUNDLE_MANIFEST = "manifest.json"
_BUNDLE_PREFIX = "postmortem-"
_INJECT_MODES = ("crash", "torn")


class TornBundle(RuntimeError):
    """A postmortem bundle failed integrity verification (missing COMMIT
    marker, unreadable/foreign manifest, or a payload checksum mismatch).
    Treated like :class:`~quiver_tpu.resilience.integrity
    .CorruptCheckpoint`: quarantine and ignore."""


class FlightRecorder:
    """Bounded black-box ring + triggered atomic postmortem dumps.

    Args:
      directory: bundle root (created if missing).
      capacity: event-ring bound (oldest :meth:`note` records evicted).
      keep: committed-bundle retention window (oldest pruned after a
        successful dump; the newest ``keep`` survive).
      tracer: optional :class:`~quiver_tpu.obs.tracing.Tracer` whose
        retained spans are dumped into every bundle (``spans.json``,
        Chrome trace-event format).
      metrics: optional :class:`MetricsRegistry` to land the recorder's
        own counters on (``recorder.bundles`` / ``recorder.events``);
        it is also snapshotted into bundles like any attached registry.

    Wire one recorder through a stack (trainer + server + streaming
    graph + breaker) and every fault class dumps into one directory with
    the shared tracer/metric context attached.
    """

    def __init__(self, directory, capacity: int = 512, keep: int = 4,
                 tracer=None, metrics: MetricsRegistry | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = os.path.abspath(os.fspath(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.capacity = int(capacity)
        self.keep = int(keep)
        self.tracer = tracer
        self.metrics = metrics
        self._registries: list[MetricsRegistry] = []
        if metrics is not None:
            metrics.counter(
                RECORDER_BUNDLES, unit="bundles",
                doc="postmortem bundles published by the flight recorder "
                    "(trigger events + explicit dumps)",
            )
            metrics.counter(
                RECORDER_EVENTS, unit="events",
                doc="decision/audit events noted into the flight "
                    "recorder's bounded ring (lifetime total)",
            )
            self._registries.append(metrics)
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.events_total = 0
        self.bundles_total = 0
        self._seq = self._next_seq()

    def _next_seq(self) -> int:
        seq = 0
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 1
        for name in names:
            if not name.startswith(_BUNDLE_PREFIX):
                continue
            parts = name[len(_BUNDLE_PREFIX):].split("-", 1)
            try:
                seq = max(seq, int(parts[0]))
            except ValueError:
                continue
        return seq + 1

    # -- ring ----------------------------------------------------------------

    def attach_registry(self, registry: MetricsRegistry) -> "FlightRecorder":
        """Snapshot this registry into every future bundle (idempotent)."""
        if registry is not None and all(r is not registry
                                        for r in self._registries):
            self._registries.append(registry)
        return self

    def note(self, kind: str, **attrs) -> None:
        """Append one decision/audit record to the bounded ring — cheap
        host bookkeeping; only a trigger persists anything."""
        with self._lock:
            self.events_total += 1
            self._events.append({
                "seq": self.events_total,
                "kind": str(kind),
                "t": time.time(),
                **{k: _jsonable(v) for k, v in attrs.items()},
            })
            total = self.events_total
        if self.metrics is not None:
            self.metrics.set(RECORDER_EVENTS, np.int32(total))

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    # -- dumping -------------------------------------------------------------

    def trigger(self, reason: str, stage: str | None = None,
                inject_failure: str | None = None, **attrs) -> str:
        """Dump one postmortem bundle for ``reason`` (the fault class)
        with ``stage`` naming the faulting stage; returns the committed
        bundle path. ``inject_failure`` is the chaos seam — ``"crash"``
        raises before the COMMIT marker (invisible temp left behind),
        ``"torn"`` publishes a corrupt, marker-less bundle."""
        if inject_failure is not None and inject_failure not in _INJECT_MODES:
            raise ValueError(
                f"inject_failure must be one of {_INJECT_MODES}, "
                f"got {inject_failure!r}"
            )
        with self._lock:
            seq = self._seq
            self._seq += 1
        name = f"{_BUNDLE_PREFIX}{seq:06d}-{_slug(reason)}"
        final = os.path.join(self.directory, name)
        tmp_dir = os.path.join(self.directory, f".tmp-{name}")
        os.makedirs(tmp_dir)
        spans = self.tracer.spans() if self.tracer is not None else []
        snaps = []
        for reg in self._registries:
            snaps.extend(snapshot_to_dict(s) for s in reg.snapshots())
        payload = {
            "spans.json": _encode(to_chrome_trace(spans)),
            "metrics.json": _encode(snaps),
            "events.json": _encode(self.events()),
        }
        files = {}
        for fname, data in payload.items():
            _write_file(os.path.join(tmp_dir, fname), data)
            files[fname] = {
                "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                "nbytes": len(data),
            }
        manifest = {
            "format": BUNDLE_FORMAT,
            "seq": seq,
            "reason": str(reason),
            "stage": stage,
            "attrs": {k: _jsonable(v) for k, v in attrs.items()},
            "written_at": time.time(),
            "spans": len(spans),
            "events": len(self._events),
            "files": files,
        }
        _write_file(os.path.join(tmp_dir, BUNDLE_MANIFEST),
                    _encode(manifest))
        if inject_failure == "crash":
            # the kill-mid-dump drill: die with the temp dir on disk —
            # no COMMIT, no publish; list_bundles never sees it
            raise RuntimeError(
                f"injected recorder crash before COMMIT (temp left at "
                f"{tmp_dir})"
            )
        if inject_failure == "torn":
            # simulate lost unflushed pages surfacing at the final name:
            # truncate a payload and publish WITHOUT the marker
            with open(os.path.join(tmp_dir, "spans.json"), "w") as fh:
                fh.write('{"traceEvents": [tor')
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp_dir, final)
            return final
        _write_file(os.path.join(tmp_dir, COMMIT_NAME), b"COMMIT\n")
        os.replace(tmp_dir, final)
        with self._lock:
            self.bundles_total += 1
            total = self.bundles_total
        if self.metrics is not None:
            self.metrics.set(RECORDER_BUNDLES, np.int32(total))
        self._prune()
        return final

    def dump(self, stage: str | None = None,
             inject_failure: str | None = None, **attrs) -> str:
        """Explicit (non-fault) postmortem dump."""
        return self.trigger("manual", stage=stage,
                            inject_failure=inject_failure, **attrs)

    def _prune(self) -> None:
        bundles = list_bundles(self.directory, quarantine=False)
        for path, _manifest in bundles[: max(len(bundles) - self.keep, 0)]:
            for fname in os.listdir(path):
                try:
                    os.unlink(os.path.join(path, fname))
                except OSError:
                    pass
            try:
                os.rmdir(path)
            except OSError:
                pass

    def bundles(self) -> list[tuple[str, dict]]:
        """Committed, integrity-verified bundles (oldest first); torn
        directories are quarantined as a side effect."""
        return list_bundles(self.directory, quarantine=True)


# -- verification -------------------------------------------------------------

def verify_bundle(path: str) -> dict:
    """Full integrity check of one bundle directory: COMMIT marker,
    manifest parse + format, every payload file's size and CRC32.
    Returns the manifest; raises :class:`TornBundle` naming the first
    failing check."""
    if not os.path.isdir(path):
        raise TornBundle(f"{path}: not a bundle directory")
    if not os.path.exists(os.path.join(path, COMMIT_NAME)):
        raise TornBundle(f"{path}: no COMMIT marker (torn/partial dump)")
    mpath = os.path.join(path, BUNDLE_MANIFEST)
    try:
        with open(mpath, encoding="utf-8") as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        raise TornBundle(
            f"{path}: unreadable manifest ({type(e).__name__}: {e})"
        ) from None
    if manifest.get("format") != BUNDLE_FORMAT:
        raise TornBundle(
            f"{path}: unknown bundle format {manifest.get('format')!r} "
            f"(expected {BUNDLE_FORMAT!r})"
        )
    for fname, rec in manifest.get("files", {}).items():
        fpath = os.path.join(path, fname)
        try:
            with open(fpath, "rb") as fh:
                data = fh.read()
        except OSError as e:
            raise TornBundle(f"{path}: unreadable {fname} ({e})") from None
        if len(data) != int(rec["nbytes"]):
            raise TornBundle(
                f"{path}: {fname} is {len(data)} B, manifest covers "
                f"{rec['nbytes']} B"
            )
        crc = zlib.crc32(data) & 0xFFFFFFFF
        if crc != int(rec["crc32"]):
            raise TornBundle(
                f"{path}: checksum mismatch on {fname} "
                f"(stored {rec['crc32']}, computed {crc})"
            )
    return manifest


def list_bundles(directory, quarantine: bool = True) -> list[tuple[str, dict]]:
    """(path, manifest) for every valid bundle under ``directory``,
    oldest (lowest seq) first. A final-named directory that fails
    verification is quarantine-renamed (``quarantine=True``) so no later
    scan trusts it — temp dirs are invisible by construction."""
    out = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not name.startswith(_BUNDLE_PREFIX):
            continue
        path = os.path.join(directory, name)
        try:
            manifest = verify_bundle(path)
        except TornBundle:
            if quarantine:
                qpath = os.path.join(
                    directory,
                    quarantine_name(name, int(time.time() * 1e6)),
                )
                try:
                    os.replace(path, qpath)
                except OSError:
                    pass
            continue
        out.append((path, manifest))
    out.sort(key=lambda pm: int(pm[1].get("seq", 0)))
    return out


# -- helpers ------------------------------------------------------------------

def _slug(reason: str) -> str:
    keep = [c if c.isalnum() else "_" for c in str(reason).lower()]
    return "".join(keep)[:40] or "trigger"


def _encode(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode("utf-8")


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def _write_file(path: str, data: bytes) -> None:
    """Write + fsync one bundle member (always under the temp dir —
    the atomic-publish discipline's write helper)."""
    with open(path, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
