"""Profiler bracketing for whole epochs.

``profile_epoch(log_dir)`` wraps a training epoch in a ``jax.profiler``
capture (TensorBoard/Perfetto format, the reference's stdtracer role) and
force-enables ``trace_scope`` for its duration — so every
``StepTimeline.stage(...)`` and ``trace_scope(...)`` inside the block lands
as a named slice on BOTH the host track and the XLA device timeline, with
the same stage names the host-side metrics report uses. The prior
trace-enable state is restored on exit (a profiled epoch must not leave
tracing globally on).
"""

from __future__ import annotations

import contextlib

import jax

from ..utils import trace as _trace
from ..utils.trace import trace_scope

__all__ = ["profile_epoch"]


@contextlib.contextmanager
def profile_epoch(log_dir: str, name: str = "epoch"):
    """Capture a device+host profile of the enclosed epoch.

    >>> with profile_epoch("/tmp/prof"):
    ...     params, opt_state, losses = trainer.epoch_scan(...)

    opens in TensorBoard/Perfetto with the epoch bracketed under ``name``
    and every inner stage annotated.
    """
    prev = _trace._enabled
    _trace.enable_trace()
    jax.profiler.start_trace(log_dir)
    try:
        with trace_scope(name):
            yield
    finally:
        try:
            jax.profiler.stop_trace()
        finally:
            _trace._enabled = prev
