"""graftscope metrics registry — ONE audited telemetry path for traced code.

Before this module the repo had three in-program telemetry streams
(``last_routed_overflow``, ``last_tier_hits``, ``last_sample_overflow``),
each hand-threading its device value through ``shard_map``/``lax.scan`` with
its own psum placement and its own eager surfacing attribute. The registry
generalizes the discipline those streams proved:

* traced code *registers* a named counter or gauge once (host side, before
  the program is built) and *feeds* it through a :class:`MetricsTape`
  inside the traced body;
* ``tape.finalize()`` emits one metrics pytree (a plain ``{name: array}``
  dict) that rides the program's outputs through ``shard_map``,
  ``lax.scan`` and cond-gated fallback paths like any other value — mesh
  reduction (psum) is applied exactly once per metric per step, at the
  axes the producer declared;
* the eager caller hands the returned pytree to
  :meth:`MetricsRegistry.record`, which lands it as typed
  :class:`MetricSnapshot` objects — epoch_scan-stacked ``(steps, ...)``
  values are detected by shape against the registered spec.

Collection is a real program-level switch: a disabled registry's tape
feeds nothing and finalizes to ``{}``, so the compiled step carries ZERO
metric collectives — and the loss trajectory is bit-identical either way
(tests/test_obs.py differential).

Snapshots hold the device value *lazily* (``int()``/``np.asarray`` of a
just-dispatched scalar would force a sync mid-pipeline — the same rule the
``last_*`` attributes always followed); exporters and reports materialize
on access.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "MetricSpec",
    "MetricSnapshot",
    "MetricsRegistry",
    "MetricsTape",
    "ROUTED_OVERFLOW",
    "TIER_HITS",
    "SAMPLE_OVERFLOW",
    "HETERO_SAMPLE_OVERFLOW",
    "GUARD_SKIPPED",
    "GUARD_NONFINITE",
    "PREFETCH_RETRIES",
    "PREFETCH_SKIPS",
    "PREFETCH_QUEUE_DEPTH",
    "DEGRADED_LOOKUPS",
    "DELTAS_QUARANTINED",
    "DELTAS_COMMITTED",
    "STREAMING_COMMITS",
    "SERVE_REQUESTS",
    "SERVE_DEADLINE_MISSES",
    "SERVE_DEGRADED_LOOKUPS",
    "SERVE_RECOMPILES",
    "SERVE_AOT_LOADS",
    "SERVE_SHED",
    "SERVE_CLASS_MISSES",
    "TRAIN_OVERLAP_EFFICIENCY",
    "PIPELINE_REISSUES",
    "FEATURE_ROW_HEAT",
    "CTRL_DECISIONS",
    "CTRL_REPINS",
    "CTRL_SPLIT_MOVES",
    "CTRL_ALPHA_CHANGES",
    "CTRL_OOC_PROMOTIONS",
    "OOC_STAGE_WAIT",
    "OOC_PAGE_READS",
    "OOC_READAHEAD_HITS",
    "TRACE_SPANS",
    "RECORDER_BUNDLES",
    "RECORDER_EVENTS",
]

# well-known metric names — the three streams the registry was distilled
# from (kept as module constants so producers and consumers cannot drift
# on spelling)
ROUTED_OVERFLOW = "feature.routed_overflow"
TIER_HITS = "feature.tier_hits"
SAMPLE_OVERFLOW = "sample.hop_overflow"
# per-(hop, edge-type) routed-overflow lanes of the distributed hetero
# sampler (flat vector in the sampler's static slot order; relations
# sharing a destination type share that hop's route plan, so they report
# the plan's overflow equally)
HETERO_SAMPLE_OVERFLOW = "sample.hetero_hop_overflow"
# resilience layer: steps cond-skipped by the non-finite guard, and the
# mesh-total count of non-finite loss/grad values it detected
GUARD_SKIPPED = "resilience.skipped_steps"
GUARD_NONFINITE = "resilience.nonfinite_grads"
# host-side resilience counters: prefetcher batch re-dispatches and
# dropped batches (pipeline health next to resilience.skipped_steps in
# metrics_report), and feature lookups served degraded by the cold-tier
# circuit breaker's fallback instead of crashing the step
PREFETCH_RETRIES = "prefetch.retries"
PREFETCH_SKIPS = "prefetch.skipped_batches"
# in-flight prefetch dispatches at the most recent queue transition — the
# gauge that distinguishes "pipeline keeps the depth budget full" from
# "consumer is starving the worker" (lifetime counters can't)
PREFETCH_QUEUE_DEPTH = "prefetch.queue_depth"
DEGRADED_LOOKUPS = "resilience.degraded_lookups"
# out-of-core disk tier (quiver_tpu/ooc): seconds a gather spent BLOCKED
# on window reads (the exposed share of disk cost — hidden reads never
# land here), window reads issued to disk, and requested rows served
# from an already-staged window (the readahead working)
OOC_STAGE_WAIT = "ooc.stage_wait"
OOC_PAGE_READS = "ooc.page_reads"
OOC_READAHEAD_HITS = "ooc.readahead_hits"
# streaming mutation layer (quiver_tpu/streaming): delta batches rejected
# at the ingestion boundary or by a failed commit (quarantined with a
# reason, never partially applied), delta batches merged by a published
# commit, and published commits (= version bumps)
DELTAS_QUARANTINED = "streaming.deltas_quarantined"
DELTAS_COMMITTED = "streaming.deltas_committed"
STREAMING_COMMITS = "streaming.commits"
# online serving layer (quiver_tpu/serving): completed point queries,
# requests finished after their admission deadline, feature lookups a
# serve batch satisfied through the circuit breaker's degraded fallback,
# and ladder-program compilations (zero after warmup = the steady-state
# never-recompile contract of the compiled micro-batch step)
SERVE_REQUESTS = "serve.requests"
SERVE_DEADLINE_MISSES = "serve.deadline_misses"
SERVE_DEGRADED_LOOKUPS = "serve.degraded_lookups"
SERVE_RECOMPILES = "serve.recompiles"
# fleet scale-out (serving/aot.py + serving/fleet.py): ladder programs
# warmed by deserializing a persisted AOT executable instead of compiling
# (a cache-warm replica reports aot_loads == program count and
# recompiles == 0), plus the SLO-class-attributed admission outcomes —
# requests shed under a full queue and requests completed after their
# deadline, both as vectors in serving.coalesce.PRIORITIES order
# (gold, bronze)
SERVE_AOT_LOADS = "serve.aot_loads"
SERVE_SHED = "serve.shed_requests"
SERVE_CLASS_MISSES = "serve.class_deadline_misses"
# software-pipelined epoch (parallel/trainer.py pipeline_depth=1): the
# derived overlap-efficiency gauge (serial stage-sum over measured
# pipelined step time, > 1.0 = the schedule is hiding sample/gather
# latency under compute; fed by bench_epoch --pipeline from the
# StepTimeline) and the count of prologue batches re-issued at
# checkpoint-chunk/resume boundaries (the carried batch is replayed from
# the seed matrix rather than serialized — each boundary costs one extra
# sample+gather)
TRAIN_OVERLAP_EFFICIENCY = "train.overlap_efficiency"
PIPELINE_REISSUES = "train.pipeline_reissues"
# control plane (quiver_tpu/control): the in-program per-row access-heat
# histogram (positional bins over the store's translated row order, psum'd
# once per step like feature.tier_hits; opt-in — registered only when a
# controller asks for it so controller-off telemetry is untouched), and the
# host-side decision counters every CacheController audit record increments:
# total decisions emitted, L0 repins to a measured hot set, L0/L1 boundary
# moves, and routed_alpha changes (grow OR shrink)
FEATURE_ROW_HEAT = "feature.row_heat"
CTRL_DECISIONS = "ctrl.decisions"
CTRL_REPINS = "ctrl.repins"
CTRL_SPLIT_MOVES = "ctrl.split_moves"
CTRL_ALPHA_CHANGES = "ctrl.alpha_changes"
# disk->host-cold promotion/demotion decisions over an out-of-core store
# (quiver_tpu/ooc): one decision restages the whole host cold cache to
# the sketch's measured-hottest disk rows
CTRL_OOC_PROMOTIONS = "ctrl.ooc_promotions"
# grafttrace (obs/tracing.py + obs/recorder.py): finished causal spans
# recorded by the tracer (bounded ring keeps the newest), postmortem
# bundles the flight recorder has published, and decision/audit events
# noted into its ring buffer
TRACE_SPANS = "trace.spans"
RECORDER_BUNDLES = "recorder.bundles"
RECORDER_EVENTS = "recorder.events"

_KINDS = ("counter", "gauge")


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """Declaration of one named metric.

    ``shape`` is the per-step logical shape (``()`` for scalars); an
    epoch_scan epoch lands the metric as ``(steps,) + shape``. ``counter``
    values accumulate within a step (tape ``add``); ``gauge`` values
    overwrite (tape ``set``).
    """

    name: str
    kind: str
    shape: tuple[int, ...] = ()
    dtype: Any = jnp.int32
    doc: str = ""
    unit: str = ""

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {self.kind!r}")


@dataclasses.dataclass
class MetricSnapshot:
    """One recorded metric value (a step's, or a scanned epoch's stack).

    ``value`` may be a device array — it is materialized lazily via
    :attr:`numpy` so recording never forces a host sync. ``steps`` is
    ``None`` for a single step and the scan length for epoch_scan-shaped
    values (leading axis = step index).
    """

    name: str
    kind: str
    value: Any
    steps: int | None = None
    unit: str = ""
    doc: str = ""

    @property
    def numpy(self) -> np.ndarray:
        return np.asarray(self.value)

    @property
    def shape(self) -> tuple[int, ...]:
        """Full stored shape (includes the steps axis when present)."""
        return tuple(np.shape(self.value))

    def total(self):
        """Sum over every axis — the natural counter reduction."""
        return self.numpy.sum()

    def last(self) -> np.ndarray:
        """The most recent per-step value (the value itself when single)."""
        arr = self.numpy
        return arr[-1] if self.steps is not None else arr


class MetricsTape:
    """Per-trace builder of the step's metrics pytree.

    Create one per traced body via :meth:`MetricsRegistry.tape`; feed
    values with :meth:`add` (counters accumulate) / :meth:`set` (gauges
    overwrite); :meth:`finalize` applies each metric's declared psum axes
    once and returns the ``{name: array}`` dict to thread out of the
    program. On a disabled registry every method is a no-op and
    ``finalize`` returns ``{}`` — the compiled program carries no metric
    values at all.
    """

    def __init__(self, registry: "MetricsRegistry"):
        self._registry = registry
        self._values: dict[str, Any] = {}
        self._psum: dict[str, tuple] = {}

    def _note_psum(self, name: str, psum) -> None:
        if psum is None:
            return
        axes = (psum,) if isinstance(psum, str) else tuple(psum)
        prev = self._psum.get(name)
        if prev is not None and prev != axes:
            raise ValueError(
                f"metric {name!r} fed with conflicting psum axes "
                f"{prev} vs {axes}"
            )
        self._psum[name] = axes

    def add(self, name: str, value, psum=None) -> None:
        """Accumulate ``value`` into counter ``name`` (trace-safe ``+``)."""
        if not self._registry.enabled:
            return
        spec = self._registry.spec(name)
        if spec.kind != "counter":
            raise ValueError(f"metric {name!r} is a {spec.kind}; use set()")
        cur = self._values.get(name)
        self._values[name] = value if cur is None else cur + value
        self._note_psum(name, psum)

    def set(self, name: str, value, psum=None) -> None:
        """Overwrite gauge ``name`` with ``value``."""
        if not self._registry.enabled:
            return
        spec = self._registry.spec(name)
        if spec.kind != "gauge":
            raise ValueError(f"metric {name!r} is a {spec.kind}; use add()")
        self._values[name] = value
        self._note_psum(name, psum)

    def finalize(self, names=None) -> dict[str, Any]:
        """The step's metrics pytree: every registered metric present
        (zero-filled from its spec when unfed — the dict structure must be
        static across traces), each psum'd ONCE at its declared axes.

        ``names`` restricts the emitted dict to that subset of registered
        metrics (still zero-filled when unfed). This is what lets a step
        built from SPLIT bodies — the pipelined trainer's issue/train
        halves — emit disjoint dicts whose merge is exactly the fused
        body's pytree; without the filter each half would zero-fill the
        other half's metrics and the merge would clobber real values.
        Feeding a metric and then finalizing without it would silently
        drop the value, so that raises instead."""
        if not self._registry.enabled:
            return {}
        if names is None:
            specs = self._registry.specs()
        else:
            specs = {name: self._registry.spec(name) for name in names}
            dropped = [n for n in self._values if n not in specs]
            if dropped:
                raise ValueError(
                    f"finalize(names=...) would drop fed metrics "
                    f"{sorted(dropped)}; include them in names or don't "
                    f"feed them on this tape"
                )
        out = {}
        for name, spec in specs.items():
            v = self._values.get(name)
            if v is None:
                v = jnp.zeros(spec.shape, spec.dtype)
            else:
                axes = self._psum.get(name)
                if axes:
                    v = jax.lax.psum(v, axes if len(axes) > 1 else axes[0])
                v = jnp.asarray(v, spec.dtype)
            out[name] = v
        return out


class MetricsRegistry:
    """Named counters/gauges with trace-side tapes and eager snapshots.

    Host side: :meth:`counter`/:meth:`gauge` declare metrics (idempotent —
    re-declaring with an identical spec is a no-op, a conflicting one
    raises); :meth:`record` lands a program's metrics pytree as
    :class:`MetricSnapshot` objects; :meth:`value`/:meth:`snapshot` read
    them back. Trace side: :meth:`tape`. ``enabled=False`` turns the whole
    registry into a no-op (tapes feed nothing, record drops everything) —
    the compiled-program-level collection switch.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._specs: dict[str, MetricSpec] = {}
        self._snaps: dict[str, MetricSnapshot] = {}

    # -- declaration --------------------------------------------------------

    def _register(self, spec: MetricSpec) -> str:
        prev = self._specs.get(spec.name)
        if prev is not None:
            if prev != spec:
                raise ValueError(
                    f"metric {spec.name!r} already registered with a "
                    f"different spec ({prev} vs {spec})"
                )
            return spec.name
        self._specs[spec.name] = spec
        return spec.name

    def counter(self, name: str, shape=(), dtype=jnp.int32, doc: str = "",
                unit: str = "") -> str:
        """Register (or re-assert) a counter; returns ``name``."""
        return self._register(
            MetricSpec(name, "counter", tuple(shape), dtype, doc, unit)
        )

    def gauge(self, name: str, shape=(), dtype=jnp.int32, doc: str = "",
              unit: str = "") -> str:
        """Register (or re-assert) a gauge; returns ``name``."""
        return self._register(
            MetricSpec(name, "gauge", tuple(shape), dtype, doc, unit)
        )

    def spec(self, name: str) -> MetricSpec:
        try:
            return self._specs[name]
        except KeyError:
            raise KeyError(
                f"metric {name!r} is not registered (known: "
                f"{sorted(self._specs)})"
            ) from None

    def specs(self) -> dict[str, MetricSpec]:
        """Registered specs, insertion-ordered (read-only copy)."""
        return dict(self._specs)

    def names(self) -> list[str]:
        return list(self._specs)

    # -- trace side ---------------------------------------------------------

    def tape(self) -> MetricsTape:
        return MetricsTape(self)

    # -- eager side ---------------------------------------------------------

    def _steps_of(self, spec: MetricSpec, value) -> int | None:
        ndim = np.ndim(value)
        if ndim == len(spec.shape):
            return None
        if ndim == len(spec.shape) + 1:
            return int(np.shape(value)[0])  # epoch_scan stack
        raise ValueError(
            f"metric {spec.name!r}: value ndim {ndim} matches neither the "
            f"spec shape {spec.shape} nor a (steps,)-stacked epoch of it"
        )

    def record(self, values: dict[str, Any]) -> None:
        """Land a program's metrics pytree as snapshots (no host sync —
        values stay device-resident until an exporter/report reads them)."""
        if not self.enabled or not values:
            return
        for name, v in values.items():
            self.set(name, v)

    def set(self, name: str, value) -> None:
        """Host-side write of one metric (``None`` clears it) — the thin
        compatibility path behind the legacy ``last_*`` attribute setters."""
        if value is None:
            self._snaps.pop(name, None)
            return
        spec = self.spec(name)
        self._snaps[name] = MetricSnapshot(
            name, spec.kind, value, self._steps_of(spec, value),
            spec.unit, spec.doc,
        )

    def value(self, name: str):
        """The raw recorded value (device array or host array), or None."""
        snap = self._snaps.get(name)
        return None if snap is None else snap.value

    def snapshot(self, name: str) -> MetricSnapshot | None:
        return self._snaps.get(name)

    def snapshots(self) -> list[MetricSnapshot]:
        """Every recorded snapshot, registration-ordered."""
        return [self._snaps[n] for n in self._specs if n in self._snaps]

    def clear(self, name: str | None = None) -> None:
        if name is None:
            self._snaps.clear()
        else:
            self._snaps.pop(name, None)
