"""quiver-tpu: TPU-native graph sampling + feature collection for GNN training.

A from-scratch JAX/XLA/Pallas framework with the capabilities of
ed-aisys/torch-quiver (see SURVEY.md): GPU-class k-hop neighbor sampling and
tiered feature caching for PyG-style mini-batch GNN training, redesigned for
TPU — static shapes, single-controller SPMD over a ``jax.Mesh``, ICI
collectives instead of NVLink peer access, and host-offload staging instead
of UVA zero-copy.

Top-level exports mirror the reference package surface
(torch-quiver srcs/python/quiver/__init__.py:1-10).
"""

from .control import AlphaTuner, CacheController, CostModel, FreqSketch, SplitTuner
from .core.config import CachePolicy, SampleMode, parse_size_bytes
from .datasets import GraphDataset, load_dataset, planted_partition
from .core.hetero import HeteroCSRTopo, RelCSR
from .core.hetero_sharded import HeteroShardedTopology
from .core.sharded_topology import ShardedTopology
from .core.topology import CSRTopo, DeviceTopology
from .feature.feature import Feature, HeteroFeature
from .feature.shard import ShardedFeature, ShardedTensor
from .parallel.mesh import MeshTopo, can_device_access_peer, init_p2p, make_mesh
from .parallel.pipeline import Batch, Prefetcher
from .parallel.trainer import DataParallelTrainer, DistributedTrainer
from .sampling.dist_hetero import DistHeteroSampler
from .sampling.hetero import HeteroGraphSampler, HeteroLayer, HeteroSampleOutput
from .sampling.saint import (
    SAINTEdgeSampler,
    SAINTNodeSampler,
    SAINTRandomWalkSampler,
    saint_subgraph,
)
from .obs import (
    FlightRecorder,
    MetricSnapshot,
    MetricsRegistry,
    StepTimeline,
    TelemetryEndpoint,
    Tracer,
    profile_epoch,
)
from .ooc import (
    AsyncStager,
    CorruptRawDir,
    MmapFeatureStore,
    quarantine_raw_dir,
    verify_raw_dir,
)
from .resilience import (
    CircuitBreaker,
    CorruptCheckpoint,
    DegradedFeature,
    FaultPlan,
    Preemption,
    TransientFault,
)
from .sampling.dist import DistGraphSageSampler
from .sampling.sampler import Adj, GraphSageSampler, SampleOutput
from .serving import (
    AOTExecutableCache,
    DeadlineBatcher,
    EmbeddingRefresher,
    InferenceServer,
    ServeQueueFull,
    ServingFleet,
)
from .streaming import (
    CommitAborted,
    DeltaBatch,
    DeltaRejected,
    StreamingGraph,
    VersionMismatchError,
)
from .utils.debug import show_tensor_info, tensor_info
from .utils.reorder import reorder_by_degree
from .utils.trace import Timer, enable_trace, get_logger, trace_scope

# reference name parity: `quiver.p2pCliqueTopo` (utils.py:64-104) is the
# clique view of the device set — on TPU, the ICI-slice view
p2pCliqueTopo = MeshTopo

__all__ = [
    "CSRTopo",
    "DeviceTopology",
    "ShardedTopology",
    "HeteroShardedTopology",
    "DistGraphSageSampler",
    "DistHeteroSampler",
    "HeteroCSRTopo",
    "RelCSR",
    "GraphSageSampler",
    "HeteroGraphSampler",
    "HeteroLayer",
    "HeteroSampleOutput",
    "SAINTNodeSampler",
    "SAINTEdgeSampler",
    "SAINTRandomWalkSampler",
    "saint_subgraph",
    "Adj",
    "SampleOutput",
    "Feature",
    "HeteroFeature",
    "ShardedFeature",
    "ShardedTensor",
    "MeshTopo",
    "p2pCliqueTopo",
    "Batch",
    "Prefetcher",
    "DataParallelTrainer",
    "DistributedTrainer",
    "make_mesh",
    "init_p2p",
    "can_device_access_peer",
    "CachePolicy",
    "SampleMode",
    "parse_size_bytes",
    "GraphDataset",
    "load_dataset",
    "planted_partition",
    "reorder_by_degree",
    "show_tensor_info",
    "tensor_info",
    "Checkpointer",
    "Timer",
    "trace_scope",
    "enable_trace",
    "get_logger",
    "MetricsRegistry",
    "MetricSnapshot",
    "StepTimeline",
    "profile_epoch",
    "Tracer",
    "FlightRecorder",
    "TelemetryEndpoint",
    "MmapFeatureStore",
    "AsyncStager",
    "CorruptRawDir",
    "verify_raw_dir",
    "quarantine_raw_dir",
    "FaultPlan",
    "Preemption",
    "TransientFault",
    "CircuitBreaker",
    "CorruptCheckpoint",
    "DegradedFeature",
    "DeltaBatch",
    "DeltaRejected",
    "StreamingGraph",
    "CommitAborted",
    "VersionMismatchError",
    "InferenceServer",
    "DeadlineBatcher",
    "EmbeddingRefresher",
    "ServeQueueFull",
    "ServingFleet",
    "AOTExecutableCache",
    "AlphaTuner",
    "CacheController",
    "CostModel",
    "FreqSketch",
    "SplitTuner",
]

__version__ = "0.1.0"


def __getattr__(name):
    # Checkpointer stays a lazy resolve (historical import-shape parity:
    # the store was once orbax-backed and optional; it is self-contained
    # now, but call sites import it both ways)
    if name == "Checkpointer":
        from .utils.checkpoint import Checkpointer

        return Checkpointer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
