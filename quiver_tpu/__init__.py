"""quiver-tpu: TPU-native graph sampling + feature collection for GNN training.

A from-scratch JAX/XLA/Pallas framework with the capabilities of
ed-aisys/torch-quiver (see SURVEY.md): GPU-class k-hop neighbor sampling and
tiered feature caching for PyG-style mini-batch GNN training, redesigned for
TPU — static shapes, single-controller SPMD over a ``jax.Mesh``, ICI
collectives instead of NVLink peer access, and host-offload staging instead
of UVA zero-copy.

Top-level exports mirror the reference package surface
(torch-quiver srcs/python/quiver/__init__.py:1-10).
"""

from .core.config import CachePolicy, SampleMode, parse_size_bytes
from .core.topology import CSRTopo, DeviceTopology
from .sampling.sampler import Adj, GraphSageSampler, SampleOutput
from .utils.reorder import reorder_by_degree

__all__ = [
    "CSRTopo",
    "DeviceTopology",
    "GraphSageSampler",
    "Adj",
    "SampleOutput",
    "CachePolicy",
    "SampleMode",
    "parse_size_bytes",
    "reorder_by_degree",
]

__version__ = "0.1.0"
