"""quiver-ooc — out-of-core graph store: the disk tier below the ladder.

The reference's UVA hierarchy ends at host RAM: a papers100M-class run
assumes the full CSR and every cold feature row fit the host. This
package adds the fourth storage tier below the existing L0/L1/cold
ladder — disk — without changing a single gather's bytes:

* :mod:`~quiver_tpu.ooc.format` — the mmap-native on-disk layout:
  per-array uncompressed ``.npy`` files plus a CRC32 manifest and a
  COMMIT marker, published atomically (tmp dir + fsync + ``os.replace``,
  the ``resilience/integrity`` discipline). ``CSRTopo.save(path,
  format="raw")`` / ``CSRTopo.load(path, mmap=True)`` ride it.
* :class:`~quiver_tpu.ooc.store.MmapFeatureStore` — a disk-backed
  feature store bitwise-identical to the in-RAM :class:`~quiver_tpu.
  feature.feature.Feature` (same translated row space, same tiered
  gather merge), with resident bytes O(touched pages), not O(graph).
* :class:`~quiver_tpu.ooc.stager.AsyncStager` — bounded background
  window reads with seeded retry/backoff (the Prefetcher's resilience
  pattern), measured via ``ooc.stage_wait`` / ``ooc.page_reads`` /
  ``ooc.readahead_hits``.

quiver-ctl closes the loop one tier further down: the FreqSketch's
measured heat decides which disk rows earn promotion into the host cold
cache (:meth:`~quiver_tpu.control.controller.CacheController
.maybe_promote`), audited like every other controller decision.
"""

from .format import (
    RAW_FORMAT,
    CorruptRawDir,
    load_raw_dir,
    quarantine_raw_dir,
    save_raw_dir,
    verify_raw_dir,
)
from .stager import AsyncStager
from .store import MmapFeatureStore

__all__ = [
    "AsyncStager",
    "CorruptRawDir",
    "MmapFeatureStore",
    "RAW_FORMAT",
    "load_raw_dir",
    "quarantine_raw_dir",
    "save_raw_dir",
    "verify_raw_dir",
]
