"""MmapFeatureStore — disk-backed feature rows, bitwise-equal gathers.

The in-RAM :class:`~quiver_tpu.feature.feature.Feature` ends at host
memory: its cold tier is a pinned-host array holding EVERY beyond-budget
row. This store pushes that tail one tier down — the full post-reorder,
post-quantize row table lives on disk in the raw format
(:mod:`~quiver_tpu.ooc.format`), and a gather touches only the pages it
needs:

* **hot tier** — translated rows ``[0, hot_rows)``, materialized into
  HBM once at open (identical bytes to Feature's hot tier);
* **host cold cache** — an OPTIONAL, arbitrary set of promoted disk rows
  pinned in host RAM (``host_cache_rows`` budget; quiver-ctl restages it
  to the FreqSketch's measured-hottest rows via :meth:`restage`);
* **disk tier** — everything, window-read through an
  :class:`~quiver_tpu.ooc.stager.AsyncStager`.

Bitwise identity with Feature is by construction, not by tolerance: the
write path (:meth:`write`) runs the SAME split/reorder/quantize
decisions as ``Feature.from_cpu_tensor`` (same budget arithmetic, same
``reorder_by_degree`` seed, quantize-after-reorder), and the lookup path
reuses the SAME ``tiered_lookup`` merge with the SAME hot gather and
dequant wrapping. The only difference is where the cold tier's bytes
come from: Feature gathers them from a device-resident table inside the
program; this store assembles the lane-aligned cold block on the host
(cache + windowed disk reads) and hands it to the identical merge — the
values per lane are the same bytes, so batches, losses and telemetry
match bit-for-bit (tests/test_ooc.py differentials).

Consequence: lookups are EAGER (host staging cannot be traced), which is
exactly the unfused ``DataParallelTrainer``/``Prefetcher`` path — the
reference's flagship papers100M architecture. The fused trainer keeps
its in-RAM stores.

Address-space modes: ``access="mmap"`` (default) backs the row table
onto ``np.memmap`` — resident bytes O(touched pages), virtual bytes
O(file). ``access="pread"`` never maps the file at all — windows are
``os.pread`` into pooled buffers, so VIRTUAL address space stays
O(cache_windows * window_bytes); this is the mode the rlimit'd drill
(benchmarks/ooc_drill.py) runs under ``resource.setrlimit(RLIMIT_AS)``
to make "the graph does not fit" mechanical.
"""

from __future__ import annotations

import os

import numpy as np

import jax.numpy as jnp

from ..core.config import parse_size_bytes
from ..feature.feature import (
    KernelChoice,
    _hot_gather_fn,
    _parse_storage_dtype,
    quantize_rows_int8,
    tiered_lookup,
    validate_gather_kernel,
    wrap_dequant_gathers,
)
from ..utils.reorder import reorder_by_degree
from ..utils.trace import get_logger, trace_scope
from .format import load_raw_dir, npy_data_offset, save_raw_dir
from .stager import AsyncStager

__all__ = ["MmapFeatureStore"]

_ACCESS_MODES = ("mmap", "pread")
_ROWS_KIND = "quiver-ooc-feature-rows"


class MmapFeatureStore(KernelChoice):
    """Open a :meth:`write`-prepared raw feature directory for lookups.

    Args:
      path: raw-format directory written by :meth:`write`.
      kernel: hot-tier gather kernel ("auto" elects, like Feature).
      access: "mmap" (np.memmap window slices) or "pread"
        (positioned reads, zero file mappings — the rlimit-drill mode).
      window_rows: rows per disk read (the readahead granularity).
      cache_windows: stager LRU capacity in windows.
      host_cache_rows: byte-budget-free row count for the promoted host
        cold cache ("0" disables; quiver-ctl fills it via restage()).
      retries/backoff/backoff_cap/jitter/retry_seed: stager resilience
        knobs (the Prefetcher contract).
      metrics/timeline: graftscope registry + StepTimeline for the
        ``ooc.*`` counters and stages.
    """

    def __init__(self, path: str, kernel: str = "auto",
                 access: str = "mmap", window_rows: int = 1024,
                 cache_windows: int = 32, host_cache_rows: int = 0,
                 retries: int = 0, backoff: float = 0.05,
                 backoff_cap: float = 2.0, jitter: float = 0.5,
                 retry_seed: int = 0, metrics=None, timeline=None):
        if access not in _ACCESS_MODES:
            raise ValueError(
                f"access must be one of {_ACCESS_MODES}, got {access!r}"
            )
        self.path = str(path)
        self._kernel = validate_gather_kernel(kernel)
        self.access = access
        self.metrics = metrics
        self.timeline = timeline
        # structural checks only: the manifest CRCs were computed at
        # write time and a full sweep would page the whole table in —
        # run ooc.verify_raw_dir(path) when bytes are suspect
        arrays, meta = load_raw_dir(self.path, mmap=True, verify=False)
        if meta.get("kind") != _ROWS_KIND:
            raise ValueError(
                f"{path}: not a feature-rows raw dir "
                f"(kind={meta.get('kind')!r}); write one with "
                f"MmapFeatureStore.write()"
            )
        rows = arrays["rows"]
        n, f = rows.shape
        self.shape = (n, f)
        self.dtype = rows.dtype
        self.hot_rows = int(meta["hot_rows"])
        self.cache_budget = int(meta.get("cache_budget", 0))
        # scale/feature_order are O(N) metadata tiers, resident like
        # Feature's (the O(graph) rows are what stays on disk)
        self.scale = None
        if "scale" in arrays:
            self.scale = jnp.asarray(np.asarray(arrays["scale"]))
        self.feature_order = None
        self._order_np = None
        if "feature_order" in arrays:
            order = np.asarray(arrays["feature_order"])
            self.feature_order = jnp.asarray(order)
            self._order_np = order
        self.hot = None
        if self.hot_rows > 0:
            self.hot = jnp.asarray(np.asarray(rows[:self.hot_rows]))
        self._cold_rows = n - self.hot_rows
        self._rows_mm = None
        self._fd = -1
        self._data_offset = 0
        rows_path = os.path.join(self.path, "rows.npy")
        if access == "mmap":
            self._rows_mm = rows
        else:
            _, _, self._data_offset = npy_data_offset(rows_path)
            self._fd = os.open(rows_path, os.O_RDONLY)
        # promoted host cold cache: translated cold-local row ids
        # (sorted) + their rows, restaged by quiver-ctl between batches
        self.host_cache_rows = int(host_cache_rows)
        self._cache_ids = np.empty(0, np.int64)
        self._cache_block = None
        self.cold_cache_hits_total = 0
        self.stager = None
        if self._cold_rows > 0:
            num_windows = -(-self._cold_rows // int(window_rows))
            self.stager = AsyncStager(
                self._read_window, num_windows=num_windows,
                window_rows=int(window_rows),
                cache_windows=int(cache_windows), retries=retries,
                backoff=backoff, backoff_cap=backoff_cap, jitter=jitter,
                retry_seed=retry_seed, metrics=metrics, timeline=timeline,
            )
        get_logger("ooc").info(
            "opened %s: %d rows x %d (%s, %s), hot=%d on device, "
            "cold=%d on disk (window=%d rows, cache=%d windows), host "
            "cache budget=%d rows",
            path, n, f, self.dtype, access, self.hot_rows,
            self._cold_rows, int(window_rows), int(cache_windows),
            self.host_cache_rows,
        )

    # -- write side ----------------------------------------------------------

    @classmethod
    def write(cls, path: str, tensor, device_cache_size: int | str = 0,
              csr_topo=None, dtype=None,
              hot_shuffle_seed: int = 0) -> dict:
        """Prepare a raw feature directory from an in-RAM table.

        Runs EXACTLY ``Feature.from_cpu_tensor``'s placement decisions —
        same byte-budget arithmetic (int8 charges the (N,) scale tier
        first), same degree reorder at the same hot ratio and seed,
        quantization AFTER the reorder — then publishes the
        post-processed row table (plus scale/feature_order) atomically
        in the raw format. A Feature built from the same inputs and an
        MmapFeatureStore opened on this directory hold identical bytes
        in every tier. Sets ``csr_topo.feature_order`` like the Feature
        path does. Returns the manifest.
        """
        tensor = np.asarray(tensor)
        storage_dtype = _parse_storage_dtype(dtype)
        quantized = (
            storage_dtype is not None
            and storage_dtype == np.dtype(np.int8)
        )
        if (
            storage_dtype is not None
            and not quantized
            and tensor.dtype != storage_dtype
        ):
            tensor = tensor.astype(storage_dtype)
        n, f = tensor.shape
        cache_budget = parse_size_bytes(device_cache_size)
        if quantized:
            row_bytes = f
            hot_rows = min(n, max(cache_budget - 4 * n, 0) // row_bytes)
        else:
            row_bytes = f * tensor.dtype.itemsize
            hot_rows = min(n, cache_budget // row_bytes)

        order = None
        if csr_topo is not None and hot_rows < n:
            hot_ratio = hot_rows / n
            tensor, order = reorder_by_degree(
                tensor, csr_topo.degree, hot_ratio, seed=hot_shuffle_seed
            )
            csr_topo.feature_order = order

        scale = None
        if quantized:
            tensor, scale = quantize_rows_int8(tensor)  # AFTER the reorder

        arrays = {"rows": tensor}
        if scale is not None:
            arrays["scale"] = scale
        if order is not None:
            arrays["feature_order"] = order
        meta = {
            "kind": _ROWS_KIND,
            "shape": [int(n), int(f)],
            "storage_dtype": str(tensor.dtype),
            "hot_rows": int(hot_rows),
            "cache_budget": int(cache_budget),
            "hot_shuffle_seed": int(hot_shuffle_seed),
            "quantized": bool(quantized),
        }
        return save_raw_dir(path, arrays, meta)

    # -- disk access ---------------------------------------------------------

    def _read_window(self, window: int) -> np.ndarray:
        """One window of cold-tier rows (cold-local row space); runs on
        the stager's worker thread."""
        w = self.stager.window_rows
        lo = window * w
        hi = min(lo + w, self._cold_rows)
        if self.access == "mmap":
            return np.array(self._rows_mm[self.hot_rows + lo:
                                          self.hot_rows + hi])
        n, f = self.shape
        row_bytes = f * self.dtype.itemsize
        offset = self._data_offset + (self.hot_rows + lo) * row_bytes
        nbytes = (hi - lo) * row_bytes
        buf = b""
        while len(buf) < nbytes:  # pread may return short on some fs
            chunk = os.pread(self._fd, nbytes - len(buf), offset + len(buf))
            if not chunk:
                raise OSError(
                    f"{self.path}: short read at offset {offset} "
                    f"({len(buf)}/{nbytes} B)"
                )
            buf += chunk
        return np.frombuffer(buf, self.dtype).reshape(hi - lo, f)

    def _gather_cold(self, cold_local: np.ndarray) -> np.ndarray:
        """Lane-aligned cold block: host cache hits + staged disk reads."""
        out = None
        pending = np.ones(cold_local.shape, bool)
        if self._cache_ids.size:
            pos = np.searchsorted(self._cache_ids, cold_local)
            pos_c = np.minimum(pos, self._cache_ids.size - 1)
            hit = self._cache_ids[pos_c] == cold_local
            if hit.any():
                out = np.empty(
                    cold_local.shape + self.shape[1:2], self.dtype
                )
                out[hit] = self._cache_block[pos_c[hit]]
                pending &= ~hit
                self.cold_cache_hits_total += int(hit.sum())
        if pending.any():
            block = self.stager.fetch(cold_local[pending])
            if out is None:
                out = np.empty(
                    cold_local.shape + block.shape[1:], block.dtype
                )
            out[pending] = block
        return out

    # -- lookup --------------------------------------------------------------

    def _cold_local(self, n_id) -> np.ndarray | None:
        """Host-side mirror of tiered_lookup's cold-tier id routing:
        valid lanes translate through feature_order; other-tier and
        invalid lanes point at cold row 0 (the cold-lane trick), so the
        assembled block is lane-for-lane what Feature's device gather
        reads."""
        if self._cold_rows <= 0:
            return None
        ids = np.asarray(n_id).reshape(-1)
        ids = np.where(ids >= 0, ids, 0)
        if self._order_np is not None:
            ids = np.asarray(self._order_np[ids], np.int64)
        return np.where(ids >= self.hot_rows, ids - self.hot_rows, 0)

    def __getitem__(self, n_id):
        """Gather rows for (possibly padded, -1 sentinel) node ids.

        Eager (host-staged disk reads); bitwise-identical to the in-RAM
        Feature's lookup — same translated row space, same tier merge,
        same dequant wrapping.
        """
        cold_local = self._cold_local(n_id)
        cold_gather = None
        if cold_local is not None:
            with trace_scope("ooc_stage"):
                block = jnp.asarray(self._gather_cold(cold_local))
            # lane-aligned: tiered_lookup's traced cold ids reproduce
            # exactly the routing _cold_local ran on the host, so the
            # block IS the gather's result (the dequant wrapper still
            # consumes the traced ids for its scale lookup)
            cold_gather = lambda ids: block  # noqa: E731
        hot_gather = (
            None if self.hot is None
            else _hot_gather_fn(self.hot, self.kernel)
        )
        _, hot_gather, cold_gather = wrap_dequant_gathers(
            self.scale, self.hot_rows, hot_gather, cold_gather
        )
        with trace_scope("feature_gather"):
            return tiered_lookup(
                n_id, self.feature_order, self.hot_rows, hot_gather,
                cold_gather,
            )

    def trace_lookup(self, batch: int):
        """AOT-trace the device-side tier merge one staged batch runs —
        the SAME ``tiered_lookup`` + dequant wrapping as
        :meth:`__getitem__`, with the host-assembled cold block as a
        program *operand* (host staging cannot be traced). No disk
        I/O, no execution: this is the graftmem audit surface for the
        out-of-core path (``mmap_tiered_gather``), so the merge's
        per-device bytes are provable without paging the table in."""
        import jax

        operands = [jax.ShapeDtypeStruct((int(batch),), jnp.int32)]
        if self._cold_rows > 0:
            operands.append(jax.ShapeDtypeStruct(
                (int(batch), self.shape[1]), self.dtype))

        def merged(n_id, *staged):
            cold_gather = None
            if staged:
                block = staged[0]
                cold_gather = lambda ids: block  # noqa: E731
            hot_gather = (
                None if self.hot is None
                else _hot_gather_fn(self.hot, self.kernel)
            )
            _, hot_gather, cold_gather = wrap_dequant_gathers(
                self.scale, self.hot_rows, hot_gather, cold_gather
            )
            return tiered_lookup(
                n_id, self.feature_order, self.hot_rows, hot_gather,
                cold_gather,
            )

        return jax.jit(merged).trace(*operands)

    def prefetch(self, n_id) -> int:
        """Dispatch background disk reads for a FUTURE batch's cold rows
        (bounded; returns reads issued). The overlap seam: call with
        batch t+1's ids while batch t trains."""
        cold_local = self._cold_local(n_id)
        if cold_local is None:
            return 0
        return self.stager.prefetch(cold_local)

    # -- promoted host cold cache (quiver-ctl's seam) ------------------------

    def restage(self, cold_local_ids) -> int:
        """Replace the host cold cache with ``cold_local_ids`` (cold-tier
        row space), reading newly promoted rows through the stager.
        Capped at ``host_cache_rows``; rows not in the new set spill back
        to disk-only (their bytes were never mutated — dropping the copy
        IS the demotion). Returns the resident row count."""
        ids = np.unique(np.asarray(cold_local_ids, np.int64).reshape(-1))
        ids = ids[(ids >= 0) & (ids < self._cold_rows)]
        if self.host_cache_rows > 0:
            ids = ids[:self.host_cache_rows]
        if ids.size == 0:
            self._cache_ids = np.empty(0, np.int64)
            self._cache_block = None
            return 0
        self._cache_block = self.stager.fetch(ids)
        self._cache_ids = ids
        return int(ids.size)

    @property
    def staged_ids(self) -> np.ndarray:
        """Current host-cache membership (cold-local row ids, sorted)."""
        return self._cache_ids

    # -- Feature-parity surface ----------------------------------------------

    def size(self, dim: int) -> int:
        return self.shape[dim]

    @property
    def cache_ratio(self) -> float:
        return self.hot_rows / self.shape[0] if self.shape else 0.0

    def close(self) -> None:
        if self.stager is not None:
            self.stager.close()
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def __enter__(self) -> "MmapFeatureStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
