"""AsyncStager — bounded background window reads for the disk tier.

The Prefetcher already hides batch t+1's host-side sample+gather under
batch t's device step; this class rides the same pattern one level down:
the disk reads a cold-row gather needs are dispatched to a single
background thread as WINDOW reads (``window_rows`` consecutive rows per
read — the GNNSampler locality argument: staged layouts keep DMA reads
contiguous), and the gather only blocks on the windows it actually
needs. The blocked share is measured, not asserted:

* ``ooc.stage_wait`` (StepTimeline stage + registry gauge, seconds) —
  time :meth:`fetch` spent blocked on window futures, i.e. the EXPOSED
  disk cost; reads that completed under compute cost zero here (their
  full durations land on the ``ooc.read`` timeline stage);
* ``ooc.page_reads`` — window reads issued to disk;
* ``ooc.readahead_hits`` — requested rows served without a new read:
  the row's window was already cached or in flight, or rode in on a
  window this same fetch dispatched for a neighboring row (every row
  beyond a dispatched window's first is a readahead hit — the windowed
  read amortized).

Failures follow the Prefetcher's resilience contract exactly: a raising
read is retried with bounded exponential backoff and deterministic
seeded jitter (``retries``/``backoff``/``backoff_cap``/``jitter``/
``retry_seed``); exhausted retries surface at the fetch that needed the
window. The worker is a single thread, so read order — and therefore
the page cache's eviction order — is deterministic.

Lifecycle: the stager owns its executor; call :meth:`close` (or use it
as a context manager) when done — the graftlint executor-lifecycle rule
holds this to the same standard as every other pool owner in the repo.
"""

from __future__ import annotations

import collections
import concurrent.futures
import random
import threading
import time

import numpy as np

from ..obs.registry import (
    OOC_PAGE_READS,
    OOC_READAHEAD_HITS,
    OOC_STAGE_WAIT,
)

__all__ = ["AsyncStager"]


class AsyncStager:
    """Stage disk-tier row windows through a bounded background reader.

    Args:
      read_window: callable ``(window_index) -> np.ndarray`` returning
        the window's rows — the only thing that touches the disk. Runs
        on the worker thread; may raise (retried per the policy below).
      num_windows: total window count (bounds prefetch requests).
      window_rows: rows per window (the readahead granularity).
      cache_windows: LRU capacity in windows; also the in-flight bound —
        the stager never holds more than ``cache_windows`` windows
        staged + pending, so resident staging bytes are
        ``cache_windows * window_bytes`` regardless of graph size.
      retries / backoff / backoff_cap / jitter / retry_seed: the
        Prefetcher's bounded-retry contract for a raising read.
      metrics: optional graftscope ``MetricsRegistry`` — lands
        ``ooc.page_reads`` / ``ooc.readahead_hits`` counters and the
        cumulative ``ooc.stage_wait`` gauge.
      timeline: optional StepTimeline — per-event ``ooc.stage_wait``
        (exposed wait per fetch), ``ooc.read`` (each background read's
        duration), ``ooc.retry_wait`` (each backoff sleep).
      tracer: optional grafttrace :class:`~quiver_tpu.obs.tracing
        .Tracer` — the same per-event stages land as spans (subsystem
        ``stager``) tagged with the causing ``trace`` id.
      trace: trace id the stager's spans attach to.
    """

    def __init__(self, read_window, num_windows: int, window_rows: int,
                 cache_windows: int = 32, retries: int = 0,
                 backoff: float = 0.05, backoff_cap: float = 2.0,
                 jitter: float = 0.5, retry_seed: int = 0,
                 metrics=None, timeline=None, tracer=None,
                 trace: str | None = None):
        if num_windows < 1:
            raise ValueError(f"num_windows must be >= 1, got {num_windows}")
        if window_rows < 1:
            raise ValueError(f"window_rows must be >= 1, got {window_rows}")
        if cache_windows < 1:
            raise ValueError(
                f"cache_windows must be >= 1, got {cache_windows}"
            )
        if retries < 0 or backoff < 0 or backoff_cap < 0 or jitter < 0:
            raise ValueError(
                "retries/backoff/backoff_cap/jitter must be >= 0"
            )
        self._read_window = read_window
        self.num_windows = int(num_windows)
        self.window_rows = int(window_rows)
        self.cache_windows = int(cache_windows)
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.jitter = float(jitter)
        self.metrics = metrics
        self.timeline = timeline
        if metrics is not None:
            metrics.counter(
                OOC_PAGE_READS, unit="windows",
                doc="disk window reads issued by the out-of-core stager "
                    "(lifetime total)",
            )
            metrics.counter(
                OOC_READAHEAD_HITS, unit="rows",
                doc="requested disk rows served from an already-staged "
                    "window — cached, in flight, or amortized onto a "
                    "neighboring row's windowed read (lifetime total)",
            )
            metrics.gauge(
                OOC_STAGE_WAIT, dtype=np.float32, unit="s",
                doc="cumulative seconds gathers spent BLOCKED on disk "
                    "window reads (the exposed share of disk cost)",
            )
        # jitter PRNG lives on the single worker thread (like the
        # Prefetcher's: deterministic backoff stream per retry_seed)
        self._jitter_rng = random.Random(retry_seed)
        self._lock = threading.Lock()
        # window index -> rows (completed) / Future (in flight); the
        # worker function NEVER takes the lock — fetch() publishes
        # completed windows into the cache after waiting
        self._cache: collections.OrderedDict = collections.OrderedDict()
        self._pending: dict = {}
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="quiver-ooc-stage"
        )
        self.tracer = tracer
        self.trace = trace
        self.page_reads_total = 0
        self.readahead_hits_total = 0
        self.read_retries_total = 0
        self.stage_wait_total = 0.0

    # -- telemetry -----------------------------------------------------------

    def _observe(self, stage: str, seconds: float) -> None:
        if self.timeline is not None:
            self.timeline.observe(stage, seconds)
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.observe(
                stage, seconds, trace=self.trace, subsystem="stager",
            )

    def _publish_counters(self) -> None:
        if self.metrics is not None:
            self.metrics.set(OOC_PAGE_READS, np.int32(self.page_reads_total))
            self.metrics.set(
                OOC_READAHEAD_HITS, np.int32(self.readahead_hits_total)
            )
            self.metrics.set(
                OOC_STAGE_WAIT, np.float32(self.stage_wait_total)
            )

    # -- worker side (no lock: reads bytes, returns them) --------------------

    def _read_resilient(self, window: int) -> np.ndarray:
        attempt = 0
        while True:
            t0 = time.perf_counter()
            try:
                rows = self._read_window(window)
            except Exception:  # noqa: BLE001 — bounded retry, then surface
                if attempt >= self.retries:
                    raise
                attempt += 1
                self.read_retries_total += 1
                delay = min(
                    self.backoff * 2.0 ** (attempt - 1), self.backoff_cap
                ) * (1.0 + self.jitter * self._jitter_rng.random())
                self._observe("ooc.retry_wait", delay)
                if delay > 0:
                    time.sleep(delay)
            else:
                self._observe("ooc.read", time.perf_counter() - t0)
                return np.asarray(rows)

    # -- staging -------------------------------------------------------------

    def _windows_of(self, rows: np.ndarray) -> np.ndarray:
        return np.unique(rows // self.window_rows)

    def _dispatch_locked(self, window: int) -> None:
        """Issue one window read (caller holds the lock; submit() only
        enqueues — the worker function takes no locks, so there is no
        re-acquisition across this call)."""
        self._pending[window] = self._pool.submit(
            self._read_resilient, int(window)
        )
        self.page_reads_total += 1

    def prefetch(self, rows) -> int:
        """Dispatch background reads for the windows covering ``rows``
        without waiting. Bounded: stops once staged + in-flight windows
        reach ``cache_windows``. Returns the number of reads issued."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        if rows.size == 0:
            return 0
        issued = 0
        with self._lock:
            for w in self._windows_of(rows).tolist():
                if w in self._cache or w in self._pending:
                    continue
                if len(self._cache) + len(self._pending) >= self.cache_windows:
                    break
                self._dispatch_locked(w)
                issued += 1
        if issued:
            self._publish_counters()
        return issued

    def fetch(self, rows) -> np.ndarray:
        """Gather disk rows ``rows`` (1-D, window-relative row ids),
        blocking only on the windows not already staged. Returns the
        (len(rows), ...) row block in request order."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        if rows.size == 0:
            raise ValueError("fetch of an empty row set")
        windows = rows // self.window_rows
        uniq, counts = np.unique(windows, return_counts=True)
        need: dict[int, object] = {}
        with self._lock:
            for w, c in zip(uniq.tolist(), counts.tolist()):
                if w in self._cache:
                    self._cache.move_to_end(w)
                    need[w] = self._cache[w]
                    self.readahead_hits_total += c
                elif w in self._pending:
                    # in flight from an earlier prefetch/fetch: its rows
                    # were hidden up to now — hits, even if we block on
                    # the tail of the read below
                    need[w] = self._pending[w]
                    self.readahead_hits_total += c
                else:
                    self._dispatch_locked(w)
                    need[w] = self._pending[w]
                    # the windowed read amortizes: every requested row
                    # beyond the window's first rode along for free
                    self.readahead_hits_total += c - 1
        t0 = time.perf_counter()
        blocks = {}
        waited = False
        for w, src in need.items():
            if isinstance(src, np.ndarray):
                blocks[w] = src
            else:
                waited = True
                blocks[w] = src.result()  # raises if retries exhausted
        wait = time.perf_counter() - t0 if waited else 0.0
        self.stage_wait_total += wait
        self._observe("ooc.stage_wait", wait)
        with self._lock:
            for w in need:
                self._pending.pop(w, None)
                self._cache[w] = blocks[w]
                self._cache.move_to_end(w)
            while len(self._cache) > self.cache_windows:
                self._cache.popitem(last=False)
        self._publish_counters()
        out = None
        for w in blocks:
            sel = windows == w
            local = rows[sel] - w * self.window_rows
            part = blocks[w][local]
            if out is None:
                out = np.empty((rows.size,) + part.shape[1:], part.dtype)
            out[sel] = part
        return out

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Shut the worker down without joining an in-flight read (it
        finishes in the background and is dropped)."""
        self._pool.shutdown(wait=False, cancel_futures=True)

    def __enter__(self) -> "AsyncStager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
