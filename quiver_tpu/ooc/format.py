"""Raw on-disk format: per-array ``.npy`` files + CRC32 manifest.

The legacy ``CSRTopo.save`` writes one ``.npz`` — a zip container numpy
can only read by decompressing whole members into RAM, which is exactly
what an out-of-core load must not do. This module is the mmap-native
alternative: a DIRECTORY of uncompressed ``.npy`` files (each
``np.memmap``-able in place) described by a ``manifest.json`` carrying
per-array shape/dtype/CRC32/byte-span records, with the same durability
discipline as a checkpoint directory (``resilience/integrity.py``):

* every file lands in a same-filesystem temp directory first, fsynced;
* the ``COMMIT`` marker is written LAST inside the temp directory;
* one ``os.replace`` renames the directory into place — a reader that
  sees the final name sees a complete artifact, and a crash at any
  earlier point leaves only a skipped temp directory, never a torn one.

Verification is two-speed on purpose. :func:`load_raw_dir` always checks
structure (COMMIT marker, manifest format, every file present at its
exact manifested byte size) — O(1) per array. The full CRC32 sweep
(:func:`verify_raw_dir`) pages every byte in, which defeats the
O(touched-pages) residency an mmap load exists for — so mmap loads skip
it by default and eager loads run it; ``verify=`` overrides either way.
A dir that fails verification is renamed aside by
:func:`quarantine_raw_dir` (the checkpoint quarantine naming) so no
later load ever trusts it again.
"""

from __future__ import annotations

import json
import os
import shutil
import time

import numpy as np

from ..resilience.integrity import (
    COMMIT_NAME,
    MANIFEST_NAME,
    array_checksum,
    quarantine_name,
)

__all__ = [
    "RAW_FORMAT",
    "CorruptRawDir",
    "load_raw_dir",
    "npy_data_offset",
    "quarantine_raw_dir",
    "save_raw_dir",
    "verify_raw_dir",
]

RAW_FORMAT = "quiver-ooc-raw-v1"


class CorruptRawDir(ValueError):
    """A raw-format directory failed verification (missing COMMIT,
    unreadable manifest, file-size mismatch, or a CRC32 mismatch).
    Loaders treat this as "this artifact does not exist": quarantine the
    directory and fall back (e.g. to a legacy ``.npz``)."""


def npy_data_offset(path: str) -> tuple[tuple, np.dtype, int]:
    """Parse an ``.npy`` header: (shape, dtype, data byte offset).

    The offset is what windowed ``os.pread`` access needs to address row
    ranges without mapping the file; C order is required (every writer
    here emits C-contiguous arrays).
    """
    with open(path, "rb") as fh:
        version = np.lib.format.read_magic(fh)
        if version == (1, 0):
            shape, fortran, dtype = np.lib.format.read_array_header_1_0(fh)
        else:
            shape, fortran, dtype = np.lib.format.read_array_header_2_0(fh)
        if fortran:
            raise CorruptRawDir(
                f"{path}: Fortran-order .npy unsupported in the raw format"
            )
        return shape, dtype, fh.tell()


def _fsync_write_npy(path: str, arr: np.ndarray) -> None:
    with open(path, "wb") as fh:
        np.lib.format.write_array(
            fh, np.ascontiguousarray(arr), allow_pickle=False
        )
        fh.flush()
        os.fsync(fh.fileno())


def _fsync_write_text(path: str, text: str) -> None:
    with open(path, "w") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save_raw_dir(path: str, arrays: dict, meta: dict | None = None) -> dict:
    """Atomically publish ``arrays`` as a raw-format directory at ``path``.

    Each array lands as ``<name>.npy`` (uncompressed, C order, fsynced)
    with a manifest record ``{file, shape, dtype, nbytes, data_offset,
    crc32}``; ``meta`` rides the manifest uninterpreted. An existing
    directory at ``path`` is replaced atomically (rotated aside, the new
    directory renamed in, the old one removed). Returns the manifest.
    """
    path = os.path.normpath(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        records = {}
        for name, arr in arrays.items():
            arr = np.ascontiguousarray(arr)
            fname = f"{name}.npy"
            fpath = os.path.join(tmp, fname)
            _fsync_write_npy(fpath, arr)
            _, _, offset = npy_data_offset(fpath)
            records[name] = {
                "file": fname,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "nbytes": int(arr.nbytes),
                "data_offset": int(offset),
                "crc32": array_checksum(arr),
            }
        manifest = {
            "format": RAW_FORMAT,
            "arrays": records,
            "meta": dict(meta or {}),
        }
        _fsync_write_text(
            os.path.join(tmp, MANIFEST_NAME),
            json.dumps(manifest, indent=1, sort_keys=True),
        )
        _fsync_write_text(os.path.join(tmp, COMMIT_NAME), RAW_FORMAT + "\n")
        _fsync_dir(tmp)
        old = None
        if os.path.exists(path):
            old = f"{path}.old-{os.getpid()}"
            os.replace(path, old)
        os.replace(tmp, path)
        parent = os.path.dirname(path) or "."
        _fsync_dir(parent)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
        return manifest
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_manifest(path: str) -> dict:
    """Structural read of a raw dir's manifest: COMMIT marker present,
    manifest parses, format recognized. Raises :class:`CorruptRawDir`."""
    if not os.path.isdir(path):
        raise CorruptRawDir(f"{path}: not a raw-format directory")
    if not os.path.exists(os.path.join(path, COMMIT_NAME)):
        raise CorruptRawDir(
            f"{path}: no COMMIT marker (uncommitted/partial save)"
        )
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        raise CorruptRawDir(
            f"{path}: unreadable manifest ({type(e).__name__}: {e})"
        ) from None
    if manifest.get("format") != RAW_FORMAT:
        raise CorruptRawDir(
            f"{path}: unknown raw format {manifest.get('format')!r} "
            f"(expected {RAW_FORMAT!r})"
        )
    return manifest


def load_raw_dir(path: str, mmap: bool = True,
                 verify: bool | None = None) -> tuple[dict, dict]:
    """Load a raw-format directory; returns ``(arrays, meta)``.

    ``mmap=True`` backs every array onto a read-only ``np.memmap`` —
    resident bytes stay O(touched pages). Structure is ALWAYS checked
    (COMMIT, manifest, per-file byte size); the full CRC32 sweep runs
    when ``verify`` is True, or by default on eager (``mmap=False``)
    loads — an mmap load skips it because checksumming pages the whole
    file in, which is the cost this format exists to avoid. Any failure
    raises :class:`CorruptRawDir`.
    """
    manifest = load_manifest(path)
    if verify is None:
        verify = not mmap
    arrays = {}
    for name, rec in manifest["arrays"].items():
        fpath = os.path.join(path, rec["file"])
        try:
            size = os.path.getsize(fpath)
        except OSError:
            raise CorruptRawDir(
                f"{path}: missing array file {rec['file']!r}"
            ) from None
        expected = int(rec["data_offset"]) + int(rec["nbytes"])
        if size != expected:
            raise CorruptRawDir(
                f"{path}: {rec['file']} is {size} B, manifest covers "
                f"{expected} B (truncated or torn write)"
            )
        try:
            arr = np.load(fpath, mmap_mode="r" if mmap else None,
                          allow_pickle=False)
        except (OSError, ValueError) as e:
            raise CorruptRawDir(
                f"{path}: unreadable array {rec['file']!r} "
                f"({type(e).__name__}: {e})"
            ) from None
        if (list(arr.shape) != list(rec["shape"])
                or str(arr.dtype) != rec["dtype"]):
            raise CorruptRawDir(
                f"{path}: {rec['file']} header {arr.shape}/{arr.dtype} "
                f"disagrees with manifest {rec['shape']}/{rec['dtype']}"
            )
        if verify:
            crc = array_checksum(arr)
            if crc != int(rec["crc32"]):
                raise CorruptRawDir(
                    f"{path}: checksum mismatch on {rec['file']!r} "
                    f"(stored {rec['crc32']}, computed {crc})"
                )
        arrays[name] = arr
    return arrays, dict(manifest.get("meta", {}))


def verify_raw_dir(path: str) -> dict:
    """Full integrity sweep (structure + every CRC32); pages every byte
    in — the pre-trust check for chaos recovery and tests, not the hot
    load path. Returns the manifest; raises :class:`CorruptRawDir`."""
    load_raw_dir(path, mmap=False, verify=True)
    return load_manifest(path)


def quarantine_raw_dir(path: str) -> str:
    """Rename a corrupt raw dir aside (checkpoint quarantine naming) so
    no later load trusts it; returns the new path."""
    path = os.path.normpath(path)
    parent, name = os.path.split(path)
    dest = os.path.join(
        parent, quarantine_name(name, time.time_ns() // 1000)
    )
    os.replace(path, dest)
    return dest
