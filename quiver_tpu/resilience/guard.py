"""In-program non-finite step guard (trace-safe, mesh-agreed).

One NaN batch inside ``epoch_scan`` poisons params for the rest of the
epoch — and because the epoch is ONE compiled program, the host only finds
out after the full loss vector reads back. The guard runs inside the
traced step body: count non-finite loss/grad values per worker, psum the
count mesh-wide so every chip reaches the same verdict, and cond-skip the
optimizer update when any worker saw a non-finite value. The skipped
step's params/opt_state pass through bit-unchanged; the (NaN) loss still
lands in the trajectory so the skip is visible to the host.

Both cond branches return the same ``(params, opt_state)`` pytree — the
cond-branch-parity discipline graftlint enforces on the psum-fallback
conds (``parallel/routing.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

__all__ = ["nonfinite_count", "guard_verdict", "guarded_update"]


def nonfinite_count(tree) -> jnp.ndarray:
    """int32 scalar: number of non-finite elements across the inexact
    leaves of ``tree``. Integer leaves cannot hold non-finite values and
    contribute zero (dtype inspected at trace time — no host op on a
    tracer)."""
    total = jnp.zeros((), jnp.int32)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            total = total + jnp.sum(
                (~jnp.isfinite(leaf)).astype(jnp.int32)
            )
    return total


def guard_verdict(loss, grads, axes):
    """Mesh-agreed step verdict, computed BEFORE the gradient pmean (the
    pmean spreads one worker's NaN to every chip; counting pre-pmean
    attributes the fault to the workers that produced it).

    Returns ``(ok, local_bad)``: ``local_bad`` is this worker's int32
    non-finite count over ``(loss, grads)``; ``ok`` is True iff the psum
    of that count over ``axes`` is zero — every chip computes the same
    verdict, so the cond below takes the same branch mesh-wide.
    """
    local_bad = nonfinite_count((loss, grads))
    total_bad = jax.lax.psum(local_bad, axes)
    return total_bad == 0, local_bad


def guarded_update(tx, grads, opt_state, params, ok):
    """Cond-gated optimizer update: when ``ok`` is False the update is
    skipped and ``(params, opt_state)`` pass through bit-unchanged — the
    poisoned gradients never touch the optimizer. ``ok`` must be
    mesh-agreed (see :func:`guard_verdict`); a per-worker verdict would
    desync params across chips."""

    def apply_branch(operand):
        params, opt_state, grads = operand
        updates, new_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    def skip_branch(operand):
        params, opt_state, _ = operand
        return params, opt_state

    return jax.lax.cond(
        ok, apply_branch, skip_branch, (params, opt_state, grads)
    )
