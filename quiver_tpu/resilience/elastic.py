"""Elastic mesh resilience: continue a run on a different mesh shape, and
keep serving features when the cold tier is down.

Preemption at production scale routinely hands back a *smaller* slice than
the one that died (ROADMAP north star; resize-and-continue is table stakes
for scalable distributed GNN training — PAPERS.md, arxiv 2010.03166). Two
facts make a bit-identical resize possible here:

* PR 3's distributed sampler is bit-identical across topology shardings:
  per seed block and PRNG key, the owner-routed sample equals the
  replicated kernel's draw no matter how many shards answer it — so a
  block sampled on an F=4 mesh reproduces the F=8 run's block exactly.
* PR 6 pre-splits the epoch key stream globally (per-step keys are a
  function of key0 and the FULL step count), so resume boundaries cannot
  perturb the keys.

What remains mesh-shape-dependent is the *reduction order* of the
gradient/loss mean: ``pmean`` over 8 devices and ``pmean`` over 4 devices
of locally-presummed pairs associate differently and drift in the last
ulp. :func:`worker_ordered_mean` removes that dependence: per-block values
are ``all_gather``-ed into LOGICAL WORKER order (the gather axis is
device-major, blocks-minor — exactly ``worker = device * blocks_per_device
+ block``) and reduced in that fixed order, so the compiled reduction is
byte-for-byte the same computation at every mesh shape. The
``DistributedTrainer(logical_workers=)`` elastic mode builds its step on
this reduction; ``resume(mesh=)`` then re-plans ``ShardedTopology`` /
``ShardedFeature`` / the sampler onto the new mesh via their ``replan``
seams and the remaining trajectory stays bit-identical
(tests/test_resilience.py, benchmarks/chaos.py resize drill).

The degraded-mode feature store lives here too: :class:`CircuitBreaker` +
:class:`DegradedFeature` wrap host-side feature lookups (the Prefetcher /
DataParallel path, where a cold-tier outage — flaky storage, a dead host
— surfaces as raised lookups). Closed, failures propagate (bounded retry
upstream owns transients); after ``failures`` consecutive failures the
breaker opens and lookups serve a configurable fallback (zeros or
last-good rows) instead of crashing the epoch, counted on the graftscope
registry (``resilience.degraded_lookups``); half-open probes re-test the
real store and close the breaker when the outage ends.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..obs.registry import DEGRADED_LOOKUPS, MetricsRegistry
from ..utils.trace import get_logger

__all__ = [
    "CircuitBreaker",
    "DegradedFeature",
    "validate_resume_meta",
    "worker_ordered_mean",
]


def worker_ordered_mean(tree, axes, workers: int):
    """Mean over the logical-worker axis in a FIXED order — bitwise
    independent of how the workers map onto devices.

    Each leaf arrives as this device's ``(blocks_per_device, ...)`` stack
    of per-block values. ``all_gather`` over ``axes`` (major-to-minor in
    the mesh's axis order, matching the trainer's flat worker index)
    produces the ``(workers, ...)`` array in logical worker order on every
    device; the mean then reduces a tensor whose shape and content do not
    depend on the mesh shape, so an F=8 run and an F=4 run of the same
    logical workers produce bit-identical results. Call inside
    ``shard_map`` with ``axes`` built from the ``parallel/mesh`` axis
    constants.
    """

    def one(x):
        g = jax.lax.all_gather(x, axes)
        g = g.reshape((workers,) + tuple(x.shape[1:]))
        # explicit left-fold, NOT jnp.mean: XLA rewrites a reduce over an
        # all_gather'd axis into an all-reduce, whose reduction order is
        # topology-dependent — exactly the mesh-shape dependence this
        # function exists to remove. An unrolled chain of adds has one
        # fixed association and survives as-is in both programs.
        total = g[0]
        for i in range(1, workers):
            total = total + g[i]
        return total / workers

    return jax.tree_util.tree_map(one, tree)


def validate_resume_meta(meta: dict, *, mesh_shape: dict, workers: int,
                         local_batch: int) -> None:
    """Validate a checkpoint manifest's ``meta`` against the trainer that
    wants to restore it (the elastic-resume contract).

    Raises ``ValueError`` naming the first mismatch: the logical worker
    count and per-block batch size decide the seed packing and the
    per-block PRNG fold-in, so a mismatch would not crash — it would
    silently train a DIFFERENT run. Mesh-shape changes additionally
    require the writer to have been elastic (``logical_workers=``): a
    pmean-reduced trajectory is not reproducible on another shape.
    """
    saved_workers = meta.get("workers")
    if saved_workers is not None and int(saved_workers) != int(workers):
        raise ValueError(
            f"checkpoint was written with {saved_workers} logical workers, "
            f"this trainer runs {workers}; construct the trainer with "
            f"logical_workers={saved_workers} (seed packing and per-block "
            f"PRNG fold-in follow the logical worker count)"
        )
    saved_lb = meta.get("local_batch")
    if saved_lb is not None and int(saved_lb) != int(local_batch):
        raise ValueError(
            f"checkpoint was written with local_batch={saved_lb}, this "
            f"trainer uses {local_batch}; the per-block seed width must "
            f"match for the packed seed matrix to replay"
        )
    saved_mesh = meta.get("mesh")
    if saved_mesh is not None and dict(saved_mesh) != dict(mesh_shape):
        if not meta.get("elastic"):
            raise ValueError(
                f"checkpoint was written on mesh {dict(saved_mesh)} by a "
                f"NON-elastic trainer and cannot restore onto "
                f"{dict(mesh_shape)}: only the logical_workers= step "
                f"reduction is bit-reproducible across mesh shapes"
            )


class CircuitBreaker:
    """Consecutive-failure circuit breaker (closed -> open -> half-open).

    Deterministic by construction — state advances only on
    :meth:`record_success` / :meth:`record_failure` and the
    open -> half-open transition is COUNT-based (every ``probe_every``-th
    short-circuited call lets one probe through), so chaos drills replay
    exactly; no wall clock is consulted.

    States:
      * ``closed`` — every call attempts the real operation; failures
        count consecutively and propagate to the caller.
      * ``open`` — entered after ``failures`` consecutive failures (or a
        failed probe): calls are short-circuited to the fallback.
      * ``half-open`` — after ``probe_every`` short-circuited calls, one
        probe attempts the real operation: success closes the breaker,
        failure re-opens it.

    ``on_open`` (settable after construction) is called at every
    closed/half-open -> open transition — the flight-recorder trigger
    seam; exceptions it raises are swallowed (forensics must never make
    an outage worse).
    """

    def __init__(self, failures: int = 3, probe_every: int = 8,
                 on_open=None):
        if failures < 1 or probe_every < 1:
            raise ValueError(
                f"failures/probe_every must be >= 1, got "
                f"{failures}/{probe_every}"
            )
        self.failures = int(failures)
        self.probe_every = int(probe_every)
        self.on_open = on_open
        self.state = "closed"
        self._consecutive = 0
        self._since_probe = 0

    def allow(self) -> bool:
        """Should the caller attempt the real operation? Advances the
        open-state probe countdown (transitioning to ``half-open`` when a
        probe is due)."""
        if self.state == "closed" or self.state == "half-open":
            return True
        self._since_probe += 1
        if self._since_probe >= self.probe_every:
            self._since_probe = 0
            self.state = "half-open"
            return True
        return False

    def record_success(self) -> None:
        self._consecutive = 0
        if self.state != "closed":
            get_logger("resilience").info(
                "circuit breaker CLOSED (probe succeeded; outage over)"
            )
            self.state = "closed"

    def record_failure(self) -> None:
        self._consecutive += 1
        if self.state == "half-open" or (
            self.state == "closed" and self._consecutive >= self.failures
        ):
            get_logger("resilience").warning(
                "circuit breaker OPEN (%s) — serving fallback rows until "
                "a probe succeeds",
                "probe failed" if self.state == "half-open"
                else f"{self._consecutive} consecutive failures",
            )
            self.state = "open"
            self._since_probe = 0
            if self.on_open is not None:
                try:
                    self.on_open()
                except Exception:  # noqa: BLE001 — forensics must never
                    pass           # make the outage worse


class DegradedFeature:
    """Degraded-mode wrapper around a host feature-store lookup.

    Wraps anything ids->rows indexable (``Feature``, ``ShardedFeature``,
    a ``FaultPlan.wrap_feature`` product, …). While the breaker is
    closed, lookups pass through and failures propagate — the retrying
    Prefetcher upstream owns transients. Once ``failures`` consecutive
    lookups fail (a cold-tier OUTAGE, not a blip), the breaker opens and
    lookups serve ``fallback`` rows instead of raising, so the epoch
    keeps streaming; every degraded call is counted on the graftscope
    registry (``resilience.degraded_lookups``) and half-open probes close
    the breaker when the store recovers.

    Args:
      feature: the wrapped store (must expose ``shape`` ``(n, dim)``; a
        ``dtype`` / ``scale`` attribute refines the fallback row dtype).
      failures: consecutive-failure threshold that opens the breaker.
      probe_every: short-circuited calls between half-open probes.
      fallback: ``"zeros"`` (constant rows) or ``"last-good"`` (each id's
        most recently fetched rows from a bounded cache, zeros for ids
        never seen) — degraded accuracy either way, but a finished epoch.
      cache_rows: row budget of the last-good cache (insertion stops at
        the budget; ``"zeros"`` keeps no cache).
      metrics: optional external :class:`MetricsRegistry` to land the
        degraded counter on (e.g. a trainer's); a private one otherwise.
      recorder: optional :class:`~quiver_tpu.obs.recorder
        .FlightRecorder` — a breaker-open transition dumps a postmortem
        bundle naming the gather stage (the telemetry explaining the
        outage is captured at the moment it starts).
    """

    _FALLBACKS = ("zeros", "last-good")

    def __init__(self, feature, failures: int = 3, probe_every: int = 8,
                 fallback: str = "zeros", cache_rows: int = 65536,
                 metrics: MetricsRegistry | None = None, recorder=None):
        if fallback not in self._FALLBACKS:
            raise ValueError(
                f"fallback must be one of {self._FALLBACKS}, "
                f"got {fallback!r}"
            )
        self.feature = feature
        self.breaker = CircuitBreaker(failures, probe_every)
        if recorder is not None:
            self.breaker.on_open = lambda: recorder.trigger(
                "breaker_open", stage="gather", fallback=fallback,
            )
        self.fallback = fallback
        self.cache_rows = int(cache_rows)
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.metrics.counter(
            DEGRADED_LOOKUPS, unit="lookups",
            doc="feature lookups served by the circuit breaker's fallback "
                "(zeros/last-good) instead of the real store",
        )
        self.degraded_total = 0
        self._cache: dict[int, np.ndarray] = {}
        self._row_dtype = None

    def _row_spec(self):
        """(dim, dtype) of a fallback row — from the last good rows when
        seen, else from the wrapped store's declared shape/dtype (int8
        storage dequantizes to float32, the same rows the model sees)."""
        dim = int(self.feature.shape[1])
        if self._row_dtype is not None:
            return dim, self._row_dtype
        if getattr(self.feature, "scale", None) is not None:
            return dim, np.dtype(np.float32)
        dtype = getattr(self.feature, "dtype", None)
        return dim, np.dtype(dtype) if dtype is not None else np.dtype(
            np.float32
        )

    def _remember(self, ids: np.ndarray, rows: np.ndarray) -> None:
        if self.fallback != "last-good":
            return
        for i, row in zip(ids.tolist(), rows):
            if i < 0:
                continue
            if i in self._cache or len(self._cache) < self.cache_rows:
                self._cache[i] = np.array(row)

    def _serve_fallback(self, ids: np.ndarray):
        dim, dtype = self._row_spec()
        out = np.zeros((ids.shape[0], dim), dtype)
        if self.fallback == "last-good" and self._cache:
            for lane, i in enumerate(ids.tolist()):
                row = self._cache.get(i)
                if row is not None:
                    out[lane] = row
        self.degraded_total += 1
        self.metrics.set(DEGRADED_LOOKUPS, np.int32(self.degraded_total))
        return out

    def __getitem__(self, ids):
        ids_np = np.asarray(ids).reshape(-1)
        if self.breaker.allow():
            try:
                rows = np.asarray(self.feature[ids])
            except Exception:  # noqa: BLE001 — the breaker decides whether
                self.breaker.record_failure()  # this failure surfaces
                if self.breaker.state == "open":
                    return self._serve_fallback(ids_np)
                raise
            self.breaker.record_success()
            self._row_dtype = rows.dtype
            self._remember(ids_np, rows)
            return rows
        return self._serve_fallback(ids_np)

    def __getattr__(self, name):
        return getattr(self.feature, name)
