"""Deterministic fault-injection harness — the chaos substrate.

A :class:`FaultPlan` describes, ahead of time and reproducibly, every
fault a drill will inject:

* **NaN feature rows** (``nan_feature_steps``): the trainer poisons the
  first ``nan_rows`` rows of the gathered feature block *inside the
  compiled step* at the planned step indices — exactly the shape of a
  corrupt batch reaching the loss, which is what the non-finite guard
  must absorb.
* **Transient host faults** (``sampler_faults`` / ``feature_faults``):
  :meth:`wrap_sampler` / :meth:`wrap_feature` return wrappers that raise
  :class:`TransientFault` a planned number of times at planned batch
  indices, then succeed — the retrying :class:`~..parallel.pipeline.
  Prefetcher`'s test diet. Failed calls never touch the wrapped object,
  so the sampler's PRNG call order (and therefore the delivered batch
  stream) stays bit-identical to a fault-free run.
* **Simulated preemption** (``preempt_at_step``): the trainer raises
  :class:`Preemption` once the planned step has run but before its work
  is checkpointed — the checkpoint/auto-resume drill.

Plans are frozen; wrappers own all mutable retry state. Step indices mean
the ``epoch_scan`` row index (equivalently the eager ``step()`` call
count), and batch indices mean the dispatch count of the wrapped object.
:meth:`FaultPlan.chaos` derives a randomized-but-seeded plan for chaos
lanes (``benchmarks/chaos.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import numpy as np

__all__ = [
    "FaultPlan",
    "FaultySampler",
    "FaultyFeature",
    "Preemption",
    "TransientFault",
]


class TransientFault(RuntimeError):
    """Injected transient host-side failure (sampler/feature lookup)."""


class Preemption(RuntimeError):
    """Simulated preemption: the run dies at a planned step, after the
    step's work but before any checkpoint for it is written."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One deterministic fault schedule (see module docstring).

    Args:
      nan_feature_steps: step indices whose gathered features get NaN rows
        (in-program, via the trainer's ``fault_plan=`` knob).
      nan_rows: how many leading feature rows to poison per planned step.
      sampler_faults: ``{batch_index: consecutive_failures}`` for
        :meth:`wrap_sampler` — the batch fails that many times, then
        succeeds.
      feature_faults: same, for :meth:`wrap_feature` row lookups.
      preempt_at_step: step index at which the trainer raises
        :class:`Preemption` (once per trainer), or None.
      seed: recorded provenance for :meth:`chaos`-derived plans.
    """

    nan_feature_steps: tuple[int, ...] = ()
    nan_rows: int = 4
    sampler_faults: Mapping[int, int] = dataclasses.field(
        default_factory=dict
    )
    feature_faults: Mapping[int, int] = dataclasses.field(
        default_factory=dict
    )
    preempt_at_step: int | None = None
    seed: int = 0

    def __post_init__(self):
        if self.nan_rows < 1:
            raise ValueError(f"nan_rows must be >= 1, got {self.nan_rows}")
        for name in ("sampler_faults", "feature_faults"):
            for idx, n in getattr(self, name).items():
                if idx < 0 or n < 1:
                    raise ValueError(
                        f"{name}[{idx}] = {n}: batch indices must be >= 0 "
                        "and failure counts >= 1"
                    )

    @classmethod
    def chaos(cls, seed: int, steps: int, nan_p: float = 0.0,
              transient_p: float = 0.0, max_transient: int = 2,
              nan_rows: int = 4,
              preempt_at_step: int | None = None) -> "FaultPlan":
        """Derive a randomized plan from ``seed`` — same seed, same plan.
        ``nan_p``/``transient_p`` are per-step probabilities; transient
        faults draw 1..``max_transient`` consecutive failures."""
        rng = np.random.default_rng(seed)
        nan_steps = tuple(
            int(s) for s in np.nonzero(rng.random(steps) < nan_p)[0]
        )
        sampler_faults = {
            int(i): int(rng.integers(1, max_transient + 1))
            for i in np.nonzero(rng.random(steps) < transient_p)[0]
        }
        return cls(
            nan_feature_steps=nan_steps, nan_rows=nan_rows,
            sampler_faults=sampler_faults,
            preempt_at_step=preempt_at_step, seed=seed,
        )

    # -- step-indexed queries (trainer side) --------------------------------

    def injects_nan(self) -> bool:
        return bool(self.nan_feature_steps)

    def nan_at(self, step: int) -> bool:
        return step in self.nan_feature_steps

    def nan_mask(self, steps: int) -> np.ndarray:
        """bool (steps,) — True where the gathered features get poisoned;
        the per-step injection operand of the scanned epoch."""
        mask = np.zeros(steps, dtype=bool)
        for s in self.nan_feature_steps:
            if 0 <= s < steps:
                mask[s] = True
        return mask

    def preempts_in(self, lo: int, hi: int) -> bool:
        """True when the planned preemption step falls in ``[lo, hi)``."""
        return (self.preempt_at_step is not None
                and lo <= self.preempt_at_step < hi)

    # -- host-side wrappers (prefetcher / DataParallel side) ----------------

    def wrap_sampler(self, sampler) -> "FaultySampler":
        return FaultySampler(sampler, self.sampler_faults)

    def wrap_feature(self, feature) -> "FaultyFeature":
        return FaultyFeature(
            feature, self.feature_faults,
            nan_steps=self.nan_feature_steps, nan_rows=self.nan_rows,
        )


class _FaultBudget:
    """Mutable per-wrapper countdown of planned consecutive failures."""

    def __init__(self, faults: Mapping[int, int]):
        self._left = dict(faults)

    def check(self, idx: int, what: str) -> None:
        left = self._left.get(idx, 0)
        if left > 0:
            self._left[idx] = left - 1
            raise TransientFault(
                f"injected transient {what} failure at batch {idx} "
                f"({left - 1} more planned)"
            )


class FaultySampler:
    """Sampler wrapper: planned BATCHES raise :class:`TransientFault` the
    planned number of times, then succeed. A failed call never reaches the
    wrapped sampler, so its PRNG call order is preserved — the recovered
    stream is bit-identical to a fault-free one.

    Batch identity is the ``seeds`` object: a retry re-enters with the
    SAME array (the Prefetcher's contract), a new batch arrives with a new
    one — so the batch index stays correct even when a permanently-failing
    batch is dropped under ``skip_policy="skip"``."""

    def __init__(self, sampler, faults: Mapping[int, int]):
        self.sampler = sampler
        self._budget = _FaultBudget(faults)
        self._idx = 0
        self._last_seeds = None

    def sample(self, seeds):
        if self._last_seeds is not None and seeds is not self._last_seeds:
            self._idx += 1
        self._last_seeds = seeds
        self._budget.check(self._idx, "sampler")
        return self.sampler.sample(seeds)

    def __getattr__(self, name):
        return getattr(self.sampler, name)


class FaultyFeature:
    """Feature-store wrapper: planned LOOKUPS raise
    :class:`TransientFault` — ``{lookup_index: n}`` fails lookups
    ``index .. index+n-1`` (attempt-indexed: a retried feature fault
    re-enters the whole dispatch, re-drawing the sample, so batch
    identity is not stable here). Planned NaN steps poison the first
    ``nan_rows`` rows of the matching SUCCESSFUL lookup host-side — the
    unfused-path analogue of the trainer's in-program injection."""

    def __init__(self, feature, faults: Mapping[int, int],
                 nan_steps: tuple[int, ...] = (), nan_rows: int = 4):
        self.feature = feature
        self._fail_idx: set[int] = set()
        for i, n in faults.items():
            self._fail_idx.update(range(i, i + n))
        self._nan_steps = set(nan_steps)
        self._nan_rows = int(nan_rows)
        self._calls = 0
        self._ok = 0

    def __getitem__(self, ids):
        idx = self._calls
        self._calls += 1
        if idx in self._fail_idx:
            raise TransientFault(
                f"injected transient feature failure at lookup {idx}"
            )
        rows = self.feature[ids]
        if self._ok in self._nan_steps:
            rows = np.asarray(rows).copy()
            rows[: self._nan_rows] = np.nan
        self._ok += 1
        return rows

    def __getattr__(self, name):
        return getattr(self.feature, name)
