"""Checkpoint integrity: manifest schema, content checksums, verification.

A checkpoint directory written by :class:`~quiver_tpu.utils.checkpoint.
Checkpointer` is self-describing and tamper-evident:

* ``manifest.json`` — the mesh-agnostic description of the saved state:
  one record per pytree leaf (stable key path, global shape, dtype, byte
  offset into the payload, CRC32 content checksum), the checksum of the
  pickled tree structure, and free-form writer metadata (``meta``: the
  mesh shape, logical worker count, steps-per-epoch, … — what
  ``DistributedTrainer.resume`` validates before trusting the state).
* ``arrays.bin`` — every leaf's C-contiguous bytes, concatenated at the
  manifest's offsets. No sharding is baked in: leaves are saved as GLOBAL
  host arrays, so a restore can re-place them onto any mesh.
* ``treedef.pkl`` — a pickled *skeleton* pytree (the structure with leaf
  slots replaced by indices); untemplated restores rebuild the exact
  container types (tuples stay tuples — the scan carry's pytree
  discipline).
* ``COMMIT`` — the atomic durability marker. It is written LAST inside
  the temp directory, and the temp directory is then renamed into place
  in one ``os.replace``: a reader that sees the final name sees a
  complete checkpoint, and a crash at ANY earlier point leaves only a
  skipped temp directory — never a half-readable checkpoint.

:func:`verify_checkpoint_dir` re-derives every checksum and raises
:class:`CorruptCheckpoint` (with the first failing check named) on any
mismatch — the restore path quarantines such directories and falls back
to the newest valid one instead of resuming from garbage.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np

__all__ = [
    "ARRAYS_NAME",
    "COMMIT_NAME",
    "CorruptCheckpoint",
    "FORMAT",
    "MANIFEST_NAME",
    "TREEDEF_NAME",
    "array_checksum",
    "build_manifest",
    "load_manifest",
    "quarantine_name",
    "verify_checkpoint_dir",
]

FORMAT = "quiver-ckpt-v1"
MANIFEST_NAME = "manifest.json"
ARRAYS_NAME = "arrays.bin"
TREEDEF_NAME = "treedef.pkl"
COMMIT_NAME = "COMMIT"


class CorruptCheckpoint(RuntimeError):
    """A checkpoint directory failed integrity verification (missing
    COMMIT marker, unreadable manifest, payload size mismatch, or a
    content-checksum mismatch). The restore path treats this as "this
    checkpoint does not exist": quarantine and fall back."""


def array_checksum(arr: np.ndarray) -> int:
    """CRC32 of the array's C-order bytes (the manifest's per-leaf
    content checksum — cheap enough to verify on every restore)."""
    return zlib.crc32(np.asarray(arr).tobytes()) & 0xFFFFFFFF


def build_manifest(step: int, leaves: list[dict], treedef_crc: int,
                   meta: dict | None = None) -> dict:
    """Assemble the manifest dict for one checkpoint.

    ``leaves`` are per-leaf records ``{path, shape, dtype, offset, nbytes,
    crc32}`` in payload order; ``treedef_crc`` covers the pickled skeleton
    bytes; ``meta`` is the writer's free-form metadata (never interpreted
    here — :meth:`DistributedTrainer.resume` owns its semantics).
    """
    return {
        "format": FORMAT,
        "step": int(step),
        "leaves": list(leaves),
        "treedef_crc32": int(treedef_crc),
        "meta": dict(meta or {}),
    }


def load_manifest(path: str) -> dict:
    """Parse ``manifest.json`` under ``path``; raise
    :class:`CorruptCheckpoint` when missing, unparseable, or of an
    unknown format."""
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        raise CorruptCheckpoint(
            f"{path}: unreadable manifest ({type(e).__name__}: {e})"
        ) from None
    if manifest.get("format") != FORMAT:
        raise CorruptCheckpoint(
            f"{path}: unknown checkpoint format "
            f"{manifest.get('format')!r} (expected {FORMAT!r})"
        )
    return manifest


def verify_checkpoint_dir(path: str) -> dict:
    """Full integrity check of one checkpoint directory.

    Verifies, in order: the COMMIT marker exists, the manifest parses,
    the payload file has exactly the manifest's byte span, every leaf's
    CRC32 matches, and the pickled treedef's CRC32 matches. Returns the
    manifest on success; raises :class:`CorruptCheckpoint` naming the
    first failing check otherwise.
    """
    if not os.path.isdir(path):
        raise CorruptCheckpoint(f"{path}: not a checkpoint directory")
    if not os.path.exists(os.path.join(path, COMMIT_NAME)):
        raise CorruptCheckpoint(
            f"{path}: no COMMIT marker (uncommitted/partial save)"
        )
    manifest = load_manifest(path)
    apath = os.path.join(path, ARRAYS_NAME)
    try:
        with open(apath, "rb") as fh:
            payload = fh.read()
    except OSError as e:
        raise CorruptCheckpoint(f"{path}: unreadable payload ({e})") from None
    expected = sum(int(rec["nbytes"]) for rec in manifest["leaves"])
    if len(payload) != expected:
        raise CorruptCheckpoint(
            f"{path}: payload is {len(payload)} B, manifest covers "
            f"{expected} B"
        )
    for rec in manifest["leaves"]:
        off, n = int(rec["offset"]), int(rec["nbytes"])
        crc = zlib.crc32(payload[off:off + n]) & 0xFFFFFFFF
        if crc != int(rec["crc32"]):
            raise CorruptCheckpoint(
                f"{path}: checksum mismatch on leaf {rec['path']!r} "
                f"(stored {rec['crc32']}, computed {crc})"
            )
    tpath = os.path.join(path, TREEDEF_NAME)
    try:
        with open(tpath, "rb") as fh:
            tbytes = fh.read()
    except OSError as e:
        raise CorruptCheckpoint(f"{path}: unreadable treedef ({e})") from None
    tcrc = zlib.crc32(tbytes) & 0xFFFFFFFF
    if tcrc != int(manifest["treedef_crc32"]):
        raise CorruptCheckpoint(
            f"{path}: treedef checksum mismatch "
            f"(stored {manifest['treedef_crc32']}, computed {tcrc})"
        )
    return manifest


def quarantine_name(dirname: str, stamp: int) -> str:
    """Name a corrupt checkpoint directory is renamed to — prefixed so no
    step scan ever matches it again, stamped so repeated quarantines of
    same-named directories cannot collide."""
    return f"quarantine-{dirname}-{int(stamp)}"
