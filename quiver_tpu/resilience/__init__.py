"""Resilience layer — fault-tolerant training over the fused SPMD stack.

The reference ships zero fault tolerance (SURVEY §5: ``torch.save`` only
for preprocessing artifacts; a failed worker kills the ``mp.spawn`` run).
Long-running production training — the ROADMAP north star, and the
operating regime GNNSampler-style deployments assume (PAPERS.md, arxiv
2108.11571) — needs three distinct defenses, each living at the layer
where its fault class occurs:

* **In-program non-finite step guard** (``guard.py``): a NaN/Inf loss or
  gradient inside the compiled train step must not poison params.
  :func:`guard_verdict` counts non-finite values per worker and psums the
  verdict mesh-wide so every chip agrees; :func:`guarded_update`
  cond-skips the optimizer update (params/opt_state pass through
  bit-unchanged). Wired into ``DistributedTrainer(nonfinite_guard=True)``
  with skip/non-finite counters on the graftscope registry.
* **Checkpoint / auto-resume** (``parallel/trainer.py`` +
  ``utils/checkpoint.py``): ``DistributedTrainer(checkpoint_dir=,
  checkpoint_every=)`` saves (params, opt_state, step, PRNG key)
  asynchronously between scan chunks; :meth:`DistributedTrainer.resume`
  restores the latest state and the caller replays the packed seed
  stream from the saved step — the resumed loss trajectory is
  bit-identical to an uninterrupted run (tests/test_resilience.py).
* **Retrying prefetcher** (``parallel/pipeline.py``): host-side
  sample/gather/transform failures are transient (preempted host,
  flaky storage) — ``Prefetcher(retries=, backoff=, skip_policy=)``
  retries with exponential backoff + deterministic jitter and can
  skip-and-count a poisoned batch after retries exhaust.

The elastic layer (PR 7) extends the same defenses to faults that CHANGE
the world instead of leaving it intact:

* **Topology-portable, integrity-verified checkpoints**
  (``integrity.py`` + ``utils/checkpoint.py``): saves are atomic (temp
  dir + ``COMMIT`` marker + one rename) with a mesh-agnostic manifest of
  per-array checksums; restore quarantines corrupt/partial checkpoints
  and falls back to the newest valid one.
* **Elastic resume** (``elastic.py`` + ``DistributedTrainer(
  logical_workers=)`` / ``resume(mesh=)``): a run checkpointed at F=8
  continues at F=4 — :func:`worker_ordered_mean` makes the step reduction
  bitwise mesh-shape independent, and the sharded topology / three-tier
  feature store re-partition via their ``replan`` seams.
* **Degraded-mode feature serving** (``elastic.py``):
  :class:`CircuitBreaker` + :class:`DegradedFeature` turn a cold-tier
  OUTAGE into fallback rows (zeros/last-good) and a
  ``resilience.degraded_lookups`` counter instead of a dead epoch.

``faults.py`` is the test substrate proving all of the above: a seeded,
fully deterministic :class:`FaultPlan` that injects NaN rows into gathered
features (in-program, step-indexed), transient exceptions into host
sampler/feature lookups, and simulated preemption — reusable as a chaos
lane by benchmarks (``benchmarks/chaos.py``, the mega_session ``chaos``
stage).
"""

from .elastic import (
    CircuitBreaker,
    DegradedFeature,
    validate_resume_meta,
    worker_ordered_mean,
)
from .faults import (
    FaultPlan,
    FaultyFeature,
    FaultySampler,
    Preemption,
    TransientFault,
)
from .guard import guard_verdict, guarded_update, nonfinite_count
from .integrity import CorruptCheckpoint

__all__ = [
    "CircuitBreaker",
    "CorruptCheckpoint",
    "DegradedFeature",
    "FaultPlan",
    "FaultySampler",
    "FaultyFeature",
    "Preemption",
    "TransientFault",
    "guard_verdict",
    "guarded_update",
    "nonfinite_count",
    "validate_resume_meta",
    "worker_ordered_mean",
]
