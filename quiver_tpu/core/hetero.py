"""Heterogeneous graph topology (typed nodes, typed relations).

The reference has no heterogeneous support — its roadmap's R-GCN/MAG240M
configs (BASELINE.json config 5) imply it. quiver-tpu makes it first-class:
a ``HeteroCSRTopo`` holds one rectangular CSR per canonical relation
``(src_type, rel_name, dst_type)``, stored as *incoming* adjacency
(row = destination node, columns = source neighbors), because sampling
expands from seed/destination nodes toward message sources — the same
direction PyG's NeighborSampler walks.

Each relation's CSR reuses the homogeneous machinery (native linear-time
builder, DeviceTopology placement, padded sampling ops) — a relation is just
a rectangular graph whose rows live in the dst-type id space and whose
column values live in the src-type id space.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .config import SampleMode
from .memory import to_pinned_host
from .topology import DeviceTopology, _as_numpy, _build_csr

__all__ = ["RelCSR", "HeteroCSRTopo"]

EdgeType = tuple  # (src_type, rel_name, dst_type)


class RelCSR:
    """Rectangular CSR for one relation: rows = dst nodes, cols = src nodes.

    Unlike CSRTopo, column values index a *different* (src-type) id space,
    so the square-graph validation does not apply; ``src_node_count`` bounds
    them instead.
    """

    def __init__(self, indptr, indices, src_node_count: int, eid=None):
        self._indptr = indptr.astype(np.int64, copy=False)
        self._indices = indices
        self._eid = eid
        self.src_node_count = int(src_node_count)
        if indices.size and int(indices.max()) >= src_node_count:
            raise ValueError(
                f"relation references src node {int(indices.max())} but the "
                f"src type only has {src_node_count} nodes"
            )

    @classmethod
    def from_edge_index(cls, edge_index, num_dst: int, num_src: int,
                        use_native: bool = True) -> "RelCSR":
        """Build from (2, E) [src_ids, dst_ids] COO (PyG convention)."""
        edge_index = _as_numpy(edge_index)
        if edge_index.ndim != 2 or edge_index.shape[0] != 2:
            raise ValueError(f"edge_index must be (2, E), got {edge_index.shape}")
        src, dst = edge_index[0], edge_index[1]
        if edge_index.size:
            if src.min() < 0 or dst.min() < 0:
                raise ValueError("edge_index must not contain negative node ids")
            if int(dst.max()) >= num_dst:
                raise ValueError(
                    f"dst id {int(dst.max())} out of range for {num_dst} dst nodes"
                )
        # incoming CSR: row = dst, col = src. The native builder stores
        # column ids as int32, so it is only safe when the SRC id space fits
        # (the square-topology gate checks rows only).
        use_native = use_native and num_src <= np.iinfo(np.int32).max
        indptr, indices, eid = _build_csr(dst, src, num_dst, use_native)
        return cls(indptr, indices, num_src, eid)

    @property
    def indptr(self) -> np.ndarray:
        return self._indptr

    @property
    def indices(self) -> np.ndarray:
        return self._indices

    @property
    def node_count(self) -> int:
        """Destination-side node count (CSR row count)."""
        return int(self._indptr.shape[0] - 1)

    @property
    def edge_count(self) -> int:
        return int(self._indptr[-1])

    @property
    def degree(self) -> np.ndarray:
        """In-degree of each dst node under this relation."""
        return np.diff(self._indptr)

    @property
    def max_degree(self) -> int:
        return int(self.degree.max(initial=0))

    def to_device(self, mode: SampleMode | str = SampleMode.HBM) -> DeviceTopology:
        mode = SampleMode.parse(mode)
        indptr = jnp.asarray(self._indptr)
        host = False
        if mode == SampleMode.HOST:
            indices, host = to_pinned_host(self._indices)
        else:
            indices = jnp.asarray(self._indices)
        return DeviceTopology(indptr=indptr, indices=indices, host_indices=host)


class HeteroCSRTopo:
    """Typed multi-relation graph container.

    Args:
      num_nodes: {node_type: count}.
      edge_index_dict: {(src_type, rel_name, dst_type): (2, E) [src, dst]}.

    The per-relation CSRs are incoming (dst -> src neighbors); a sampler
    seeded with dst-type nodes draws the sources that message them.
    """

    def __init__(self, num_nodes: dict, edge_index_dict: dict,
                 use_native: bool = True):
        self.num_nodes = {str(t): int(n) for t, n in num_nodes.items()}
        self.relations: dict[EdgeType, RelCSR] = {}
        for etype, ei in edge_index_dict.items():
            if len(etype) != 3:
                raise ValueError(
                    f"edge type must be (src_type, rel, dst_type), got {etype!r}"
                )
            s, r, d = (str(t) for t in etype)
            if s not in self.num_nodes or d not in self.num_nodes:
                raise ValueError(f"unknown node type in relation {etype!r}")
            self.relations[(s, r, d)] = RelCSR.from_edge_index(
                ei, self.num_nodes[d], self.num_nodes[s], use_native
            )

    @property
    def node_types(self) -> list:
        return list(self.num_nodes)

    @property
    def edge_types(self) -> list:
        return list(self.relations)

    def rels_into(self, dst_type: str) -> list:
        """Relations whose destination is ``dst_type`` (sampling fan-in)."""
        return [et for et in self.relations if et[2] == dst_type]

    def __repr__(self):
        return (
            f"HeteroCSRTopo(nodes={self.num_nodes}, "
            f"relations={[f'{s}-{r}->{d}' for s, r, d in self.relations]})"
        )

    def to_device(self, mode: SampleMode | str = SampleMode.HBM) -> dict:
        return {et: rel.to_device(mode) for et, rel in self.relations.items()}
