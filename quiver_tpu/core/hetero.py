"""Heterogeneous graph topology (typed nodes, typed relations).

The reference has no heterogeneous support — its roadmap's R-GCN/MAG240M
configs (BASELINE.json config 5) imply it. quiver-tpu makes it first-class:
a ``HeteroCSRTopo`` holds one rectangular CSR per canonical relation
``(src_type, rel_name, dst_type)``, stored as *incoming* adjacency
(row = destination node, columns = source neighbors), because sampling
expands from seed/destination nodes toward message sources — the same
direction PyG's NeighborSampler walks.

Each relation's CSR reuses the homogeneous machinery (native linear-time
builder, DeviceTopology placement, padded sampling ops) — a relation is just
a rectangular graph whose rows live in the dst-type id space and whose
column values live in the src-type id space.
"""

from __future__ import annotations

import numpy as np

from .config import SampleMode
from .topology import (
    DeviceTopology,
    _as_numpy,
    _build_csr,
    _row_prefix_weights,
    place_csr_arrays,
)

__all__ = ["RelCSR", "HeteroCSRTopo"]

EdgeType = tuple  # (src_type, rel_name, dst_type)


class RelCSR:
    """Rectangular CSR for one relation: rows = dst nodes, cols = src nodes.

    Unlike CSRTopo, column values index a *different* (src-type) id space,
    so the square-graph validation does not apply; ``src_node_count`` bounds
    them instead.
    """

    def __init__(self, indptr, indices, src_node_count: int, eid=None):
        self._indptr = indptr.astype(np.int64, copy=False)
        self._indices = indices
        self._eid = eid
        self._edge_weight = None
        self._cum_weights = None
        self.src_node_count = int(src_node_count)
        if indices.size and int(indices.max()) >= src_node_count:
            raise ValueError(
                f"relation references src node {int(indices.max())} but the "
                f"src type only has {src_node_count} nodes"
            )

    @classmethod
    def from_edge_index(cls, edge_index, num_dst: int, num_src: int,
                        use_native: bool = True) -> "RelCSR":
        """Build from (2, E) [src_ids, dst_ids] COO (PyG convention)."""
        edge_index = _as_numpy(edge_index)
        if edge_index.ndim != 2 or edge_index.shape[0] != 2:
            raise ValueError(f"edge_index must be (2, E), got {edge_index.shape}")
        src, dst = edge_index[0], edge_index[1]
        if edge_index.size:
            if src.min() < 0 or dst.min() < 0:
                raise ValueError("edge_index must not contain negative node ids")
            if int(dst.max()) >= num_dst:
                raise ValueError(
                    f"dst id {int(dst.max())} out of range for {num_dst} dst nodes"
                )
        # incoming CSR: row = dst, col = src. The native builder stores
        # column ids as int32, so it is only safe when the SRC id space fits
        # (the square-topology gate checks rows only).
        use_native = use_native and num_src <= np.iinfo(np.int32).max
        indptr, indices, eid = _build_csr(dst, src, num_dst, use_native)
        return cls(indptr, indices, num_src, eid)

    @property
    def indptr(self) -> np.ndarray:
        return self._indptr

    @property
    def indices(self) -> np.ndarray:
        return self._indices

    @property
    def node_count(self) -> int:
        """Destination-side node count (CSR row count)."""
        return int(self._indptr.shape[0] - 1)

    @property
    def edge_count(self) -> int:
        return int(self._indptr[-1])

    @property
    def degree(self) -> np.ndarray:
        """In-degree of each dst node under this relation."""
        return np.diff(self._indptr)

    @property
    def max_degree(self) -> int:
        return int(self.degree.max(initial=0))

    @property
    def eid(self) -> np.ndarray | None:
        """CSR slot -> original COO edge position (None for direct builds)."""
        return self._eid

    # -- edge weights (weighted per-relation sampling) ----------------------

    def set_edge_weight(self, edge_weight, coo_order: bool = True) -> "RelCSR":
        """Attach per-edge weights (same contract as CSRTopo.set_edge_weight:
        ``coo_order=True`` aligns with the COO build order via ``eid``)."""
        w = _as_numpy(edge_weight).astype(np.float64, copy=False).reshape(-1)
        if w.shape[0] != self.edge_count:
            raise ValueError(
                f"edge_weight must have {self.edge_count} entries, got {w.shape[0]}"
            )
        if w.size and not (np.isfinite(w).all() and w.min() >= 0):
            raise ValueError("edge weights must be finite and non-negative")
        if coo_order and self._eid is not None:
            w = w[self._eid]
        self._edge_weight = w.astype(np.float32)
        self._cum_weights = _row_prefix_weights(w, self._indptr)
        return self

    @property
    def edge_weight(self) -> np.ndarray | None:
        return self._edge_weight

    @property
    def cum_weights(self) -> np.ndarray | None:
        return self._cum_weights

    def to_device(self, mode: SampleMode | str = SampleMode.HBM,
                  with_eid: bool = False,
                  with_weights: bool = False) -> DeviceTopology:
        """Place the relation for sampling — shares CSRTopo's placement
        logic (place_csr_arrays): HOST mode keeps the large per-edge arrays
        (indices/eid/cum_weights) in pinned host memory."""
        if with_weights and self._cum_weights is None:
            raise ValueError(
                "weighted sampling requires edge weights; call "
                "set_edge_weight() on this relation first"
            )
        return place_csr_arrays(
            self._indptr, self._indices,
            self._eid if with_eid else None,
            self._cum_weights if with_weights else None,
            self.max_degree, mode,
        )


class HeteroCSRTopo:
    """Typed multi-relation graph container.

    Args:
      num_nodes: {node_type: count}.
      edge_index_dict: {(src_type, rel_name, dst_type): (2, E) [src, dst]}.

    The per-relation CSRs are incoming (dst -> src neighbors); a sampler
    seeded with dst-type nodes draws the sources that message them.
    """

    def __init__(self, num_nodes: dict, edge_index_dict: dict,
                 use_native: bool = True, edge_weight_dict: dict | None = None):
        self.num_nodes = {str(t): int(n) for t, n in num_nodes.items()}
        self.relations: dict[EdgeType, RelCSR] = {}
        for etype, ei in edge_index_dict.items():
            if len(etype) != 3:
                raise ValueError(
                    f"edge type must be (src_type, rel, dst_type), got {etype!r}"
                )
            s, r, d = (str(t) for t in etype)
            if s not in self.num_nodes or d not in self.num_nodes:
                raise ValueError(f"unknown node type in relation {etype!r}")
            self.relations[(s, r, d)] = RelCSR.from_edge_index(
                ei, self.num_nodes[d], self.num_nodes[s], use_native
            )
        for etype, w in (edge_weight_dict or {}).items():
            self.set_edge_weight(etype, w)

    def set_edge_weight(self, edge_type, edge_weight,
                        coo_order: bool = True) -> "HeteroCSRTopo":
        """Attach per-edge weights to one relation (COO order by default)."""
        et = tuple(str(t) for t in edge_type)
        if et not in self.relations:
            raise ValueError(f"unknown relation {edge_type!r}")
        self.relations[et].set_edge_weight(edge_weight, coo_order)
        return self

    @property
    def weighted_edge_types(self) -> list:
        return [et for et, rel in self.relations.items()
                if rel.cum_weights is not None]

    @property
    def node_types(self) -> list:
        return list(self.num_nodes)

    @property
    def edge_types(self) -> list:
        return list(self.relations)

    def rels_into(self, dst_type: str) -> list:
        """Relations whose destination is ``dst_type`` (sampling fan-in)."""
        return [et for et in self.relations if et[2] == dst_type]

    def __repr__(self):
        return (
            f"HeteroCSRTopo(nodes={self.num_nodes}, "
            f"relations={[f'{s}-{r}->{d}' for s, r, d in self.relations]})"
        )

    def to_device(self, mode: SampleMode | str = SampleMode.HBM,
                  with_eid: bool = False, weighted_rels=()) -> dict:
        weighted_rels = {tuple(et) for et in weighted_rels}
        unknown = weighted_rels - set(self.relations)
        if unknown:
            raise ValueError(f"unknown weighted relations: {unknown}")
        return {
            et: rel.to_device(mode, with_eid=with_eid,
                              with_weights=et in weighted_rels)
            for et, rel in self.relations.items()
        }
