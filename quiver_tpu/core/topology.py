"""Graph topology containers.

``CSRTopo`` is the host-side CSR graph container, capability-parity with the
reference's ``quiver.CSRTopo`` (torch-quiver utils.py:117-210): build from COO
``edge_index`` or from ``indptr``/``indices``, expose ``degree``/``eid``/
``feature_order``. Construction is pure numpy (no scipy needed — a stable
argsort plus bincount replaces the reference's ``scipy.sparse.csr_matrix``
round-trip, utils.py:107-114).

``DeviceTopology`` is the device-side view: a pytree of jnp arrays placed in
HBM (reference "GPU" mode) or pinned host memory (the TPU stand-in for the
reference's UVA zero-copy registration, quiver_sample.cu:400-408).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .config import SampleMode
from .memory import to_pinned_host

__all__ = ["CSRTopo", "DeviceTopology"]


def _as_numpy(x) -> np.ndarray:
    """Coerce array-likes (numpy, lists, torch CPU tensors) to numpy."""
    if hasattr(x, "detach"):  # torch tensor without importing torch
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _index_dtype(max_value: int) -> np.dtype:
    return np.dtype(np.int32) if max_value <= np.iinfo(np.int32).max else np.dtype(np.int64)


def _build_csr(row, col, node_count: int, use_native: bool):
    """COO -> CSR. Prefers the native linear-time parallel builder
    (native/quiver_host.cpp csr_from_coo); falls back to numpy stable
    argsort. Both are stable (CSR slots within a row follow COO order), so
    the two paths — and independent builds on different hosts — produce
    identical indices/eid arrays."""
    if use_native and node_count <= np.iinfo(np.int32).max:
        try:
            from ..native import available, csr_from_coo
        except ImportError:
            available = False
        if available:
            # real failures inside the native builder must propagate, not
            # silently fall back
            return csr_from_coo(row, col, node_count)
    order = np.argsort(row, kind="stable")
    counts = np.bincount(row, minlength=node_count)
    indptr = np.zeros(node_count + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return indptr, np.ascontiguousarray(col[order]), order


class CSRTopo:
    """CSR graph topology with degree and feature-order bookkeeping.

    Parameters mirror the reference: either ``edge_index`` (2, E) COO, or
    ``indptr`` + ``indices`` directly. ``eid`` maps CSR edge slots back to
    the original COO edge positions (identity when built from indptr/indices).
    """

    def __init__(self, edge_index=None, indptr=None, indices=None, eid=None,
                 use_native: bool = True):
        if edge_index is not None:
            if indptr is not None or indices is not None:
                raise ValueError("pass either edge_index or indptr/indices, not both")
            edge_index = _as_numpy(edge_index)
            if edge_index.ndim != 2 or edge_index.shape[0] != 2:
                raise ValueError(f"edge_index must be (2, E), got {edge_index.shape}")
            row, col = edge_index[0], edge_index[1]
            if edge_index.size and min(row.min(), col.min()) < 0:
                # the native builder indexes raw ids; a stray -1 sentinel
                # must fail loudly here, not corrupt memory there
                raise ValueError("edge_index must not contain negative node ids")
            node_count = int(max(row.max(initial=-1), col.max(initial=-1)) + 1)
            indptr, indices, eid = _build_csr(row, col, node_count, use_native)
        elif indptr is not None and indices is not None:
            indptr = _as_numpy(indptr).astype(np.int64, copy=False)
            indices = _as_numpy(indices)
            if eid is not None:
                eid = _as_numpy(eid)
            # user-supplied CSR: validate, because XLA's clamping gathers
            # would otherwise turn inconsistencies into silently wrong samples
            if indptr.ndim != 1 or indptr.shape[0] < 1 or indptr[0] != 0:
                raise ValueError("indptr must be 1-D and start at 0")
            if np.any(np.diff(indptr) < 0):
                raise ValueError("indptr must be non-decreasing")
            if int(indptr[-1]) != indices.shape[0]:
                raise ValueError(
                    f"indptr[-1]={int(indptr[-1])} != len(indices)={indices.shape[0]}"
                )
        else:
            raise ValueError("need edge_index or indptr+indices")

        node_count = int(indptr.shape[0] - 1)
        if indices.size and int(indices.max()) >= node_count:
            raise ValueError(
                f"indices reference node {int(indices.max())} but indptr only "
                f"defines {node_count} nodes"
            )
        edge_count = int(indptr[-1])
        self._indptr = indptr.astype(_index_dtype(edge_count), copy=False)
        self._indices = indices.astype(_index_dtype(max(node_count - 1, 0)), copy=False)
        self._eid = None if eid is None else eid.astype(_index_dtype(max(edge_count - 1, 0)), copy=False)
        self._feature_order = None  # set by Feature's degree reorder

    # -- properties (parity with reference utils.py:150-210) ---------------

    @property
    def indptr(self) -> np.ndarray:
        return self._indptr

    @property
    def indices(self) -> np.ndarray:
        return self._indices

    @property
    def eid(self) -> np.ndarray | None:
        return self._eid

    @property
    def feature_order(self) -> np.ndarray | None:
        """Old-node-id -> reordered-feature-row map, shared with Feature."""
        return self._feature_order

    @feature_order.setter
    def feature_order(self, order):
        order = _as_numpy(order)
        if order.shape != (self.node_count,):
            raise ValueError(
                f"feature_order must have shape ({self.node_count},), got {order.shape}"
            )
        self._feature_order = order

    @property
    def degree(self) -> np.ndarray:
        return np.diff(self._indptr)

    @property
    def max_degree(self) -> int:
        return int(self.degree.max(initial=0))

    @property
    def node_count(self) -> int:
        return int(self._indptr.shape[0] - 1)

    @property
    def edge_count(self) -> int:
        return int(self._indptr[-1])

    def __repr__(self):
        return f"CSRTopo(nodes={self.node_count}, edges={self.edge_count})"

    # -- device placement ---------------------------------------------------

    def to_device(self, mode: SampleMode | str = SampleMode.HBM, with_eid: bool = False) -> "DeviceTopology":
        """Place the topology for sampling.

        HBM mode puts everything in device memory. HOST mode keeps the large
        ``indices`` (and ``eid``) arrays in pinned host memory where supported
        — on platforms without a pinned_host memory space it degrades to HBM
        with a warning-free fallback (CPU tests take this path).
        """
        mode = SampleMode.parse(mode)
        indptr = jnp.asarray(self._indptr)
        eid = jnp.asarray(self._eid) if (with_eid and self._eid is not None) else None
        host = False
        if mode == SampleMode.HOST:
            indices, host = to_pinned_host(self._indices)
            if eid is not None and host:
                eid, _ = to_pinned_host(self._eid)
        else:
            indices = jnp.asarray(self._indices)
        return DeviceTopology(indptr=indptr, indices=indices, eid=eid, host_indices=host)


@jax.tree_util.register_pytree_node_class
class DeviceTopology:
    """Device-resident CSR arrays, usable inside jit as a pytree.

    ``host_indices`` is static metadata: True when ``indices``/``eid`` live in
    pinned host memory (HOST mode) so gathers must stage through host compute.
    """

    def __init__(self, indptr, indices, eid=None, host_indices: bool = False):
        self.indptr = indptr
        self.indices = indices
        self.eid = eid
        self.host_indices = host_indices

    @property
    def node_count(self) -> int:
        return self.indptr.shape[0] - 1

    @property
    def edge_count(self) -> int:
        return self.indices.shape[0]

    def tree_flatten(self):
        if self.eid is None:
            return (self.indptr, self.indices), ("no_eid", self.host_indices)
        return (self.indptr, self.indices, self.eid), ("eid", self.host_indices)

    @classmethod
    def tree_unflatten(cls, aux, children):
        eid = children[2] if aux[0] == "eid" else None
        return cls(children[0], children[1], eid, host_indices=aux[1])
