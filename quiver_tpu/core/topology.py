"""Graph topology containers.

``CSRTopo`` is the host-side CSR graph container, capability-parity with the
reference's ``quiver.CSRTopo`` (torch-quiver utils.py:117-210): build from COO
``edge_index`` or from ``indptr``/``indices``, expose ``degree``/``eid``/
``feature_order``. Construction is pure numpy (no scipy needed — a stable
argsort plus bincount replaces the reference's ``scipy.sparse.csr_matrix``
round-trip, utils.py:107-114).

``DeviceTopology`` is the device-side view: a pytree of jnp arrays placed in
HBM (reference "GPU" mode) or pinned host memory (the TPU stand-in for the
reference's UVA zero-copy registration, quiver_sample.cu:400-408).
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

from .config import SampleMode
from .memory import to_pinned_host

__all__ = ["CSRTopo", "DeviceTopology", "VersionMismatchError"]


class VersionMismatchError(RuntimeError):
    """A consumer holds a placement of graph state (device CSR partition,
    feature tiers, a trainer's captured operands) whose ``version`` no
    longer matches the committed host state — a streaming mutation
    (``quiver_tpu.streaming``) published a new version since the placement
    was built. Raised instead of serving a silently stale read; call the
    consumer's ``refresh``/``refresh_topology`` seam to re-place."""


def _boundary_checks_enabled() -> bool:
    """O(E)/O(n) construction-boundary scans (index ranges, indptr
    monotonicity) run by DEFAULT — a corrupt CSR reaching XLA's clamping
    gathers turns into silently wrong samples, which is far worse than the
    scan. ``QUIVER_CHECK=0`` opts out for huge graphs on a hot rebuild
    path. (Asymmetric with models/layers: the *debug* trace assertions
    there default OFF; these *boundary* validations default ON. Host-side
    eager code — never trace-resident, so the env read per construction is
    trace-safe.)"""
    return os.environ.get("QUIVER_CHECK", "1") not in ("0", "false", "False")


def _as_numpy(x) -> np.ndarray:
    """Coerce array-likes (numpy, lists, torch CPU tensors) to numpy."""
    if hasattr(x, "detach"):  # torch tensor without importing torch
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _index_dtype(max_value: int) -> np.dtype:
    return np.dtype(np.int32) if max_value <= np.iinfo(np.int32).max else np.dtype(np.int64)


def _build_csr(row, col, node_count: int, use_native: bool):
    """COO -> CSR. Prefers the native linear-time parallel builder
    (native/quiver_host.cpp csr_from_coo); falls back to numpy stable
    argsort. Both are stable (CSR slots within a row follow COO order), so
    the two paths — and independent builds on different hosts — produce
    identical indices/eid arrays."""
    if use_native and node_count <= np.iinfo(np.int32).max:
        try:
            from ..native import available, csr_from_coo
        except ImportError:
            available = False
        if available:
            # real failures inside the native builder must propagate, not
            # silently fall back
            return csr_from_coo(row, col, node_count)
    order = np.argsort(row, kind="stable")
    counts = np.bincount(row, minlength=node_count)
    indptr = np.zeros(node_count + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return indptr, np.ascontiguousarray(col[order]), order


def _row_prefix_weights(w: np.ndarray, indptr: np.ndarray) -> np.ndarray:
    """Row-local inclusive prefix sums of CSR-ordered edge weights.

    The device-side weighted sampler inverse-CDF-searches these per row
    (the TPU analogue of the reference's per-node normalized prefix weights,
    cuda_random.cu.hpp:160-170). Rows whose total weight is <= 0 get the
    uniform prefix 1..deg so they degrade to uniform sampling instead of NaN.
    Computed in float64 (a global cumsum over E edges), emitted float32
    (row-local magnitudes only).
    """
    E = int(w.shape[0])
    deg = np.diff(indptr).astype(np.int64)
    starts = np.repeat(indptr[:-1].astype(np.int64), deg)  # row start per edge
    cw = np.cumsum(w, dtype=np.float64)
    base = np.where(starts > 0, cw[np.maximum(starts - 1, 0)], 0.0)
    prefix = cw - base
    ends = indptr[1:].astype(np.int64) - 1
    tot = np.where(deg > 0, prefix[np.maximum(ends, 0)], 0.0)
    bad = np.repeat(tot <= 0, deg)
    if bad.any():
        local = np.arange(E, dtype=np.int64) - starts
        prefix[bad] = (local[bad] + 1).astype(np.float64)
    return prefix.astype(np.float32)


def _time_sort_order(indptr: np.ndarray, times: np.ndarray) -> np.ndarray:
    """Permutation that stably sorts each CSR row's edges by timestamp.

    The temporal sampler binary-searches a ``[lo, hi]`` window per row, so
    rows must be time-nondecreasing; stability keeps the original CSR slot
    order as the tiebreak, which is what makes independently built
    replicated and sharded placements bitwise identical."""
    deg = np.diff(indptr).astype(np.int64)
    rows = np.repeat(np.arange(deg.shape[0], dtype=np.int64), deg)
    # lexsort: last key (rows) is primary, stable on equal (row, time) pairs
    return np.lexsort((times, rows))


class CSRTopo:
    """CSR graph topology with degree and feature-order bookkeeping.

    Parameters mirror the reference: either ``edge_index`` (2, E) COO, or
    ``indptr`` + ``indices`` directly. ``eid`` maps CSR edge slots back to
    the original COO edge positions (identity when built from indptr/indices).
    """

    def __init__(self, edge_index=None, indptr=None, indices=None, eid=None,
                 edge_weight=None, edge_time=None, use_native: bool = True):
        if edge_index is not None:
            if indptr is not None or indices is not None:
                raise ValueError("pass either edge_index or indptr/indices, not both")
            edge_index = _as_numpy(edge_index)
            if edge_index.ndim != 2 or edge_index.shape[0] != 2:
                raise ValueError(f"edge_index must be (2, E), got {edge_index.shape}")
            row, col = edge_index[0], edge_index[1]
            if edge_index.size and min(row.min(), col.min()) < 0:
                # the native builder indexes raw ids; a stray -1 sentinel
                # must fail loudly here, not corrupt memory there
                raise ValueError("edge_index must not contain negative node ids")
            node_count = int(max(row.max(initial=-1), col.max(initial=-1)) + 1)
            indptr, indices, eid = _build_csr(row, col, node_count, use_native)
        elif indptr is not None and indices is not None:
            indptr = _as_numpy(indptr).astype(np.int64, copy=False)
            indices = _as_numpy(indices)
            if eid is not None:
                eid = _as_numpy(eid)
            # user-supplied CSR: validate, because XLA's clamping gathers
            # would otherwise turn inconsistencies into silently wrong samples
            if indptr.ndim != 1 or indptr.shape[0] < 1 or indptr[0] != 0:
                raise ValueError("indptr must be 1-D and start at 0")
            if indices.ndim != 1:
                raise ValueError(
                    f"indices must be 1-D, got shape {indices.shape}"
                )
            if _boundary_checks_enabled() and np.any(np.diff(indptr) < 0):
                raise ValueError("indptr must be non-decreasing")
            if int(indptr[-1]) != indices.shape[0]:
                raise ValueError(
                    f"indptr[-1]={int(indptr[-1])} != len(indices)={indices.shape[0]}"
                )
        else:
            raise ValueError("need edge_index or indptr+indices")

        node_count = int(indptr.shape[0] - 1)
        if indices.size and _boundary_checks_enabled():
            lo, hi = int(indices.min()), int(indices.max())
            if lo < 0:
                raise ValueError(
                    f"indices contain negative node id {lo}; CSR neighbor "
                    f"slots must reference nodes in [0, {node_count})"
                )
            if hi >= node_count:
                raise ValueError(
                    f"indices reference node {hi} but indptr only "
                    f"defines {node_count} nodes"
                )
        edge_count = int(indptr[-1])
        self._indptr = indptr.astype(_index_dtype(edge_count), copy=False)
        self._indices = indices.astype(_index_dtype(max(node_count - 1, 0)), copy=False)
        self._eid = None if eid is None else eid.astype(_index_dtype(max(edge_count - 1, 0)), copy=False)
        self._feature_order = None  # set by Feature's degree reorder
        self._edge_weight = None
        self._cum_weights = None
        self._edge_time = None
        self._max_degree = None  # lazy cache (manifest-seeded on raw loads)
        # streaming-mutation version: bumped ONCE per committed transaction
        # (quiver_tpu.streaming); device placements capture the version they
        # were built from and raise VersionMismatchError instead of serving
        # a stale partition after a commit
        self._version = 0
        if edge_weight is not None:
            self.set_edge_weight(edge_weight, coo_order=edge_index is not None)
        if edge_time is not None:
            self.set_edge_time(edge_time, coo_order=edge_index is not None)

    # -- properties (parity with reference utils.py:150-210) ---------------

    @property
    def indptr(self) -> np.ndarray:
        return self._indptr

    @property
    def indices(self) -> np.ndarray:
        return self._indices

    @property
    def eid(self) -> np.ndarray | None:
        return self._eid

    @property
    def feature_order(self) -> np.ndarray | None:
        """Old-node-id -> reordered-feature-row map, shared with Feature."""
        return self._feature_order

    @feature_order.setter
    def feature_order(self, order):
        order = _as_numpy(order)
        if order.shape != (self.node_count,):
            raise ValueError(
                f"feature_order must have shape ({self.node_count},), got {order.shape}"
            )
        self._feature_order = order

    # -- edge weights (weighted sampling) -----------------------------------
    # The reference *plumbed* per-edge weights (inverse-CDF ``weight_sample``,
    # cuda_random.cu.hpp:143-186) but the weighted constructor is commented
    # out (quiver.cu.hpp:240-272), leaving the path unreachable. Here it is a
    # real, tested feature.

    def set_edge_weight(self, edge_weight, coo_order: bool = True) -> "CSRTopo":
        """Attach per-edge weights for weighted neighbor sampling.

        ``coo_order=True`` means weights align with the COO edge order this
        topology was built from (translated through ``eid``); otherwise they
        are taken to already be in CSR slot order.
        """
        w = _as_numpy(edge_weight).astype(np.float64, copy=False).reshape(-1)
        if w.shape[0] != self.edge_count:
            raise ValueError(
                f"edge_weight must have {self.edge_count} entries, got {w.shape[0]}"
            )
        if w.size and not (np.isfinite(w).all() and w.min() >= 0):
            # NaN is < 0-blind and would silently degenerate the CDF search
            raise ValueError("edge weights must be finite and non-negative")
        if coo_order and self._eid is not None:
            w = w[self._eid]
        self._edge_weight = w.astype(np.float32)
        self._cum_weights = _row_prefix_weights(w, self._indptr)
        return self

    @property
    def edge_weight(self) -> np.ndarray | None:
        """Per-edge weights in CSR slot order, or None if unweighted."""
        return self._edge_weight

    @property
    def cum_weights(self) -> np.ndarray | None:
        """Row-local inclusive prefix sums of edge weights (float32, CSR
        order); rows with non-positive total weight fall back to the uniform
        prefix 1..deg."""
        return self._cum_weights

    # -- edge timestamps (temporal sampling) ---------------------------------

    def set_edge_time(self, edge_time, coo_order: bool = True) -> "CSRTopo":
        """Attach per-edge timestamps for temporal (time-windowed) sampling.

        Each row's edges are stably re-sorted time-nondecreasing (``eid``
        and ``edge_weight`` follow the permutation; the weight prefix sums
        re-derive), so the sampler can binary-search a ``[lo, hi]`` window
        to a contiguous slot range per row. The re-sort changes CSR slot
        order — attach timestamps BEFORE building samplers or device
        placements. ``coo_order=True`` means timestamps align with the COO
        edge order this topology was built from (translated through
        ``eid``); otherwise they are taken in CSR slot order.
        """
        t = _as_numpy(edge_time).astype(np.float64, copy=False).reshape(-1)
        if t.shape[0] != self.edge_count:
            raise ValueError(
                f"edge_time must have {self.edge_count} entries, got {t.shape[0]}"
            )
        if t.size and not np.isfinite(t).all():
            # NaN compares false everywhere and would silently empty or
            # corrupt every window search
            raise ValueError("edge times must be finite")
        if coo_order and self._eid is not None:
            t = t[self._eid]
        t = t.astype(np.float32)
        order = _time_sort_order(self._indptr, t)
        self._indices = self._indices[order]
        self._edge_time = t[order]
        if self._eid is not None:
            self._eid = self._eid[order]
        if self._edge_weight is not None:
            self._edge_weight = self._edge_weight[order]
            self._cum_weights = _row_prefix_weights(
                self._edge_weight, self._indptr
            )
        return self

    @property
    def edge_time(self) -> np.ndarray | None:
        """Per-edge timestamps in CSR slot order (float32, rows sorted
        time-nondecreasing), or None if untimestamped."""
        return self._edge_time

    @property
    def version(self) -> int:
        """Committed mutation version (0 for a freshly built topology;
        +1 per published ``quiver_tpu.streaming`` commit). Consumers
        compare their placed version against this to detect staleness."""
        return self._version

    def _publish_mutation(self, indptr: np.ndarray, indices: np.ndarray,
                          edge_weight: np.ndarray | None = None,
                          edge_time: np.ndarray | None = None) -> None:
        """Streaming-commit publish seam (``quiver_tpu.streaming`` only):
        swap in the merged, already-VERIFIED CSR arrays and bump the
        version — the single publication point of an atomic commit. Every
        array is built and checked aside before this runs; the method body
        is pure reference assignment plus per-row derived-array rebuilds on
        arrays no reader holds yet, so there is no window in which a reader
        can observe a half-applied merge. ``eid`` is dropped (COO
        provenance does not survive mutation); ``feature_order`` is kept
        (the node id space is invariant — streaming deltas never add or
        remove nodes). A weighted/timestamped topology must be published
        with matching merged attribute arrays (the streaming admission
        layer guarantees this by rejecting attribute-less deltas);
        timestamped rows are re-sorted time-nondecreasing, restoring the
        sampler's binary-search invariant after appends."""
        if (self._edge_weight is not None) != (edge_weight is not None):
            raise ValueError(
                "mutation publish must carry edge weights exactly when the "
                "topology is weighted (the streaming admission layer "
                "rejects mismatched deltas)"
            )
        if (self._edge_time is not None) != (edge_time is not None):
            raise ValueError(
                "mutation publish must carry edge times exactly when the "
                "topology is timestamped (the streaming admission layer "
                "rejects mismatched deltas)"
            )
        edge_count = int(indptr[-1])
        node_count = int(indptr.shape[0] - 1)
        indptr = indptr.astype(_index_dtype(edge_count), copy=False)
        indices = indices.astype(
            _index_dtype(max(node_count - 1, 0)), copy=False
        )
        if edge_time is not None:
            t = edge_time.astype(np.float32, copy=False)
            # appended inserts land at row ends in ingestion order; re-sort
            # each row time-nondecreasing (identity on untouched rows)
            order = _time_sort_order(indptr, t)
            indices = indices[order]
            t = t[order]
            if edge_weight is not None:
                edge_weight = edge_weight[order]
            self._edge_time = t
        if edge_weight is not None:
            self._edge_weight = edge_weight.astype(np.float32, copy=False)
            self._cum_weights = _row_prefix_weights(
                self._edge_weight.astype(np.float64), indptr
            )
        self._indptr = indptr
        self._indices = indices
        self._eid = None
        self._max_degree = None  # degrees changed; re-derive on demand
        self._version += 1

    @property
    def degree(self) -> np.ndarray:
        return np.diff(self._indptr)

    @property
    def max_degree(self) -> int:
        # cached: samplers read this per construction, and on an mmap'd
        # raw load the O(N) degree scan would page the whole indptr in —
        # the manifest carries the value instead (invalidated on mutation)
        if self._max_degree is None:
            self._max_degree = int(self.degree.max(initial=0))
        return self._max_degree

    @property
    def node_count(self) -> int:
        return int(self._indptr.shape[0] - 1)

    @property
    def edge_count(self) -> int:
        return int(self._indptr[-1])

    def __repr__(self):
        return f"CSRTopo(nodes={self.node_count}, edges={self.edge_count})"

    # -- persistence --------------------------------------------------------

    def _persist_arrays(self) -> dict:
        """Every array worth round-tripping, keyed by canonical name.
        ``cum_weights`` rides along so a load never pays the O(E) prefix
        recompute; the raw format's mmap loads depend on that."""
        arrays = {"indptr": self._indptr, "indices": self._indices}
        for name in ("eid", "edge_weight", "cum_weights", "edge_time",
                     "feature_order"):
            v = getattr(self, f"_{name}")
            if v is not None:
                arrays[name] = v
        return arrays

    def save(self, path: str, format: str = "npz") -> None:
        """Persist the topology (CSR + eid + weights + feature_order).

        ``format="npz"`` (default) writes one ``.npz`` — the reference's
        users ``torch.save`` their CSR preprocessing artifacts
        (benchmarks/ogbn-papers100M/preprocess.py); this is the same
        round-trip without a torch dependency. A ``_integrity`` member
        (JSON, per-array CRC32 via the raw-manifest helper) rides inside
        the zip so :meth:`load` can catch silent byte corruption, not
        just zip-level truncation.

        ``format="raw"`` writes the mmap-native directory layout
        (:mod:`quiver_tpu.ooc.format`): per-array uncompressed ``.npy``
        files + CRC32 manifest + COMMIT marker. This is the out-of-core
        path — :meth:`load` with ``mmap=True`` backs ``indptr``/
        ``indices``/edge attrs onto ``np.memmap`` so resident bytes stay
        O(touched pages). Derived state (``cum_weights``, ``max_degree``)
        is persisted so the load path never runs an O(E) or O(N) scan.

        Both formats publish atomically (same-filesystem temp + fsync +
        ``os.replace``): a crash mid-save can leave a stale temp behind
        but never a torn artifact at ``path``."""
        if format == "raw":
            from ..ooc.format import save_raw_dir  # lazy: ooc sits above core

            save_raw_dir(path, self._persist_arrays(), meta={
                "kind": "csr-topo",
                "node_count": self.node_count,
                "edge_count": self.edge_count,
                "max_degree": self.max_degree,
                "version": self._version,
            })
            return
        if format != "npz":
            raise ValueError(f'format must be "npz" or "raw", got {format!r}')
        from ..resilience.integrity import array_checksum  # lazy (cycle)
        import json

        arrays = self._persist_arrays()
        arrays.pop("cum_weights", None)  # npz loads re-derive (legacy shape)
        integrity = json.dumps(
            {name: array_checksum(v) for name, v in arrays.items()},
            sort_keys=True,
        )
        # JSON-as-uint8 smuggles the checksums through np.savez without
        # allow_pickle; readers that predate it just see an extra member
        arrays["_integrity"] = np.frombuffer(
            integrity.encode(), dtype=np.uint8
        )
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as fh:  # exact filename, no np suffixing
                np.savez(fh, **arrays)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    @classmethod
    def _from_raw(cls, arrays: dict, meta: dict) -> "CSRTopo":
        """Assemble a topology from raw-format arrays WITHOUT running
        ``__init__`` — its O(N)/O(E) boundary scans and int64 coercion
        would page every byte of an mmap'd load in, defeating the
        out-of-core point. Safe because the arrays were validated on the
        way INTO :func:`~quiver_tpu.ooc.format.save_raw_dir` (they came
        from a live CSRTopo) and the format's manifest pins their exact
        sizes; run ``ooc.verify_raw_dir`` for a full byte-level sweep."""
        topo = cls.__new__(cls)
        topo._indptr = arrays["indptr"]
        topo._indices = arrays["indices"]
        topo._eid = arrays.get("eid")
        topo._feature_order = arrays.get("feature_order")
        topo._edge_weight = arrays.get("edge_weight")
        topo._cum_weights = arrays.get("cum_weights")
        topo._edge_time = arrays.get("edge_time")
        topo._max_degree = (
            int(meta["max_degree"]) if "max_degree" in meta else None
        )
        topo._version = int(meta.get("version", 0))
        return topo

    @classmethod
    def load(cls, path: str, mmap: bool = False) -> "CSRTopo":
        """Rebuild a :meth:`save`'d topology (either format — a directory
        at ``path`` is the raw layout, a file is the legacy ``.npz``).

        ``mmap=True`` (raw format only) backs every array onto read-only
        ``np.memmap``: resident bytes stay O(touched pages) and no
        validation scan runs (see :meth:`_from_raw`) — the papers100M
        path, where the CSR alone outgrows host RAM. Eager raw loads
        (``mmap=False``) run the full CRC32 sweep instead.

        Legacy ``.npz``: weights re-derive their per-row prefix sums
        (stored CSR-ordered, so coo_order is False on the way back in);
        when the archive carries a ``_integrity`` member the per-array
        CRC32s are verified, so silent byte corruption fails as loudly
        as zip-level truncation. A truncated, corrupt, or foreign file
        raises a clear ``ValueError`` naming the artifact — np.load's
        raw zipfile errors (or a KeyError three stack frames later) left
        the operator guessing which file was bad."""
        import zipfile

        if os.path.isdir(path):
            from ..ooc.format import load_raw_dir  # lazy: ooc sits above core

            arrays, meta = load_raw_dir(path, mmap=mmap)
            if meta.get("kind") != "csr-topo":
                raise ValueError(
                    f"{path}: raw dir holds {meta.get('kind')!r}, not a "
                    f"csr-topo artifact"
                )
            return cls._from_raw(arrays, meta)
        if mmap:
            raise ValueError(
                f"{path}: mmap loading needs the raw directory format — "
                f'save with format="raw" (a legacy .npz is a zip that '
                f"must be decompressed into RAM)"
            )
        try:
            z = np.load(path)
        except (OSError, ValueError, EOFError, zipfile.BadZipFile) as e:
            raise ValueError(
                f"{path}: not a readable topology file — truncated, "
                f"corrupt, or not an .npz ({type(e).__name__}: {e})"
            ) from None
        with z:
            cls._verify_npz_integrity(path, z)
            missing = [k for k in ("indptr", "indices") if k not in z.files]
            if missing:
                raise ValueError(
                    f"{path}: topology file lacks required array(s) "
                    f"{missing} (has {sorted(z.files)}) — truncated save "
                    f"or not a CSRTopo artifact"
                )
            try:
                topo = cls(indptr=z["indptr"], indices=z["indices"],
                           eid=z["eid"] if "eid" in z.files else None)
            except (OSError, ValueError, EOFError,
                    zipfile.BadZipFile) as e:
                raise ValueError(
                    f"{path}: topology arrays failed to load/validate "
                    f"({e})"
                ) from None
            if "edge_weight" in z.files:
                topo.set_edge_weight(z["edge_weight"], coo_order=False)
            if "edge_time" in z.files:
                # stored post-sort, so the re-sort inside is the identity
                topo.set_edge_time(z["edge_time"], coo_order=False)
            if "feature_order" in z.files:
                topo.feature_order = z["feature_order"]
        return topo

    @staticmethod
    def _verify_npz_integrity(path: str, z) -> None:
        """Check the ``_integrity`` CRC32 record an npz :meth:`save`
        embeds (absent on pre-record archives — those load unverified,
        backward compatible). Raises ``ValueError`` naming the first
        corrupt array."""
        if "_integrity" not in z.files:
            return
        import json
        import zipfile

        from ..resilience.integrity import array_checksum  # lazy (cycle)

        try:
            expected = json.loads(bytes(z["_integrity"]).decode())
        except (ValueError, UnicodeDecodeError, zipfile.BadZipFile) as e:
            raise ValueError(
                f"{path}: unreadable _integrity record ({e})"
            ) from None
        for name, crc in expected.items():
            if name not in z.files:
                raise ValueError(
                    f"{path}: _integrity covers array {name!r} but the "
                    f"archive lacks it — truncated or tampered save"
                )
            try:
                got = array_checksum(z[name])
            except (OSError, ValueError, EOFError, zipfile.BadZipFile) as e:
                # the zip's own member CRC can fire first on corrupt bytes
                raise ValueError(
                    f"{path}: array {name!r} unreadable — corrupt bytes "
                    f"({type(e).__name__}: {e})"
                ) from None
            if got != int(crc):
                raise ValueError(
                    f"{path}: checksum mismatch on array {name!r} "
                    f"(stored {crc}, computed {got}) — corrupt bytes"
                )

    # -- device placement ---------------------------------------------------

    def to_device(self, mode: SampleMode | str = SampleMode.HBM,
                  with_eid: bool = False, with_weights: bool = False,
                  with_times: bool = False) -> "DeviceTopology":
        """Place the topology for sampling.

        HBM mode puts everything in device memory. HOST mode keeps the large
        ``indices`` (and ``eid``/``cum_weights``) arrays in pinned host memory
        where supported — on platforms without a pinned_host memory space it
        degrades to HBM with a warning-free fallback (CPU tests take this
        path). ``with_weights`` ships the prefix-weight array for weighted
        sampling (requires ``set_edge_weight`` first); ``with_times`` ships
        the timestamp array for temporal windows (requires ``set_edge_time``
        first, HBM mode only — the window search gathers timestamps inside
        the draw loop, which HOST staging cannot serve).
        """
        if with_weights and self._cum_weights is None:
            raise ValueError(
                "weighted sampling requires edge weights; call "
                "set_edge_weight() or pass edge_weight= to CSRTopo"
            )
        if with_times:
            if self._edge_time is None:
                raise ValueError(
                    "temporal sampling requires edge timestamps; call "
                    "set_edge_time() or pass edge_time= to CSRTopo"
                )
            if SampleMode.parse(mode) is not SampleMode.HBM:
                raise ValueError(
                    "temporal sampling requires mode='HBM' — the window "
                    "search gathers timestamps inside the draw loop, which "
                    "HOST-staged placement cannot serve"
                )
        return place_csr_arrays(
            self._indptr, self._indices,
            self._eid if with_eid else None,
            self._cum_weights if with_weights else None,
            self.max_degree, mode,
            edge_time=self._edge_time if with_times else None,
        )


def place_csr_arrays(indptr, indices, eid, cum_weights, max_degree: int,
                     mode: SampleMode | str,
                     edge_time=None) -> "DeviceTopology":
    """Shared CSR placement for CSRTopo and hetero RelCSR.

    HBM mode puts everything in device memory; HOST mode keeps the large
    per-edge arrays (indices/eid/cum_weights) in pinned host memory where
    supported. Pass ``eid``/``cum_weights``/``edge_time`` as None to omit
    them (``edge_time`` is HBM-only, enforced by the ``to_device`` callers);
    the weighted/temporal binary searches' static iteration bound derives
    from ``max_degree``.
    """
    mode = SampleMode.parse(mode)
    indptr = jnp.asarray(indptr)
    host = False
    if mode == SampleMode.HOST:
        indices, host = to_pinned_host(indices)
        if eid is not None:
            eid = to_pinned_host(eid)[0] if host else jnp.asarray(eid)
        if cum_weights is not None:
            cum_weights = (
                to_pinned_host(cum_weights)[0] if host
                else jnp.asarray(cum_weights)
            )
    else:
        indices = jnp.asarray(indices)
        if eid is not None:
            eid = jnp.asarray(eid)
        if cum_weights is not None:
            cum_weights = jnp.asarray(cum_weights)
    if edge_time is not None:
        edge_time = jnp.asarray(edge_time)
    iters = (
        max(int(np.ceil(np.log2(max_degree + 1))), 1)
        if cum_weights is not None or edge_time is not None
        else 0
    )
    return DeviceTopology(indptr=indptr, indices=indices, eid=eid,
                          cum_weights=cum_weights, edge_time=edge_time,
                          host_indices=host, search_iters=iters,
                          max_degree=int(max_degree))


@jax.tree_util.register_pytree_node_class
class DeviceTopology:
    """Device-resident CSR arrays, usable inside jit as a pytree.

    ``host_indices`` is static metadata: True when ``indices``/``eid`` live in
    pinned host memory (HOST mode) so gathers must stage through host compute.
    ``max_degree`` is static host metadata (None when unknown, e.g. a
    hand-built topology); the fused Pallas sampler uses it for trace-time
    window-coverage decisions.
    """

    def __init__(self, indptr, indices, eid=None, cum_weights=None,
                 edge_time=None, host_indices: bool = False,
                 search_iters: int = 0, max_degree: int | None = None):
        self.indptr = indptr
        self.indices = indices
        self.eid = eid
        self.cum_weights = cum_weights
        self.edge_time = edge_time
        self.host_indices = host_indices
        self.search_iters = search_iters
        self.max_degree = max_degree

    @property
    def node_count(self) -> int:
        return self.indptr.shape[0] - 1

    @property
    def edge_count(self) -> int:
        return self.indices.shape[0]

    def tree_flatten(self):
        children = (self.indptr, self.indices, self.eid, self.cum_weights,
                    self.edge_time)
        return children, (self.host_indices, self.search_iters,
                          self.max_degree)

    @classmethod
    def tree_unflatten(cls, aux, children):
        indptr, indices, eid, cum_weights, edge_time = children
        return cls(indptr, indices, eid, cum_weights, edge_time,
                   host_indices=aux[0], search_iters=aux[1],
                   max_degree=aux[2])
