"""Memory-placement helpers shared by topology and feature tiers."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["to_pinned_host"]


def to_pinned_host(x: np.ndarray, mesh=None) -> tuple[jax.Array, bool]:
    """Place an array in pinned host memory if the platform supports it.

    With ``mesh``, the host array is replicated across the mesh's devices
    (one physical copy per host) so it composes with mesh-sharded arrays.
    Returns (array, is_host). Falls back to default placement with
    is_host=False on platforms without a pinned_host memory space — callers
    branch on the flag to pick direct vs staged gathers.
    """
    try:
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            s = NamedSharding(mesh, PartitionSpec(), memory_kind="pinned_host")
        else:
            s = jax.sharding.SingleDeviceSharding(
                jax.devices()[0], memory_kind="pinned_host"
            )
        arr = jax.device_put(np.asarray(x), s)
        if getattr(arr.sharding, "memory_kind", None) == "pinned_host":
            return arr, True
    except Exception:
        pass
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(np.asarray(x), NamedSharding(mesh, PartitionSpec())), False
    return jnp.asarray(x), False
