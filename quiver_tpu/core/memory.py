"""Memory-placement helpers shared by topology and feature tiers."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["to_pinned_host"]


def to_pinned_host(x: np.ndarray) -> tuple[jax.Array, bool]:
    """Place an array in pinned host memory if the platform supports it.

    Returns (array, is_host). Falls back to default device placement with
    is_host=False on platforms without a pinned_host memory space — callers
    branch on the flag to pick direct vs staged gathers.
    """
    dev = jax.devices()[0]
    try:
        s = jax.sharding.SingleDeviceSharding(dev, memory_kind="pinned_host")
        arr = jax.device_put(np.asarray(x), s)
        if getattr(arr.sharding, "memory_kind", None) == "pinned_host":
            return arr, True
    except Exception:
        pass
    return jnp.asarray(x), False
