"""Mesh-sharded heterogeneous topology: every relation's CSR partitioned.

The typed sibling of :class:`~quiver_tpu.core.sharded_topology.ShardedTopology`:
each relation ``(src_type, rel, dst_type)`` of a
:class:`~quiver_tpu.core.hetero.HeteroCSRTopo` is a rectangular incoming CSR
whose rows live in the DESTINATION type's id space, so the partition is a
contiguous row-range split per *node type* — shard ``d`` owns dst rows
``[d * rows_per_shard[t], (d+1) * rows_per_shard[t])`` of every relation
into type ``t``, and ``owner(v) = v // rows_per_shard[t]``.

Because all relations into one destination type share the SAME row ranges,
one owner-routing plan per (hop, dst type) serves every relation's degree
and neighbor exchanges (``sampling/dist_hetero.py``) — the plan's id lanes
are sent once and cached.

Layout per relation mirrors the homogeneous partition: rebased
``(F, rows_per_shard + 1)`` indptr, zero-padded ``(F, padded_edges)``
indices (plus an optional prefix-weight slice for weighted relations —
row-local prefixes, so each shard's slice is bitwise identical to the
replicated array's segment), placed with ``NamedSharding(mesh, P(axis,
None))`` so a ``shard_map`` body receives exactly its own block.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import FEATURE_AXIS
from ..utils.trace import get_logger
from .hetero import HeteroCSRTopo

__all__ = ["HeteroShardedTopology", "ShardedRel"]


class ShardedRel:
    """One relation's row-range partition: per-shard rebased CSR blocks
    plus the static geometry the distributed hetero hop needs."""

    def __init__(self, indptr, indices, cum_weights, rows_per_shard: int,
                 padded_edges: int, search_iters: int, shard_edges):
        self.indptr = indptr  # (F, rows_per_shard + 1) device, P(axis, None)
        self.indices = indices  # (F, padded_edges) device, P(axis, None)
        self.cum_weights = cum_weights  # (F, padded_edges) f32 or None
        self.rows_per_shard = rows_per_shard
        self.padded_edges = padded_edges
        self.search_iters = search_iters
        self.shard_edges = shard_edges  # host list, per-shard true edge count


class HeteroShardedTopology:
    """Per-relation row-range partition of a :class:`HeteroCSRTopo`.

    Args:
      mesh: the device mesh; partitions run over ``mesh.shape[axis]``
        shards (replicated across the other axes).
      hetero_topo: host typed topology to partition. ``eid`` is not
        carried (with_eid stays on the replicated sampler).
      axis: mesh axis to shard over (default ``"feature"``).
      weighted_rels: edge types whose prefix-weight arrays ship with the
        shards for weighted distributed draws (each must have weights
        attached via ``set_edge_weight``).
    """

    def __init__(self, mesh, hetero_topo: HeteroCSRTopo,
                 axis: str = FEATURE_AXIS, weighted_rels=()):
        self.mesh = mesh
        self.axis = axis
        self.hetero_topo = hetero_topo
        self.weighted_rels = frozenset(
            tuple(str(t) for t in et) for et in weighted_rels
        )
        unknown = self.weighted_rels - set(hetero_topo.relations)
        if unknown:
            raise ValueError(f"unknown weighted relations: {sorted(unknown)}")
        for et in sorted(self.weighted_rels):
            if hetero_topo.relations[et].cum_weights is None:
                raise ValueError(
                    f"weighted relation {et} needs edge weights attached: "
                    f"call hetero_topo.set_edge_weight() first"
                )
        F = int(mesh.shape[axis])
        self.num_shards = F
        self.num_nodes = dict(hetero_topo.num_nodes)
        # one row-range geometry per NODE type — every relation into a
        # type shares it, which is what lets one route plan per (hop,
        # dst type) serve all of them
        self.rows_per_shard = {
            t: (-(-n // F) if n else 1)
            for t, n in hetero_topo.num_nodes.items()
        }
        sharding = NamedSharding(mesh, P(axis, None))
        self.rels: dict[tuple, ShardedRel] = {}
        per_chip = 0
        replicated = 0
        for et, rel in hetero_topo.relations.items():
            d_t = et[2]
            rps = self.rows_per_shard[d_t]
            n = rel.node_count
            indptr = np.asarray(rel.indptr, dtype=np.int64)
            indices = np.asarray(rel.indices)
            E = int(indptr[-1])
            shard_edges, local_indptrs = [], []
            for d in range(F):
                lo = min(d * rps, n)
                hi = min((d + 1) * rps, n)
                lo_e, hi_e = int(indptr[lo]), int(indptr[hi])
                li = np.full(rps + 1, hi_e - lo_e, dtype=np.int64)
                li[: hi - lo + 1] = indptr[lo : hi + 1] - lo_e
                local_indptrs.append(li)
                shard_edges.append(hi_e - lo_e)
            E_pad = max(max(shard_edges), 1)
            ip_dtype = (
                np.int32 if E_pad <= np.iinfo(np.int32).max else np.int64
            )
            ip = np.stack(local_indptrs).astype(ip_dtype)
            ix = np.zeros((F, E_pad), dtype=indices.dtype)
            cw = None
            weighted = et in self.weighted_rels
            if weighted:
                cum = np.asarray(rel.cum_weights)
                cw = np.zeros((F, E_pad), dtype=cum.dtype)
            for d in range(F):
                lo_e = int(indptr[min(d * rps, n)])
                ix[d, : shard_edges[d]] = indices[lo_e : lo_e + shard_edges[d]]
                if weighted:
                    cw[d, : shard_edges[d]] = cum[lo_e : lo_e + shard_edges[d]]
            iters = (
                max(int(np.ceil(np.log2(rel.max_degree + 1))), 1)
                if weighted else 0
            )
            self.rels[et] = ShardedRel(
                jax.device_put(ip, sharding),
                jax.device_put(ix, sharding),
                None if cw is None else jax.device_put(cw, sharding),
                rps, E_pad, iters, shard_edges,
            )
            per_chip += (
                (rps + 1) * ip.dtype.itemsize + E_pad * ix.dtype.itemsize
                + (E_pad * 4 if weighted else 0)
            )
            replicated += (
                (n + 1) * indptr.dtype.itemsize + E * indices.dtype.itemsize
                + (E * 4 if weighted else 0)
            )
        self.version = 0
        self.plan = {
            "num_shards": F,
            "rows_per_shard": dict(self.rows_per_shard),
            "relations": {
                et: {
                    "rows_per_shard": r.rows_per_shard,
                    "padded_edges": r.padded_edges,
                    "shard_edges": r.shard_edges,
                }
                for et, r in self.rels.items()
            },
            "per_chip_bytes": per_chip,
            "replicated_bytes": replicated,
            "shrink_factor": replicated / max(per_chip, 1),
        }
        get_logger("topology").info(
            "hetero sharded topology: %d relations x %d shards on mesh "
            "axis '%s'; %.2f MB/chip vs %.2f MB replicated (%.1fx shrink)",
            len(self.rels), F, axis, per_chip / 2**20, replicated / 2**20,
            self.plan["shrink_factor"],
        )

    def replan(self, mesh, axis: str | None = None) -> "HeteroShardedTopology":
        """Re-partition the same host topology onto a different mesh
        (elastic resume) — new geometry, identical sampling bits."""
        return HeteroShardedTopology(
            mesh, self.hetero_topo, axis=self.axis if axis is None else axis,
            weighted_rels=self.weighted_rels,
        )

    def __repr__(self):
        return (
            f"HeteroShardedTopology(relations={len(self.rels)}, "
            f"shards={self.num_shards}, "
            f"shrink={self.plan['shrink_factor']:.1f}x)"
        )
