"""Mesh-sharded graph topology: the CSR itself partitioned across chips.

``DeviceTopology`` (core/topology.py) replicates the whole CSR on every
chip — the reference's device-resident topology registration
(quiver_sample.cu:400-408) has the same property per GPU — so the largest
trainable graph is bounded by ONE chip's memory no matter how many chips
the mesh has. ``ShardedTopology`` removes that wall: a contiguous
row-range partition of ``indptr``/``indices`` across the mesh's
``feature`` axis, with the same owner-offset layout as ``ShardedTensor``
(feature/shard.py): shard ``d`` owns rows
``[d * rows_per_shard, (d+1) * rows_per_shard)`` and
``owner(v) = v // rows_per_shard``. Per-chip topology bytes shrink to
roughly ``1/F`` of the replicated placement (see :attr:`plan` — the
partition plan the dryrun/tests assert on); graph capacity scales with
mesh size instead of chip size.

Distributed-partition sampling over this layout is the established
scale-out answer (Zeng et al., arXiv:2010.03166); the per-hop owner
routing that makes it fast lives in ``sampling/dist.py`` +
``parallel/routing.py``.

Layout details:

* Each shard's slice is rebased to LOCAL edge offsets: ``indptr`` becomes
  an ``(F, rows_per_shard + 1)`` array whose row ``d`` is
  ``csr.indptr[d*rps : (d+1)*rps + 1] - csr.indptr[d*rps]`` (padding rows
  past ``node_count`` repeat the last offset, i.e. degree 0).
* ``indices`` becomes ``(F, padded_edges)`` with every shard's slice
  zero-padded to the widest shard (static shapes; the pad is never
  addressed — local offsets stay below the shard's true edge count).
* Both arrays are placed with ``NamedSharding(mesh, P(axis, None))`` so a
  ``shard_map`` body receives exactly its own ``(1, rows_per_shard + 1)``
  / ``(1, padded_edges)`` block.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import FEATURE_AXIS
from ..utils.trace import get_logger
from .topology import CSRTopo

__all__ = ["ShardedTopology"]


class ShardedTopology:
    """Row-range partition of a :class:`CSRTopo` over a mesh axis.

    Args:
      mesh: the device mesh; the partition runs over ``mesh.shape[axis]``
        shards (and is replicated across the other axes, so every data
        group holds one full copy of the partition — not of the graph).
      csr_topo: host CSR to partition. ``eid`` is not carried (with_eid
        sampling stays on the replicated sampler).
      axis: mesh axis name to shard over (default ``"feature"`` — the same
        axis the sharded feature table lives on, so one owner-routing
        budget covers both).
      with_weights: also ship each shard's slice of the row-local
        prefix-weight array (``CSRTopo.cum_weights``) for weighted
        distributed draws. The prefixes are ROW-local, so a shard's slice
        is bitwise identical to the replicated array's segment — the
        weighted bit-parity source.
      with_times: also ship each shard's slice of the CSR-ordered
        timestamp array (``CSRTopo.edge_time``) for temporal windows.
    """

    def __init__(self, mesh, csr_topo: CSRTopo, axis: str = FEATURE_AXIS,
                 with_weights: bool = False, with_times: bool = False):
        self.mesh = mesh
        self.axis = axis
        # retained for replan(): an elastic resume re-partitions the SAME
        # host CSR onto a differently-shaped mesh (the arrays are already
        # host-resident on the CSRTopo — this is a reference, not a copy)
        self.csr_topo = csr_topo
        self.with_weights = bool(with_weights)
        self.with_times = bool(with_times)
        if self.with_weights and csr_topo.cum_weights is None:
            raise ValueError(
                "with_weights=True requires edge weights; call "
                "csr_topo.set_edge_weight() or pass edge_weight= to CSRTopo"
            )
        if self.with_times and csr_topo.edge_time is None:
            raise ValueError(
                "with_times=True requires edge timestamps; call "
                "csr_topo.set_edge_time() or pass edge_time= to CSRTopo"
            )
        F = int(mesh.shape[axis])
        indptr = np.asarray(csr_topo.indptr, dtype=np.int64)
        indices = np.asarray(csr_topo.indices)
        n = int(indptr.shape[0] - 1)
        E = int(indptr[-1])
        rps = -(-n // F) if n else 1  # ceil; at least one row per shard
        shard_edges = []
        local_indptrs = []
        for d in range(F):
            lo = min(d * rps, n)
            hi = min((d + 1) * rps, n)
            lo_e, hi_e = int(indptr[lo]), int(indptr[hi])
            li = np.full(rps + 1, hi_e - lo_e, dtype=np.int64)
            li[: hi - lo + 1] = indptr[lo : hi + 1] - lo_e
            local_indptrs.append(li)
            shard_edges.append(hi_e - lo_e)
        E_pad = max(max(shard_edges), 1)
        ip_dtype = np.int32 if E_pad <= np.iinfo(np.int32).max else np.int64
        ip = np.stack(local_indptrs).astype(ip_dtype)
        ix = np.zeros((F, E_pad), dtype=indices.dtype)
        for d in range(F):
            lo_e = int(indptr[min(d * rps, n)])
            ix[d, : shard_edges[d]] = indices[lo_e : lo_e + shard_edges[d]]

        def _edge_attr_slices(attr):
            # same per-shard edge ranges as indices; zero-padded to E_pad.
            # np slicing copies bytes verbatim, so each shard's slice is
            # bitwise identical to the replicated array's segment
            out = np.zeros((F, E_pad), dtype=attr.dtype)
            for d in range(F):
                lo_e = int(indptr[min(d * rps, n)])
                out[d, : shard_edges[d]] = attr[lo_e : lo_e + shard_edges[d]]
            return out

        sharding = NamedSharding(mesh, P(axis, None))
        self.indptr = jax.device_put(ip, sharding)
        self.indices = jax.device_put(ix, sharding)
        self.cum_weights = None
        self.edge_time = None
        attr_bytes = 0
        if self.with_weights:
            cw = _edge_attr_slices(np.asarray(csr_topo.cum_weights))
            self.cum_weights = jax.device_put(cw, sharding)
            attr_bytes += E_pad * cw.dtype.itemsize
        if self.with_times:
            et = _edge_attr_slices(np.asarray(csr_topo.edge_time))
            self.edge_time = jax.device_put(et, sharding)
            attr_bytes += E_pad * et.dtype.itemsize
        # static binary-search bound for the weighted/temporal draws, from
        # the GLOBAL max degree so every shard compiles the same loop
        self.search_iters = (
            max(int(np.ceil(np.log2(csr_topo.max_degree + 1))), 1)
            if (self.with_weights or self.with_times)
            else 0
        )
        self.node_count = n
        self.edge_count = E
        self.max_degree = int(csr_topo.max_degree)
        self.num_shards = F
        self.rows_per_shard = rps
        # the committed mutation version this partition was built from
        # (streaming commits bump the host CSR's version; a consumer
        # comparing the two detects a stale device partition)
        self.version = int(getattr(csr_topo, "version", 0))

        # the partition plan — per-chip byte accounting the acceptance
        # criteria assert on (padded_edges is the widest shard, so skewed
        # row ranges show up here as a shrink factor below F)
        per_chip = (
            (rps + 1) * ip.dtype.itemsize + E_pad * ix.dtype.itemsize
            + attr_bytes
        )
        replicated = (
            (n + 1) * csr_topo.indptr.dtype.itemsize
            + E * indices.dtype.itemsize
            + (E * 4 if self.with_weights else 0)
            + (E * 4 if self.with_times else 0)
        )
        self.plan = {
            "num_shards": F,
            "rows_per_shard": rps,
            "node_count": n,
            "edge_count": E,
            "shard_edges": shard_edges,
            "padded_edges": E_pad,
            "per_chip_bytes": per_chip,
            "replicated_bytes": replicated,
            "shrink_factor": replicated / max(per_chip, 1),
        }
        get_logger("topology").info(
            "sharded topology: %d rows x %d shards on mesh axis '%s' "
            "(%d rows/shard, widest shard %d/%d edges); %.2f MB/chip vs "
            "%.2f MB replicated (%.1fx shrink)",
            n, F, axis, rps, E_pad, E, per_chip / 2**20,
            replicated / 2**20, self.plan["shrink_factor"],
        )

    def replan(self, mesh, axis: str | None = None) -> "ShardedTopology":
        """Re-partition the same host CSR onto a different mesh (elastic
        resume: preemption handed back a different device count). Returns
        a FRESH partition — new ``rows_per_shard``/owner map/``plan`` at
        the new axis size; node and edge data are untouched, so sampling
        results stay bit-identical (the PR 3 parity contract: routing
        decides which wires the bits cross, never the bits)."""
        return ShardedTopology(
            mesh, self.csr_topo, axis=self.axis if axis is None else axis,
            with_weights=self.with_weights, with_times=self.with_times,
        )

    def owner_of(self, ids):
        """Owning shard index of each (global) node id."""
        return jnp.asarray(ids) // self.rows_per_shard

    def __repr__(self):
        return (
            f"ShardedTopology(nodes={self.node_count}, "
            f"edges={self.edge_count}, shards={self.num_shards}, "
            f"rows_per_shard={self.rows_per_shard}, "
            f"shrink={self.plan['shrink_factor']:.1f}x)"
        )
