"""Typed runtime configuration for quiver-tpu.

The reference scatters three string-typed knobs across modules: a byte-size
parser duplicated in two files (torch-quiver feature.py:64-81 and
shard_tensor.py:42-68), ``cache_policy`` strings (feature.py:35-37), and the
sampler ``mode`` flag (pyg/sage_sampler.py:43-44). Here they are unified into
one module with enums that still accept the reference's spellings for API
parity.
"""

from __future__ import annotations

import dataclasses
import enum
import re

__all__ = [
    "parse_size_bytes",
    "resolve_platform_strategy",
    "CachePolicy",
    "SampleMode",
    "SamplerConfig",
]


def resolve_platform_strategy(env_var: str, choices, tpu_default: str,
                              other_default: str) -> str:
    """Shared env-override-then-platform-default resolver.

    Several ops keep two bit-identical implementations whose cost model
    flips between backends (XLA serializes general scatters on TPU):
    dedup strategies, occurrence counts, chunked inference aggregation.
    Each exposes an env var that FORCES a strategy during chip windows; a
    typo'd force must raise, not silently measure the platform default.
    """
    import os

    v = os.environ.get(env_var, "").strip().lower()
    if v:
        if v not in choices:
            raise ValueError(f"{env_var}={v!r} is not one of {tuple(choices)}")
        return v
    import jax

    return (tpu_default if jax.default_backend() == "tpu"
            else other_default)

_SIZE_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([A-Za-z]*)\s*$")

_UNITS = {
    "": 1,
    "B": 1,
    "K": 2**10,
    "KB": 2**10,
    "M": 2**20,
    "MB": 2**20,
    "G": 2**30,
    "GB": 2**30,
    "T": 2**40,
    "TB": 2**40,
}


def parse_size_bytes(size: int | float | str) -> int:
    """Parse a human byte-size like ``"0.9M"``, ``"3GB"``, ``200`` into bytes.

    Accepts every spelling the reference accepts (K/KB/M/MB/G/GB, case
    insensitive, optional fraction) plus T/TB and plain ints (bytes).
    """
    if isinstance(size, bool):
        raise TypeError("size must be a number or string, not bool")
    if isinstance(size, (int, float)):
        if size < 0:
            raise ValueError(f"size must be non-negative, got {size}")
        return int(size)
    m = _SIZE_RE.match(size)
    if not m:
        raise ValueError(f"cannot parse byte size {size!r}")
    value, unit = m.group(1), m.group(2).upper()
    if unit not in _UNITS:
        raise ValueError(f"unknown size unit {unit!r} in {size!r}")
    return int(float(value) * _UNITS[unit])


class CachePolicy(enum.Enum):
    """Hot-tier placement policy for the feature cache.

    ``DEVICE_REPLICATE`` replicates the hot rows into every device's HBM
    (reference ``device_replicate``, feature.py:120-124). ``MESH_SHARD``
    partitions the hot rows across the devices of the mesh's feature axis and
    gathers over ICI — the TPU analogue of the reference's NVLink-clique
    partitioning (``p2p_clique_replicate``, feature.py:126-166).
    """

    DEVICE_REPLICATE = "device_replicate"
    MESH_SHARD = "mesh_shard"

    @classmethod
    def parse(cls, value: "CachePolicy | str") -> "CachePolicy":
        if isinstance(value, cls):
            return value
        aliases = {
            "device_replicate": cls.DEVICE_REPLICATE,
            "p2p_clique_replicate": cls.MESH_SHARD,  # reference spelling
            "mesh_shard": cls.MESH_SHARD,
        }
        try:
            return aliases[value]
        except KeyError:
            raise ValueError(
                f"unknown cache policy {value!r}; expected one of {sorted(aliases)}"
            ) from None


class SampleMode(enum.Enum):
    """Where the graph topology lives during sampling.

    ``HBM`` keeps indptr/indices in device HBM (reference ``GPU`` mode,
    sage_sampler.py:54). ``HOST`` keeps the large ``indices`` array in pinned
    host memory and stages gathers — the TPU replacement for the reference's
    UVA zero-copy mode (quiver_sample.cu:400-408), since TPU kernels cannot
    dereference host pointers.
    """

    HBM = "hbm"
    HOST = "host"

    @classmethod
    def parse(cls, value: "SampleMode | str") -> "SampleMode":
        if isinstance(value, cls):
            return value
        aliases = {
            "gpu": cls.HBM,  # reference spelling
            "hbm": cls.HBM,
            "uva": cls.HOST,  # reference spelling
            "host": cls.HOST,
            "zero_copy": cls.HOST,
        }
        try:
            return aliases[value.lower()]
        except KeyError:
            raise ValueError(
                f"unknown sample mode {value!r}; expected one of {sorted(aliases)}"
            ) from None


@dataclasses.dataclass(frozen=True)
class SamplerConfig:
    """Static-shape configuration for the multi-layer sampler.

    XLA requires static shapes, so the ragged outputs of the reference's
    sampler (quiver_sample.cu:100-119) become padded blocks. ``seed_capacity``
    is the padded batch size; ``frontier_caps`` bounds the unique-node count
    after each layer (defaults to min(worst-case growth, node_count)).
    """

    sizes: tuple[int, ...]
    seed_capacity: int
    frontier_caps: tuple[int, ...]
    mode: SampleMode = SampleMode.HBM

    def __post_init__(self):
        if len(self.frontier_caps) != len(self.sizes):
            raise ValueError("frontier_caps must have one entry per layer")
        if self.seed_capacity <= 0:
            raise ValueError("seed_capacity must be positive")
