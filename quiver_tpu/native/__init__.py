"""ctypes loader for the native host runtime (quiver_host.cpp).

Builds the shared library on first import (cached next to the source; no
pybind11 in this image, so the C ABI + ctypes replaces the reference's
torch-extension binding layer, srcs/cpp/src/quiver/torch/module.cpp).
Falls back cleanly to ``available = False`` when no toolchain exists —
callers keep their numpy paths, mirroring how the reference's CPU-only CI
builds without CUDA (HAVE_CUDA gating, setup.py:13-16).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "quiver_host.cpp")
_LIB = os.path.join(_DIR, "libquiver_host.so")

available = False
_lib = None


def _build() -> bool:
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return True
    # compile to a temp path and atomically rename so concurrent importers
    # (one JAX process per TPU host on a shared FS) never dlopen a torn file
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, available
    if not _build():
        return
    try:
        lib = ctypes.CDLL(_LIB)

        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)

        lib.csr_from_coo_i64.argtypes = [i64p, i64p, ctypes.c_int64, ctypes.c_int64, i64p, i32p, i64p]
        lib.csr_from_coo_i32.argtypes = [i32p, i32p, ctypes.c_int64, ctypes.c_int64, i64p, i32p, i64p]
        lib.gather_rows_bytes.argtypes = [u8p, ctypes.c_int64, ctypes.c_int64, i64p, ctypes.c_int64, u8p]
        lib.sample_neighbors_cpu.argtypes = [
            i64p, i32p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, i32p, i32p,
        ]
        lib.degrees_i64.argtypes = [i64p, ctypes.c_int64, i64p]
        lib.reindex_cpu.argtypes = [
            i32p, ctypes.c_int64, i32p, ctypes.c_int32, i32p, i32p,
        ]
        lib.reindex_cpu.restype = ctypes.c_int64
        lib.quiver_host_num_threads.restype = ctypes.c_int
    except (OSError, AttributeError):
        # torn/stale .so (e.g. built from older source, missing a symbol)
        return
    _lib = lib
    available = True


_load()


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def csr_from_coo(rows: np.ndarray, cols: np.ndarray, n_nodes: int, with_eid: bool = True):
    """Linear-time parallel COO->CSR. Returns (indptr i64, indices i32, eid i64|None)."""
    if not available:
        raise RuntimeError("native library unavailable")
    if n_nodes > np.iinfo(np.int32).max:
        # the native path stores indices as int32; beyond that the numpy
        # int64 fallback is the correct tool
        raise ValueError(f"native CSR builder supports < 2^31 nodes, got {n_nodes}")
    e = rows.shape[0]
    indptr = np.empty(n_nodes + 1, np.int64)
    indices = np.empty(e, np.int32)
    eid = np.empty(e, np.int64) if with_eid else None
    eid_p = _ptr(eid, ctypes.c_int64) if with_eid else None
    if rows.dtype == np.int32 and cols.dtype == np.int32:
        rows = np.ascontiguousarray(rows, np.int32)
        cols = np.ascontiguousarray(cols, np.int32)
        _lib.csr_from_coo_i32(
            _ptr(rows, ctypes.c_int32), _ptr(cols, ctypes.c_int32), e, n_nodes,
            _ptr(indptr, ctypes.c_int64), _ptr(indices, ctypes.c_int32), eid_p,
        )
    else:
        rows = np.ascontiguousarray(rows, np.int64)
        cols = np.ascontiguousarray(cols, np.int64)
        _lib.csr_from_coo_i64(
            _ptr(rows, ctypes.c_int64), _ptr(cols, ctypes.c_int64), e, n_nodes,
            _ptr(indptr, ctypes.c_int64), _ptr(indices, ctypes.c_int32), eid_p,
        )
    return indptr, indices, eid


def gather_rows(table: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Parallel host row gather; ids < 0 produce zero rows."""
    if not available:
        raise RuntimeError("native library unavailable")
    table = np.ascontiguousarray(table)
    ids = np.ascontiguousarray(ids, np.int64)
    row_bytes = table.strides[0]
    out = np.empty((ids.shape[0],) + table.shape[1:], table.dtype)
    _lib.gather_rows_bytes(
        table.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        table.shape[0], row_bytes,
        _ptr(ids, ctypes.c_int64), ids.shape[0],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def sample_neighbors(indptr: np.ndarray, indices: np.ndarray, seeds: np.ndarray,
                     k: int, seed: int = 0):
    """CPU reservoir sampler with the padded (S, k)/-1 output contract."""
    if not available:
        raise RuntimeError("native library unavailable")
    indptr = np.ascontiguousarray(indptr, np.int64)
    indices = np.ascontiguousarray(indices, np.int32)
    seeds = np.ascontiguousarray(seeds, np.int32)
    s = seeds.shape[0]
    out = np.empty((s, k), np.int32)
    counts = np.empty(s, np.int32)
    _lib.sample_neighbors_cpu(
        _ptr(indptr, ctypes.c_int64), _ptr(indices, ctypes.c_int32),
        _ptr(seeds, ctypes.c_int32), s, k, seed,
        _ptr(out, ctypes.c_int32), _ptr(counts, ctypes.c_int32),
    )
    return out, counts


def reindex(seeds: np.ndarray, neighbors: np.ndarray):
    """Hash-based order-preserving reindex (native CPUQuiver::reindex_group
    parity, reference quiver.cpp:39-84).

    Args:
      seeds: (S,) int32 node ids, -1 for padding; every valid seed keeps its
        own frontier slot (duplicates included — PyG contract).
      neighbors: (S, k) int32 sampled ids, -1 invalid.

    Returns:
      (frontier (M,) int32 seeds-first unique ids,
       col (S, k) int32 frontier-local ids, -1 where invalid).
    """
    if not available:
        raise RuntimeError("native library unavailable")
    seeds = np.ascontiguousarray(seeds, np.int32)
    neighbors = np.ascontiguousarray(neighbors, np.int32)
    s, k = neighbors.shape
    if seeds.shape[0] != s:
        raise ValueError(f"seeds {seeds.shape} vs neighbors {neighbors.shape}")
    frontier = np.empty(s * (k + 1), np.int32)
    col = np.empty((s, k), np.int32)
    m = _lib.reindex_cpu(
        _ptr(seeds, ctypes.c_int32), s,
        _ptr(neighbors, ctypes.c_int32), k,
        _ptr(frontier, ctypes.c_int32), _ptr(col, ctypes.c_int32),
    )
    return frontier[:m].copy(), col


def num_threads() -> int:
    return _lib.quiver_host_num_threads() if available else 0
