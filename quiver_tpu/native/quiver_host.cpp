// quiver-tpu native host runtime.
//
// The reference implements its host-side hot paths in C++/CUDA
// (torch-quiver srcs/cpp: CSR construction via device sort in
// quiver_sample.cu:450-484 and quiver.cpu.hpp:34-42, the CPU sampler
// quiver.cpp:10-114, and zero-copy host feature reads through UVA,
// quiver_feature.cu:189-197). On a TPU host the equivalents are plain
// CPU code feeding the device: a linear-time parallel CSR builder for
// preprocessing, an OpenMP row-gather that services the cold feature tier
// (what UVA did from inside the GPU kernel now happens host-side before
// DMA), and a reservoir-sampling CPU fallback sampler (CI tier parity,
// ci.yaml CPU-only build).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC quiver_host.cpp -o libquiver_host.so

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <random>
#include <unordered_map>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

inline int max_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

// Counting sort by row, deterministic and stable in both paths: CSR slots
// within a row always follow COO order, so native and numpy-argsort builds
// produce byte-identical indices/eid — a requirement for multi-host SPMD,
// where every host builds the "replicated" topology independently and the
// arrays must agree across hosts.
//
// Parallel scheme, O(E + T*N) total work: atomic relaxed histogram for
// indptr (order-independent), then a chunked stable scatter — edges are
// split into T contiguous chunks, each thread histograms its own chunk
// per row, a cross-chunk exclusive scan per row turns the histograms into
// deterministic per-(chunk,row) cursors, and each thread scatters only its
// own chunk. Stability holds because chunk order equals COO order. The
// T*N*4B cursor matrix is capped at ~1GB by shrinking T (T=1 degenerates
// to the serial single-pass scatter, still O(E)).
template <typename RowT, typename ColT>
void csr_from_coo_impl(const RowT* rows, const ColT* cols, int64_t n_edges,
                       int64_t n_nodes, int64_t* indptr, int32_t* indices,
                       int64_t* eid) {
  auto serial = [&]() {
    std::vector<int64_t> counts(n_nodes, 0);
    for (int64_t e = 0; e < n_edges; ++e) counts[rows[e]]++;
    indptr[0] = 0;
    for (int64_t i = 0; i < n_nodes; ++i) indptr[i + 1] = indptr[i] + counts[i];
    std::vector<int64_t> cursor(indptr, indptr + n_nodes);
    for (int64_t e = 0; e < n_edges; ++e) {
      int64_t slot = cursor[rows[e]]++;
      indices[slot] = (int32_t)cols[e];
      if (eid) eid[slot] = e;
    }
  };
  // uint32 chunk cursors assume per-row degrees < 2^32
  if (max_threads() <= 1 || n_edges >= (int64_t)UINT32_MAX) {
    serial();
    return;
  }
  std::vector<std::atomic<int64_t>> counts(n_nodes);
  for (int64_t i = 0; i < n_nodes; ++i)
    counts[i].store(0, std::memory_order_relaxed);
#pragma omp parallel for schedule(static)
  for (int64_t e = 0; e < n_edges; ++e)
    counts[rows[e]].fetch_add(1, std::memory_order_relaxed);
  indptr[0] = 0;
  for (int64_t i = 0; i < n_nodes; ++i)
    indptr[i + 1] = indptr[i] + counts[i].load(std::memory_order_relaxed);

  // cap the T*N cursor matrix at ~1GB
  int T = max_threads();
  int64_t t_cap = ((int64_t)1 << 30) / (4 * std::max(n_nodes, (int64_t)1));
  if (t_cap < T) T = (int)std::max(t_cap, (int64_t)1);
  if (T <= 1) {
    std::vector<int64_t> cursor(indptr, indptr + n_nodes);
    for (int64_t e = 0; e < n_edges; ++e) {
      int64_t slot = cursor[rows[e]]++;
      indices[slot] = (int32_t)cols[e];
      if (eid) eid[slot] = e;
    }
    return;
  }

  // chunk boundaries over the edge list
  std::vector<int64_t> chunk(T + 1);
  for (int t = 0; t <= T; ++t) chunk[t] = n_edges * t / T;

  // per-(chunk,row) histogram; c[t*n_nodes + r]
  std::vector<uint32_t> c((size_t)T * n_nodes, 0);
#pragma omp parallel for schedule(static) num_threads(T)
  for (int t = 0; t < T; ++t) {
    uint32_t* ct = c.data() + (size_t)t * n_nodes;
    for (int64_t e = chunk[t]; e < chunk[t + 1]; ++e) ct[rows[e]]++;
  }
  // exclusive scan across chunks, per row
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < n_nodes; ++r) {
    uint32_t running = 0;
    for (int t = 0; t < T; ++t) {
      uint32_t tmp = c[(size_t)t * n_nodes + r];
      c[(size_t)t * n_nodes + r] = running;
      running += tmp;
    }
  }
  // stable scatter: thread t owns chunk t and its cursor row
#pragma omp parallel for schedule(static) num_threads(T)
  for (int t = 0; t < T; ++t) {
    uint32_t* ct = c.data() + (size_t)t * n_nodes;
    for (int64_t e = chunk[t]; e < chunk[t + 1]; ++e) {
      int64_t r = (int64_t)rows[e];
      int64_t slot = indptr[r] + (int64_t)(ct[r]++);
      indices[slot] = (int32_t)cols[e];
      if (eid) eid[slot] = e;
    }
  }
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// CSR construction: counting sort by row, O(E) and parallel (vs the numpy
// argsort path's O(E log E) single thread). eid keeps the CSR-slot -> COO
// position mapping.
// ---------------------------------------------------------------------------
void csr_from_coo_i64(const int64_t* rows, const int64_t* cols, int64_t n_edges,
                      int64_t n_nodes, int64_t* indptr /* n_nodes+1 */,
                      int32_t* indices, int64_t* eid) {
  csr_from_coo_impl(rows, cols, n_edges, n_nodes, indptr, indices, eid);
}

void csr_from_coo_i32(const int32_t* rows, const int32_t* cols, int64_t n_edges,
                      int64_t n_nodes, int64_t* indptr, int32_t* indices,
                      int64_t* eid) {
  csr_from_coo_impl(rows, cols, n_edges, n_nodes, indptr, indices, eid);
}

// ---------------------------------------------------------------------------
// Host feature gather: parallel row memcpy out of the (pinned) host table —
// the cold-tier service loop. row_bytes lets one entry point cover any dtype.
// Negative ids produce zero rows (the -1 sentinel contract).
// ---------------------------------------------------------------------------
void gather_rows_bytes(const uint8_t* table, int64_t n_rows, int64_t row_bytes,
                       const int64_t* ids, int64_t n_ids, uint8_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n_ids; ++i) {
    int64_t id = ids[i];
    uint8_t* dst = out + i * row_bytes;
    if (id < 0 || id >= n_rows)
      std::memset(dst, 0, row_bytes);
    else
      std::memcpy(dst, table + id * row_bytes, row_bytes);
  }
}

// ---------------------------------------------------------------------------
// CPU reservoir sampler: per-seed uniform without-replacement neighbor
// sampling with the padded (n_seeds, k) / -1 contract. Parity with the
// reference CPU tier (quiver.cpp:20-37, std::sample over quiver.cpu.hpp).
// ---------------------------------------------------------------------------
void sample_neighbors_cpu(const int64_t* indptr, const int32_t* indices,
                          const int32_t* seeds, int64_t n_seeds, int32_t k,
                          uint64_t seed, int32_t* out /* n_seeds*k */,
                          int32_t* counts /* n_seeds */) {
#pragma omp parallel
  {
    // per-thread reservoir buffer, reused across rows (no per-row malloc)
    std::vector<int64_t> res(k);
#pragma omp for schedule(dynamic, 64)
    for (int64_t i = 0; i < n_seeds; ++i) {
      int32_t* row_out = out + i * k;
      std::fill(row_out, row_out + k, -1);
      int32_t s = seeds[i];
      if (s < 0) {
        counts[i] = 0;
        continue;
      }
      int64_t lo = indptr[s], hi = indptr[s + 1];
      int64_t deg = hi - lo;
      if (deg <= k) {
        for (int64_t j = 0; j < deg; ++j) row_out[j] = indices[lo + j];
        counts[i] = (int32_t)deg;
      } else {
        // per-row RNG keyed on (seed, row index) so results are
        // reproducible regardless of thread count or schedule
        std::mt19937_64 rng((seed + 1) * 0x9E3779B97F4A7C15ULL ^
                            (uint64_t)i * 0xBF58476D1CE4E5B9ULL);
        for (int32_t j = 0; j < k; ++j) res[j] = j;
        for (int64_t j = k; j < deg; ++j) {
          std::uniform_int_distribution<int64_t> d(0, j);
          int64_t p = d(rng);
          if (p < k) res[p] = j;
        }
        for (int32_t j = 0; j < k; ++j) row_out[j] = indices[lo + res[j]];
        counts[i] = k;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Hash-based order-preserving reindex: frontier = unique(seeds ∪ neighbors)
// with seeds forced first (duplicates kept as distinct slots), neighbor lanes
// rewritten to frontier-local ids. Native parity with the reference's
// CPUQuiver::reindex_group (quiver.cpp:39-84) under this framework's padded
// (-1 sentinel) contract. Serial hash pass (like the reference's); the
// OpenMP pass only rewrites lanes.
// Returns the frontier length. frontier must have room for
// n_seeds + n_seeds*k entries (worst case).
// ---------------------------------------------------------------------------
int64_t reindex_cpu(const int32_t* seeds, int64_t n_seeds,
                    const int32_t* neighbors /* n_seeds*k */, int32_t k,
                    int32_t* frontier /* cap >= n_seeds*(k+1) */,
                    int32_t* col /* n_seeds*k */) {
  std::unordered_map<int32_t, int32_t> first;
  first.reserve((size_t)(n_seeds * (k + 1)));
  int64_t m = 0;
  // forced seed lanes: every valid seed occupies its own slot; the map keeps
  // the FIRST occurrence so later duplicates resolve to it. Intentional
  // divergence from the reference's CPUQuiver::reindex_group (quiver.cpp:56),
  // which overwrites so duplicate seeds map to the LAST slot — this repo's
  // first-occurrence rule matches its own XLA reindex_layer (masked_unique),
  // which is what this path is differential-tested against.
  for (int64_t i = 0; i < n_seeds; ++i) {
    int32_t s = seeds[i];
    if (s < 0) continue;
    frontier[m] = s;
    first.emplace(s, (int32_t)m);
    ++m;
  }
  for (int64_t i = 0; i < n_seeds * k; ++i) {
    int32_t v = neighbors[i];
    if (v < 0) continue;
    auto it = first.emplace(v, (int32_t)m);
    if (it.second) frontier[m++] = v;
  }
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n_seeds * k; ++i) {
    int32_t v = neighbors[i];
    col[i] = v < 0 ? -1 : first.find(v)->second;
  }
  return m;
}

// ---------------------------------------------------------------------------
// Degree computation (indptr diff) — trivial but keeps preprocessing native.
// ---------------------------------------------------------------------------
void degrees_i64(const int64_t* indptr, int64_t n_nodes, int64_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n_nodes; ++i) out[i] = indptr[i + 1] - indptr[i];
}

int quiver_host_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
