"""Headline benchmark supervisor: sampled edges per second on the real chip.

Round-3 discipline (VERDICT r2 item 1): two rounds of benches died rc=1 with
no JSON because a failure *after* backend init — the first jit compile — was
unguarded. This supervisor never imports jax. It runs the measured body
(``benchmarks.bench_sampler``, the single source of truth for the SEPS
methodology — see benchmarks/README.md) in a watchdogged subprocess and
guarantees exactly ONE parseable JSON line on stdout and rc=0:

1. probe the backend in a throwaway subprocess under a short timeout (a hung
   tunnel costs minutes, not the full attempt budget), then settle briefly
   so the probe's chip hold is released before the child's own init (the
   r02 failure — probe ok, first compile UNAVAILABLE seconds later — smells
   like exactly that hold/release race);
2. run the child on the default backend under a hard timeout;
3. if the child *errored* (fast), retry once after a delay — transient
   single-chip contention; if it *hung* (slow), don't burn a second full
   budget on a dead tunnel;
4. on exhaustion, re-run pinned to CPU in smoke mode (a labeled degraded
   number beats no number);
5. if even that fails, emit a diagnostic JSON line from this process.

Headline config: products-scale synthetic power-law graph, fanout [15,10,5],
batch 2048, HBM-resident topology. ``vs_baseline`` is against the
reference's 34.29M 1-GPU UVA SEPS (docs/Introduction_en.md:41).
"""

import json
import os
import subprocess
import sys
import time

# lean headline: the three-way dedup self-selection WITHOUT the --stages
# attribution phase (that is the scoreboard's sampler-stages job now) — the
# r4 window lesson is that one monolithic first job risks the whole budget
CHILD = ["-m", "benchmarks.bench_sampler", "--stream", "128",
         "--dedup", "both"]
# one real-chip attempt budget: first jit compile alone is 20-40s; the
# products-scale graph build is ~10s; 50 measured iters a few seconds.
ATTEMPT_TIMEOUT = float(os.environ.get("QUIVER_BENCH_TIMEOUT", 1500))
PROBE_TIMEOUT = float(os.environ.get("QUIVER_BENCH_PROBE_TIMEOUT", 240))
# grant starvation guard: the plugin blocks FOREVER at backend init when
# the tunnel serves no grant (r4: a 30-min attempt budget burned entirely
# at init). If the child hasn't logged "backend ok" within this window,
# kill it — a process blocked at init holds no grant, so this is safe.
INIT_TIMEOUT = float(os.environ.get("QUIVER_BENCH_INIT_TIMEOUT", 300))
RETRY_DELAY = float(os.environ.get("QUIVER_BENCH_RETRY_DELAY", 30))
SETTLE_S = float(os.environ.get("QUIVER_BENCH_SETTLE", 5))

# the image's sitecustomize pins the TPU plugin before env vars are read,
# so JAX_PLATFORMS=cpu must be re-applied via jax.config (same workaround as
# tests/conftest.py and benchmarks.common.init_backend)
_PROBE_SRC = (
    "import os, jax;"
    "p = [x.strip().lower() for x in"
    " os.environ.get('JAX_PLATFORMS', '').split(',') if x.strip()];"
    "p == ['cpu'] and jax.config.update('jax_platforms', 'cpu');"
    "import jax.numpy as jnp;"
    "jnp.zeros(8).block_until_ready();"
    "print(jax.devices()[0].platform, flush=True)"
)


def _log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _env(overrides):
    env = dict(os.environ)
    env.update(overrides)
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else repo_root
    )
    return env


def _probe(timeout_s):
    """Backend reachable? (ok, detail) from a throwaway subprocess."""
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s, env=_env({}),
        )
    except subprocess.TimeoutExpired:
        return False, f"probe hung > {timeout_s:.0f}s (tunnel unresponsive)"
    if r.returncode != 0:
        return False, (r.stderr or r.stdout).strip()[-400:]
    return True, f"{r.stdout.strip()} in {time.time() - t0:.1f}s"


HEADLINE_METRIC = "sampled-edges/sec/chip"


def _all_records(text: str):
    recs = []
    for line in (text or "").strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                recs.append(rec)
    return recs


def _split_records(text: str):
    """(headline record | None, other records). The headline is the first
    SEPS record — extra records (--stages rows) may follow it — else the
    last parseable record."""
    recs = _all_records(text)
    if not recs:
        return None, []
    for i, rec in enumerate(recs):
        if rec["metric"] == HEADLINE_METRIC:
            return rec, recs[:i] + recs[i + 1:]
    return recs[-1], recs[:-1]


def _attempt(extra_args, env_overrides, timeout_s, label, init_timeout=None):
    """Run the measured child once. Returns (record|None, error, hung).

    ``init_timeout``: if set, the child must log "backend ok" (its
    init_backend marker) within that window or it is killed — a child
    blocked at backend init holds no grant, so killing it is safe and
    turns a silent grant-starved stall into a fast, labeled failure.
    """
    import shutil
    import tempfile

    env = _env(env_overrides)
    # the child is watchdogged HERE: it must skip its own subprocess probe
    # (slow, and briefly holds the single chip right before the child's
    # init) and fail fast instead of self-healing, so WE control fallback.
    env["QUIVER_BENCH_SUPERVISED"] = "1"
    repo_root = os.path.dirname(os.path.abspath(__file__))
    argv = [sys.executable] + CHILD + extra_args + sys.argv[1:]
    _log(f"{label}: {' '.join(argv[1:])}")
    t0 = time.time()
    # child output goes to named files; the parent reads through SEPARATE
    # handles — handing the parent's own handle to Popen would share one
    # file description, so a parent seek would move the child's write
    # offset and clobber its output mid-run
    tmpdir = tempfile.mkdtemp(prefix="bench_attempt_")
    out_path = os.path.join(tmpdir, "out")
    err_path = os.path.join(tmpdir, "err")
    marker = b"backend ok"
    try:
        with open(out_path, "wb") as child_out, \
                open(err_path, "wb") as child_err:
            proc = subprocess.Popen(argv, stdout=child_out, stderr=child_err,
                                    env=env, cwd=repo_root)
        inited = init_timeout is None
        timed_out = starved = False
        seen = 0
        tail = b""
        try:
            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                el = time.time() - t0
                if not inited:
                    # incremental read; keep a marker-sized overlap so a
                    # marker split across two reads still matches
                    with open(err_path, "rb") as fh:
                        fh.seek(seen)
                        chunk = fh.read()
                    seen += len(chunk)
                    if marker in tail + chunk:
                        inited = True
                    else:
                        tail = (tail + chunk)[-(len(marker) - 1):]
                    if not inited and el > init_timeout:
                        starved = True
                        break
                if el > timeout_s:
                    timed_out = True
                    break
                time.sleep(5)
        finally:
            if proc.poll() is None:
                # kill discipline (mirrors mega_loop.kill_tree): a child
                # past backend init holds the grant, and a SIGKILLed holder
                # wedges the chip ~10 min — INT first with a grace period,
                # then escalate. A pre-init child holds nothing; INT-first
                # costs only the grace.
                import signal

                try:
                    proc.send_signal(signal.SIGINT)
                    proc.wait(30 if inited else 10)
                except (OSError, subprocess.TimeoutExpired):
                    proc.terminate()
                    try:
                        proc.wait(30)
                    except subprocess.TimeoutExpired:
                        proc.kill()
            proc.wait()  # always reap
        with open(out_path, "rb") as fh:
            out = fh.read().decode("utf-8", "replace")
        with open(err_path, "rb") as fh:
            errtext = fh.read().decode("utf-8", "replace")
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    if starved:
        sys.stderr.write(errtext[-2000:])
        _log(f"{label}: no backend init within {init_timeout:.0f}s — "
             "grant starved (killed; no grant was held)")
        return None, f"backend init starved > {init_timeout:.0f}s", False
    if timed_out:
        sys.stderr.write(errtext[-2000:])
        # the child may have emitted the headline BEFORE hanging (e.g. in
        # a secondary phase) — a measured number must never be discarded
        # because a later phase overran the watchdog
        rec, extras = _split_records(out)
        if rec is not None:
            for x in extras:
                _log(f"extra: {json.dumps(x)}")
            _log(f"{label}: headline ok, then hung > {timeout_s:.0f}s "
                 "(killed; keeping the measurement)")
            return rec, None, False
        _log(f"{label}: hung > {timeout_s:.0f}s (killed)")
        return None, f"timeout>{timeout_s:.0f}s", True
    sys.stderr.write(errtext[-4000:])
    rec, extras = _split_records(out)
    dt = time.time() - t0
    if rec is not None:
        # secondary records (extra dedup-strategy rows) ride in stderr so
        # the driver's tail log keeps them without disturbing the one-line
        # stdout contract
        for x in extras:
            _log(f"extra: {json.dumps(x)}")
        _log(f"{label}: ok in {dt:.0f}s")
        return rec, None, False
    err = (errtext or out).strip()[-600:] or f"rc={proc.returncode}, no output"
    _log(f"{label}: failed rc={proc.returncode} in {dt:.0f}s")
    return None, err, False


def _stale_headline(reason):
    """Last-good TPU headline from the committed ledger, labeled stale.

    A dead tunnel at snapshot time must never erase a real measurement
    again (the r3 failure: 9.70M TPU SEPS survived only as markdown while
    BENCH_r03.json recorded the CPU fallback). The measured child appends
    every successful TPU record to docs/tpu_ledger.jsonl at emit time; this
    re-surfaces the newest one when a fresh attempt degrades.
    """
    try:
        from benchmarks import ledger

        # the headline methodology is fused-stream dispatch at products
        # scale (per-call measures the tunnel, not the chip; smoke rows are
        # sanity checks). Best-by-value: a --dedup both run ledgers both
        # variants and the winner must not be displaced by the loser.
        rec = (ledger.best_good(HEADLINE_METRIC, min_nodes=2_000_000,
                                dispatch="stream")
               or ledger.best_good(HEADLINE_METRIC, min_nodes=2_000_000))
    except Exception:  # noqa: BLE001 — fallback plumbing must not crash
        return None
    if rec is None:
        return None
    out = dict(rec)
    out["stale"] = out.pop("ts", "unknown")
    out["stale_reason"] = f"fresh attempt degraded: {str(reason)[:200]}"
    return out


def main():
    errors = []
    for n in (1, 2):
        if n == 2:
            _log(f"retrying in {RETRY_DELAY:.0f}s (transient chip contention?)")
            time.sleep(RETRY_DELAY)
        ok, detail = _probe(PROBE_TIMEOUT)
        _log(f"attempt {n} probe: {'ok ' + detail if ok else detail}")
        if not ok:
            errors.append(f"probe: {detail}")
            continue
        time.sleep(SETTLE_S)  # let the probe's chip hold fully release
        rec, err, hung = _attempt([], {}, ATTEMPT_TIMEOUT,
                                  f"attempt {n} (default backend)",
                                  init_timeout=INIT_TIMEOUT)
        if rec is not None:
            print(json.dumps(rec), flush=True)
            return 0
        errors.append(err)
        if hung:
            # a hang AFTER a successful probe: the tunnel died mid-run;
            # don't burn a second full budget on it
            _log("attempt hung after a good probe; skipping the retry")
            break

    # the stale label must cite why the CHIP measurement failed, not any
    # later unrelated failure of the CPU smoke itself
    tpu_reason = errors[-1] if errors else "unknown"
    rec, err, _ = _attempt(
        ["--smoke"],
        {"JAX_PLATFORMS": "cpu",
         "QUIVER_BENCH_DEGRADED": f"supervisor fallback: {errors[-1][:200]}"
         if errors else "supervisor fallback"},
        min(ATTEMPT_TIMEOUT, 600),
        "fallback (CPU smoke)",
    )
    if rec is None:
        errors.append(err)
    stale = _stale_headline(tpu_reason)
    if stale is not None:
        # headline = the last REAL TPU measurement (labeled stale); the
        # fresh degraded smoke rides in stderr so the one-line stdout
        # contract still carries a tpu-platform number
        if rec is not None:
            _log(f"fresh degraded record: {json.dumps(rec)}")
        _log(f"re-emitting last-good TPU headline (measured {stale['stale']})")
        print(json.dumps(stale), flush=True)
        return 0
    if rec is not None:
        print(json.dumps(rec), flush=True)
        return 0

    # absolute last resort: the supervisor itself emits the labeled line so
    # the round still records a parseable result.
    print(json.dumps({
        "metric": "sampled-edges/sec/chip",
        "value": 0.0,
        "unit": "SEPS",
        "vs_baseline": 0.0,
        "platform": "none",
        "degraded": "all attempts failed",
        "errors": [str(e)[:300] for e in errors],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
