"""Headline benchmark: sampled edges per second (SEPS) on the real chip.

Methodology mirrors the reference's bench_sampler.py:33-43 (SEPS = total
sampled edges / synchronized wall time) on a products-scale synthetic
power-law graph (the reference's dataset-free Pareto generator pattern,
benchmarks/generated_graph/gen_graph.py). Per BASELINE.md, padded lanes are
NOT counted — only valid (unmasked) edges — keeping the comparison against
the reference's ragged outputs honest.

Baseline: 34.29M SEPS — the reference's 1-GPU UVA number on ogbn-products,
fanout [15,10,5] (docs/Introduction_en.md:41). We run the HBM-resident mode
(reference "GPU" mode) because that is the TPU-idiomatic placement for a
graph this size; the reference's own GPU mode is +30-40% over its UVA
number (docs/Introduction_en.md:45).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import argparse
import json
import sys
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=2_450_000)  # ogbn-products scale
    p.add_argument("--avg-degree", type=float, default=50.5)  # products: 123.7M/2.45M
    p.add_argument("--batch", type=int, default=2048)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--mode", default="GPU", choices=["GPU", "UVA"])
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo, GraphSageSampler
    from quiver_tpu.utils.graphgen import generate_pareto_graph

    t0 = time.time()
    ei = generate_pareto_graph(args.nodes, args.avg_degree, seed=args.seed)
    topo = CSRTopo(edge_index=ei)
    del ei
    print(
        f"graph: {topo.node_count} nodes, {topo.edge_count} edges "
        f"({time.time()-t0:.1f}s build); device={jax.devices()[0]}",
        file=sys.stderr,
    )

    sampler = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=args.batch, seed=args.seed
    )
    rng = np.random.default_rng(args.seed)

    # warmup (includes compile)
    t0 = time.time()
    for _ in range(args.warmup):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
    jax.block_until_ready(out.n_id)
    print(f"warmup+compile: {time.time()-t0:.1f}s", file=sys.stderr)

    # timed loop; count only valid edges (mask sum), per BASELINE.md
    total_edges = 0
    t0 = time.time()
    for _ in range(args.iters):
        seeds = rng.integers(0, topo.node_count, args.batch)
        out = sampler.sample(seeds)
        for adj in out.adjs:
            total_edges += int(jnp.sum(adj.edge_index[0] >= 0))
    jax.block_until_ready(out.n_id)
    dt = time.time() - t0

    seps = total_edges / dt
    baseline = 34.29e6  # reference 1-GPU UVA SEPS, products [15,10,5]
    print(
        json.dumps(
            {
                "metric": "sampled-edges/sec/chip",
                "value": round(seps, 1),
                "unit": "SEPS",
                "vs_baseline": round(seps / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
