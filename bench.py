"""Headline benchmark: sampled edges per second (SEPS) on the real chip.

Thin wrapper over ``benchmarks.bench_sampler`` (single source of truth for
the SEPS methodology — see benchmarks/README.md) with the headline config as
defaults: products-scale synthetic power-law graph, fanout [15,10,5], batch
2048, HBM-resident topology. Prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline", ...}`` with vs_baseline against
the reference's 34.29M 1-GPU UVA SEPS (docs/Introduction_en.md:41).
"""

from benchmarks.bench_sampler import main

if __name__ == "__main__":
    main()
