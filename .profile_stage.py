import time, numpy as np, jax, jax.numpy as jnp
from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.ops.sample import sample_layer
from quiver_tpu.ops.reindex import reindex_layer
from quiver_tpu.utils.graphgen import generate_pareto_graph

ei = generate_pareto_graph(2_450_000, 50.5, seed=0)
topo_h = CSRTopo(edge_index=ei); del ei
topo = topo_h.to_device("HBM")
rng = np.random.default_rng(0)
key = jax.random.PRNGKey(0)

def bench(name, fn, *args, iters=10):
    f = jax.jit(fn)
    out = jax.block_until_ready(f(*args))
    t0=time.time()
    for _ in range(iters): out = f(*args)
    jax.block_until_ready(out)
    print(f"{name}: {(time.time()-t0)/iters*1e3:.2f} ms")
    return out

# L3-like: S=360448 seeds (simulate valid 163k), k=5
S = 360_448
seeds = np.full(S, -1, np.int32); n_valid = 163_000
seeds[:n_valid] = rng.integers(0, topo_h.node_count, n_valid)
seeds = jnp.asarray(seeds)
nbr, cnt = bench("L3 sample_layer (S=360k,k=5)", lambda t,s,n,k_: sample_layer(t,s,n,5,k_), topo, seeds, jnp.int32(n_valid), key)
bench("L3 reindex_layer (T=2.16M)", lambda s,n,nb: reindex_layer(s,n,nb,2_162_688), seeds, jnp.int32(n_valid), nbr)

# L2-like: S=32768, k=10
S2=32_768
seeds2 = np.full(S2, -1, np.int32); nv2=21_000
seeds2[:nv2] = rng.integers(0, topo_h.node_count, nv2)
seeds2 = jnp.asarray(seeds2)
nbr2, cnt2 = bench("L2 sample_layer (S=32k,k=10)", lambda t,s,n,k_: sample_layer(t,s,n,10,k_), topo, seeds2, jnp.int32(nv2), key)
bench("L2 reindex_layer (T=360k)", lambda s,n,nb: reindex_layer(s,n,nb,360_448), seeds2, jnp.int32(nv2), nbr2)
