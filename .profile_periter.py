import time, numpy as np, jax, jax.numpy as jnp
from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.utils.graphgen import generate_pareto_graph

ei = generate_pareto_graph(2_450_000, 50.5, seed=0)
topo = CSRTopo(edge_index=ei); del ei
rng = np.random.default_rng(0)
s = GraphSageSampler(topo, [15,10,5], seed_capacity=2048, seed=0)
out = s.sample(rng.integers(0, topo.node_count, 2048))
jax.block_until_ready(out.n_id)
for it in range(12):
    t0=time.time()
    seeds = rng.integers(0, topo.node_count, 2048)
    t1=time.time()
    out = s.sample(seeds)
    t2=time.time()
    jax.block_until_ready(out.n_id)
    t3=time.time()
    print(f"iter {it}: seedgen {1e3*(t1-t0):.1f} dispatch {1e3*(t2-t1):.1f} block {1e3*(t3-t2):.1f} ms")
# now same seeds every iter
seeds = rng.integers(0, topo.node_count, 2048)
for it in range(4):
    t0=time.time(); out = s.sample(seeds); jax.block_until_ready(out.n_id)
    print(f"same-seeds iter {it}: {1e3*(time.time()-t0):.1f} ms")
