#!/bin/bash
# Regenerate the CPU-floor scoreboard rows (docs/cpu_floor/) — the
# dispatch-clean lower-bound evidence used when the chip is unreachable.
#
# The floor is NOT a TPU claim: every row lands platform=cpu. Its role is
# (a) proving each measurement path end-to-end at full products scale so a
# chip window is spent measuring, not debugging, and (b) ranking config
# alternatives (dedup map-vs-sort, dtype tiers, routed-vs-psum) on
# dispatch-clean stream/scan modes. Multi-device rows (shard/routed) run on
# the 8-virtual-device CPU mesh.
#
# Usage: bash scripts/cpu_floor.sh [job ...]   (default: the feature set)
set -u
cd "$(dirname "$0")/.."
JOBS=("$@")
if [ ${#JOBS[@]} -eq 0 ]; then
  JOBS=(feature-replicate feature-replicate-xla feature-bf16 feature-int8
        feature-shard-routed feature-shard-routed-capped)
fi
JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
QUIVER_BENCH_TIMEOUT="${QUIVER_BENCH_TIMEOUT:-2400}" \
python -m benchmarks.scoreboard --only "${JOBS[@]}" --out docs/cpu_floor
