"""Step-timed TPU probe: prints wall time for each stage so a silent
tunnel stall can be localized (backend init vs transfer vs compile vs run).

Each stage prints BEFORE it starts (flushed), so a hang is attributable to
the named stage even if the process never returns.
"""

import time
import sys

T0 = time.time()


def mark(msg):
    print(f"[probe +{time.time() - T0:7.1f}s] {msg}", flush=True)


mark("importing jax")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

mark("touching backend (jax.devices())")
d = jax.devices()
mark(f"devices: {d} platform={d[0].platform}")

mark("tiny transfer (8 floats)")
x = jnp.zeros(8)
x.block_until_ready()
mark("tiny transfer done")

mark("tiny jit (x+1)")
y = jax.jit(lambda a: a + 1)(x)
y.block_until_ready()
mark("tiny jit done")

mark("1M-elem transfer")
import numpy as np  # noqa: E402

big = jnp.asarray(np.arange(1_000_000, dtype=np.int32))
big.block_until_ready()
mark("1M transfer done")

mark("medium jit (sort 1M)")
s = jax.jit(jnp.sort)(big)
s.block_until_ready()
mark("medium jit done")

mark("D2H readback (1 scalar)")
v = int(s[-1])
mark(f"readback done ({v})")

mark("medium jit 2 (argsort+cummax 1M)")


@jax.jit
def f(a):
    o = jnp.argsort(a)
    return jax.lax.cummax(a[o], axis=0)


r = f(big)
r.block_until_ready()
mark("medium jit 2 done")

mark("ALL OK")
sys.exit(0)
