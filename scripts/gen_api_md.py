"""Regenerate docs/API.md — the public-surface index.

Walks each (module, title) pair below, imports it on the CPU backend, and
tables every ``__all__`` export with the first line of its docstring.
Run after adding/renaming exports:

    JAX_PLATFORMS=cpu python scripts/gen_api_md.py
"""

import importlib
import inspect
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SECTIONS = [
    ("quiver_tpu", "Package root (reference: quiver/__init__.py exports)"),
    ("quiver_tpu.core.topology", "Graph topology (CSRTopo, device placement)"),
    ("quiver_tpu.core.sharded_topology",
     "Mesh-sharded topology (CSR partitioned across chips)"),
    ("quiver_tpu.core.hetero_sharded",
     "Mesh-sharded heterogeneous topology (per-relation partitions)"),
    ("quiver_tpu.core.config", "Config enums + byte-size parser"),
    ("quiver_tpu.core.memory", "Device/host memory placement"),
    ("quiver_tpu.sampling", "Public sampling surface (the sampler family)"),
    ("quiver_tpu.sampling.sampler", "GraphSageSampler (homo)"),
    ("quiver_tpu.sampling.dist",
     "Distributed sampler over a mesh-sharded topology"),
    ("quiver_tpu.sampling.hetero", "Heterogeneous sampler"),
    ("quiver_tpu.sampling.dist_hetero",
     "Distributed heterogeneous sampler (shared route plan per hop/type)"),
    ("quiver_tpu.sampling.saint", "GraphSAINT samplers"),
    ("quiver_tpu.feature.feature", "Tiered feature store"),
    ("quiver_tpu.feature.shard", "Mesh-sharded feature store"),
    ("quiver_tpu.models", "Model families + layer-wise inference"),
    ("quiver_tpu.parallel.mesh", "Device mesh / clique topology"),
    ("quiver_tpu.parallel.routing",
     "Capped-bucket owner routing (shared comm core)"),
    ("quiver_tpu.parallel.trainer", "Distributed fused trainer"),
    ("quiver_tpu.parallel.train", "Single-chip train step helpers"),
    ("quiver_tpu.parallel.pipeline",
     "Prefetcher + pipelined-epoch batch container"),
    ("quiver_tpu.resilience",
     "Fault tolerance — non-finite step guard, fault injection"),
    ("quiver_tpu.resilience.elastic",
     "Elastic mesh resilience — cross-mesh resume, circuit breaker"),
    ("quiver_tpu.resilience.integrity",
     "Checkpoint integrity — manifest schema, checksums, verification"),
    ("quiver_tpu.streaming",
     "Transactional streaming graph mutation — delta ingestion, atomic "
     "commits, versioned invalidation"),
    ("quiver_tpu.serving",
     "Online inference serving — deadline-aware micro-batching over "
     "AOT-compiled ladder programs"),
    ("quiver_tpu.serving.aot",
     "Persisted AOT executables — fingerprint-keyed disk cache for "
     "compile-free cold start"),
    ("quiver_tpu.serving.fleet",
     "Serving fleet — replica scale-out over one shared executable "
     "cache with SLO-class admission control"),
    ("quiver_tpu.control",
     "quiver-ctl — telemetry-driven cache & routing control plane"),
    ("quiver_tpu.ooc",
     "quiver-ooc — out-of-core disk tier: raw mmap-native format, "
     "disk-backed feature store, async window staging"),
    ("quiver_tpu.ops.sample", "Sampling ops (XLA)"),
    ("quiver_tpu.ops.reindex", "Dedup/reindex strategies"),
    ("quiver_tpu.models.layers", "Message-passing primitives"),
    ("quiver_tpu.ops.pallas.sample", "Pallas windowed sampler"),
    ("quiver_tpu.ops.pallas.gather", "Pallas row gather"),
    ("quiver_tpu.utils.reorder", "Degree-based feature reorder"),
    ("quiver_tpu.utils.checkpoint",
     "Atomic manifest checkpointing (integrity-verified)"),
    ("quiver_tpu.utils.trace", "Tracing/profiling scopes"),
    ("quiver_tpu.obs",
     "graftscope — metrics registry, step timeline, exporters"),
    ("quiver_tpu.obs.tracing",
     "grafttrace — causal spans + Chrome trace-event export"),
    ("quiver_tpu.obs.recorder",
     "grafttrace — black-box flight recorder, postmortem bundles"),
    ("quiver_tpu.obs.endpoint",
     "grafttrace — live telemetry HTTP endpoint"),
    ("quiver_tpu.datasets", "Dataset loaders + planted graphs"),
    ("quiver_tpu.tools.lint",
     "graftlint static analyzer (trace-safety rules)"),
    ("quiver_tpu.tools.audit",
     "graftaudit — jaxpr/HLO program auditor (lowered-IR invariants)"),
    ("quiver_tpu.tools.audit.mem",
     "graftmem — static per-device memory & layout accounting"),
    ("quiver_tpu.tools.sarif",
     "Shared SARIF plumbing (lint + audit, merged CI artifact)"),
]


def first_line(obj) -> str:
    doc = inspect.getdoc(obj) or ""
    line = doc.splitlines()[0].strip() if doc else ""
    # flax dataclass reprs embed object addresses — strip them so regens
    # are deterministic and diffs stay reviewable
    line = re.sub(r" object at 0x[0-9a-fA-F]+", " object", line)
    return line.replace("|", "\\|")


def main():
    out = [
        "# API index",
        "",
        "Auto-generated (`JAX_PLATFORMS=cpu python scripts/gen_api_md.py`); "
        "regenerate after adding exports.",
        "Public surface by module — first docstring line for each export.",
    ]
    for modname, title in SECTIONS:
        mod = importlib.import_module(modname)
        names = sorted(getattr(mod, "__all__", []))
        out += ["", f"## `{modname}` — {title}", "",
                "| Export | Summary |", "|---|---|"]
        for n in names:
            obj = getattr(mod, n, None)
            out.append(f"| `{n}` | {first_line(obj)} |")
    path = os.path.join(REPO, "docs", "API.md")
    with open(path, "w") as fh:
        fh.write("\n".join(out) + "\n")
    print(f"wrote {path}: {len(SECTIONS)} sections")


if __name__ == "__main__":
    main()
