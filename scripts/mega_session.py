"""Single-process chip-window evidence runner.

Round-4 lesson: on the tunneled chip every *process* needs its own device
grant, the plugin blocks silently (often forever) when the grant is not
served, and the grant appears to take ~10 minutes to be reclaimed after a
process exits. The subprocess-per-job scoreboard therefore spends a window
re-acquiring grants (or hanging) instead of measuring. This runner holds
ONE grant: it initializes the backend once, then runs every benchmark
in-process by calling each module's ``main()`` with a patched argv.

Discipline:

* ``INIT_OK`` is printed the moment the backend answers — the outer loop
  (scripts/mega_loop.py) kills a session that cannot print it within its
  init budget (safe: a process blocked at init holds no grant).
* Every job prints ``START <key> budget=<s>`` first and ``DONE <key>`` on
  completion; the outer loop enforces budget+grace on wall time because a
  wedged device RPC is not interruptible in-process.
* Job attempts/done-ness persist in a state file; a restarted session skips
  finished jobs, retries wedged ones once, then abandons them.
* Results are merged into docs/tpu_results.json + TPU_RESULTS.md after
  EVERY job (scoreboard.write_outputs merge mode) and all TPU records also
  land in docs/tpu_ledger.jsonl via the normal emit() path — a mid-window
  kill loses nothing.
"""

import argparse
import importlib
import io
import json
import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ["QUIVER_BENCH_SUPERVISED"] = "1"  # modules fail fast, no self-heal

T0 = time.time()


def mark(msg):
    print(f"[mega +{time.time() - T0:7.1f}s] {msg}", flush=True)


# Evidence order + per-job in-process budgets; module/argv/note come from
# benchmarks.scoreboard.JOBS (single source of truth — r4 review finding:
# two hand-maintained 24-entry tables WILL drift). The two non-scoreboard
# jobs (acceptance, sweep) are defined in EXTRA_JOBS.
ORDER = [
    # graftlint gate FIRST: a trace-safety/collective-consistency
    # regression fails the session before any chip-window time is burned
    # on benchmarks whose numbers a broken invariant would poison
    ("lint", 120),
    # graftaudit right after: the lowered-IR gate (collective parity,
    # metric stripping, donation claims, dtype discipline, comm budget)
    # is trace-only, so it proves the compiled-program invariants in
    # seconds before any chip time executes a step on top of them
    ("audit", 300),
    # graftmem right after graftaudit: the memory/budget gate is also
    # trace-only (CPU audit mesh) and its headline row — the tightest
    # hbm_budget headroom fraction — lands before any chip time burns
    ("memaudit", 420),
    # chaos drills right after lint: resilience regressions (guard,
    # retry, checkpoint/resume bit-parity, elastic resize, corrupt-
    # checkpoint fallback, cold-tier outage) fail the session early,
    # before bench budget burns on a stack that can't survive a bad
    # batch or a shrunk mesh
    ("chaos", 900),
    ("primitives", 600),
    ("sampler-hbm", 1800),
    ("feature-replicate", 1200),
    ("epoch-scan", 1800),
    # the pipelined row rides early: it measures four schedules in one
    # invocation (serial stages, prefetch, serial scan, pipelined scan),
    # so its overlap-efficiency evidence lands even in a short window
    ("epoch-pipelined", 1800),
    ("validation", 1200),
    ("sampler-pallas", 1200),
    ("sampler-fused-pallas", 1200),
    ("sampler-host", 1200),
    ("feature-replicate-xla", 900),
    ("feature-bf16", 900),
    ("feature-int8", 900),
    ("epoch-scan-host", 1500),
    ("sampler-weighted", 1500),
    ("epoch-fused-bf16", 1200),
    ("epoch-hbm", 1200),
    ("epoch-bf16", 1200),
    ("epoch-fused", 1200),
    ("epoch-host", 1200),
    ("sampler-stages", 1500),
    ("rgcn", 900),
    ("infer-layerwise", 900),
    ("serve-latency", 900),
    ("serve-fleet", 900),
    # out-of-core drill runs in a CPU subprocess (RLIMIT_AS is process-
    # wide and irreversible), so it burns no chip-window time
    ("feature-ooc", 900),
    ("saint-node", 900),
    ("feature-shard-routed", 900),
    ("feature-shard-routed-capped", 900),
    ("feature-threetier", 900),
    ("feature-controller", 900),
    ("sampler-sharded", 900),
    ("sampler-hetero-sharded", 900),
    ("acceptance", 1800),
    ("sweep", 2400),
]

EXTRA_JOBS = {
    "acceptance": ("examples.train_sage",
                   ["--dataset", "planted:50000", "--epochs", "3"]),
    "sweep": ("benchmarks.sweep_sampler", ["--stream", "64"]),
    # absolute paths: the runner's cwd is not guaranteed to be the repo
    "lint": ("quiver_tpu.tools.lint",
             [os.path.join(REPO, d)
              for d in ("quiver_tpu", "scripts", "benchmarks")]),
    # graftaudit over the full program registry — traces/lowers on the
    # session's backend, executes nothing; log-only, exits nonzero on a
    # lowered-IR invariant regression
    "audit": ("quiver_tpu.tools.audit", []),
    # FaultPlan smoke over a tiny epoch (guard skip, prefetch retry,
    # preempt/resume bit-parity) — log-only, asserts its own invariants
    "chaos": ("benchmarks.chaos", []),
}


def job_table():
    """(key, module, argv, budget) in ORDER, sourced from scoreboard.JOBS."""
    from benchmarks import scoreboard

    by_key = {key: (mod, argv) for key, mod, argv, _n in scoreboard.JOBS}
    by_key.update(EXTRA_JOBS)
    ordered = {k for k, _b in ORDER}
    missing = [k for k, _b in ORDER if k not in by_key]
    if missing:
        raise SystemExit(f"ORDER keys missing from scoreboard.JOBS: {missing}")
    unordered = [k for k in by_key if k not in ordered]
    if unordered:
        # both directions fail loudly: a job added to the scoreboard but
        # not given a budget/slot here would silently skip chip windows
        raise SystemExit(f"scoreboard.JOBS keys missing from ORDER: "
                         f"{sorted(unordered)}")
    return [(k, by_key[k][0], list(by_key[k][1]), b) for k, b in ORDER]

# jobs whose records feed the scoreboard table (acceptance/sweep/lint/
# audit/chaos log-only)
TABLE_EXCLUDE = {"acceptance", "sweep", "lint", "audit", "chaos"}

# jobs that emit no {"metric": ...} records; success = clean exit alone
LOG_ONLY_JOBS = {"acceptance", "lint", "audit", "chaos"}


class JobTimeout(Exception):
    pass


def _alarm(signum, frame):
    raise JobTimeout()


class Tee(io.TextIOBase):
    """Mirror writes to the real stdout while keeping a harvestable copy."""

    def __init__(self, real):
        self.real = real
        self.buf = io.StringIO()

    def write(self, s):
        self.real.write(s)
        self.buf.write(s)
        return len(s)

    def flush(self):
        self.real.flush()


def _harvest(text):
    recs = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                recs.append(rec)
    return recs


def load_state(path):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {"done": [], "attempts": {}}


def save_state(path, state):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(state, fh, indent=1)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--state", default=os.path.join(REPO, "docs",
                                                   "mega_state.json"))
    p.add_argument("--out", default=os.path.join(REPO, "docs"))
    p.add_argument("--only", nargs="*", default=None)
    p.add_argument("--max-attempts", type=int, default=2)
    p.add_argument("--allow-cpu", action="store_true",
                   help="run even if the backend is not a TPU (rehearsal)")
    p.add_argument("--smoke", action="store_true",
                   help="rehearsal: tiny shapes on every job")
    args = p.parse_args()

    from benchmarks.common import _enable_compilation_cache

    _enable_compilation_cache()
    # graftscope artifact: every bench lane appends its registry snapshots
    # (tier hits, routed/sample overflow) to ONE metrics.jsonl per run —
    # durable telemetry evidence next to the scoreboard outputs. An
    # explicit QUIVER_METRICS_JSONL (or empty, to disable) wins.
    os.environ.setdefault(
        "QUIVER_METRICS_JSONL", os.path.join(args.out, "metrics.jsonl")
    )

    jobs = job_table()
    if args.only:
        unknown = set(args.only) - {k for k, *_ in jobs}
        if unknown:
            p.error(f"unknown job keys: {sorted(unknown)}")
    state = load_state(args.state)
    done = set(state["done"])
    todo = []
    for key, module, argv, budget in jobs:
        if args.only and key not in args.only:
            continue
        if key in done:
            continue
        if state["attempts"].get(key, 0) >= args.max_attempts:
            mark(f"SKIP {key}: {state['attempts'][key]} failed attempts")
            continue
        if args.smoke:
            if key == "acceptance":
                argv = ["--dataset", "planted:5000", "--epochs", "1"]
            elif module.startswith("benchmarks"):
                argv = list(argv) + ["--smoke"]
        if key == "lint":
            # machine-readable evidence next to the scoreboard outputs:
            # SARIF findings for CI-style annotation plus the reasoned-
            # suppression debt table in the session log (rule, file,
            # reason, commit age)
            argv = list(argv) + [
                "--sarif", os.path.join(args.out, "lint.sarif"), "--debt",
            ]
        if key == "audit":
            argv = list(argv) + [
                "--sarif", os.path.join(args.out, "audit.sarif"),
            ]
        todo.append((key, module, argv, budget))
    if not todo:
        mark("ALL DONE (nothing left to run)")
        return 0

    mark(f"{len(todo)} jobs queued: {[j[0] for j in todo]}")
    mark("backend init")
    import jax

    # an explicit JAX_PLATFORMS=cpu (rehearsal) must win over the image's
    # sitecustomize TPU pin — same workaround as tests/conftest.py
    plats = [s.strip().lower()
             for s in os.environ.get("JAX_PLATFORMS", "").split(",")
             if s.strip()]
    if plats == ["cpu"]:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    try:
        dev = jax.devices()[0]
        jnp.zeros(8).block_until_ready()
    except RuntimeError as e:
        # the plugin can fail fast (UNAVAILABLE after its internal retry
        # window) instead of blocking — surface it cleanly for the loop
        mark(f"INIT_FAILED {str(e)[:200]}")
        return 4
    mark(f"INIT_OK platform={dev.platform} kind="
         f"{getattr(dev, 'device_kind', '?')}")
    if dev.platform != "tpu" and not args.allow_cpu:
        mark("backend is not a TPU; exiting 3 (outer loop will retry)")
        return 3

    # heartbeat so humans (and the log) can see the process is alive during
    # multi-minute remote compiles; wall-budget enforcement is the outer
    # loop's job, keyed on the START lines
    hb_state = {"job": None, "since": time.time()}

    def heartbeat():
        while True:
            time.sleep(120)
            j = hb_state["job"]
            if j:
                mark(f"heartbeat: {j} running {time.time() - hb_state['since']:.0f}s")

    threading.Thread(target=heartbeat, daemon=True).start()

    from benchmarks import scoreboard

    notes = {key: note for key, _m, _a, note in scoreboard.JOBS}
    signal.signal(signal.SIGALRM, _alarm)

    for key, module, argv, budget in todo:
        state["attempts"][key] = state["attempts"].get(key, 0) + 1
        save_state(args.state, state)
        mark(f"START {key} budget={budget}")
        hb_state.update(job=key, since=time.time())
        t0 = time.time()
        tee = Tee(sys.stdout)
        old_stdout, old_argv = sys.stdout, sys.argv
        err = None
        try:
            sys.stdout = tee
            sys.argv = [module] + list(argv)
            signal.alarm(budget)
            mod = importlib.import_module(module)
            rc = mod.main()
            # only an integer return is an exit status (train_sage returns
            # its (accuracy, dataset) result tuple — that is success)
            if isinstance(rc, int) and rc != 0:
                err = f"rc={rc}"
        except JobTimeout:
            err = f"in-process budget {budget}s exceeded"
        except SystemExit as e:
            if e.code not in (None, 0):
                err = f"exit={e.code}"
        except KeyboardInterrupt:
            sys.stdout, sys.argv = old_stdout, old_argv
            signal.alarm(0)
            mark(f"INTERRUPTED during {key}")
            raise
        except Exception as e:  # noqa: BLE001 — one job must not end the pass
            err = f"{type(e).__name__}: {e}"
        finally:
            signal.alarm(0)
            sys.stdout, sys.argv = old_stdout, old_argv
        hb_state["job"] = None

        recs = _harvest(tee.buf.getvalue())
        dt = time.time() - t0
        # acceptance/lint are log-only jobs; sweep swallows per-config
        # errors and can return empty — keep it retryable then
        if not err and (recs or key in LOG_ONLY_JOBS):
            state["done"].append(key)
            save_state(args.state, state)
        mark(f"DONE {key}: {len(recs)} records in {dt:.0f}s"
             + (f" (error: {str(err)[:160]})" if err else ""))
        if key == "lint" and err:
            # fail FAST: a lint regression means some trace/collective
            # invariant broke — benchmark numbers measured on top of it
            # are not evidence; fix the tree, then rerun the session
            mark(f"LINT GATE FAILED ({str(err)[:120]}); aborting session "
                 "before burning bench budget")
            return 5
        if key == "audit":
            # one merged analyzer artifact next to the scoreboard outputs
            # (same shape CI uploads); merge_sarif_files skips missing
            # inputs, so a lint-only or audit-only pass still writes it
            try:
                from quiver_tpu.tools.sarif import merge_sarif_files

                merge_sarif_files(
                    [os.path.join(args.out, "lint.sarif"),
                     os.path.join(args.out, "audit.sarif")],
                    os.path.join(args.out, "analysis.sarif"),
                )
            except Exception as e:  # noqa: BLE001
                mark(f"sarif merge failed: {e}")
            if err:
                # fail FAST, same reasoning as the lint gate: a lowered-IR
                # invariant regression (collective parity, donation claim,
                # comm budget...) poisons every number measured on top of it
                mark(f"AUDIT GATE FAILED ({str(err)[:120]}); aborting "
                     "session before burning bench budget")
                return 6
        if key not in TABLE_EXCLUDE:
            job_result = {"key": key, "note": notes.get(key, ""),
                          "records": recs, "error": err,
                          "seconds": round(dt, 1), "smoke": args.smoke}
            try:
                import contextlib

                with contextlib.redirect_stdout(io.StringIO()):
                    scoreboard.write_outputs([job_result], args.out,
                                             smoke=args.smoke, merge=True)
            except Exception as e:  # noqa: BLE001
                mark(f"scoreboard write failed: {e}")

    mark("PASS COMPLETE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
