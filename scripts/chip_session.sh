#!/bin/bash
# Chip-window evidence pass. Since round 4 this is a thin wrapper over the
# single-grant runner:
#
#   scripts/mega_session.py  — ONE process, ONE device grant, every
#       benchmark run in-process in evidence order: primitives first (a
#       2-minute small-compile job proving grants+compiles flow before
#       anything big), then sampler-hbm — which IS the headline (the exact
#       bench.py child config: stream 128, --dedup both); its records land
#       in docs/tpu_ledger.jsonl, which the driver's round-end bench.py
#       re-emits. Per-job budgets + state; results merged into
#       docs/TPU_RESULTS.md and the ledger after every job.
#   scripts/mega_loop.py     — outer watchdog: kills a session that can't
#       init (grant starvation: the plugin blocks forever and holds no
#       grant, so the kill is safe) and one whose job wedges, retries with
#       backoff until the pass completes or the wall budget runs out.
#
# WHY (r4 window postmortem): every process needs its own grant from the
# tunnel; grants stall silently for 10+ minutes; the old probe-then-
# subprocess-per-job design burned a 30-minute headline budget entirely
# BLOCKED AT INIT, then queued 20 more jobs behind the same stall. One
# grant amortized across the whole pass + an init watchdog is the fix
# rehearsed and used in round 4.
#
# Rehearsal: CHIP_SESSION_REHEARSE=1 runs the whole pass forced-CPU at
# smoke scale into docs/rehearsal/ (cannot clobber TPU evidence).
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"

if [ "${CHIP_SESSION_REHEARSE:-0}" = "1" ]; then
  rm -f /tmp/mega_rehearsal_state.json
  JAX_PLATFORMS=cpu exec python scripts/mega_session.py \
    --allow-cpu --smoke \
    --state /tmp/mega_rehearsal_state.json --out docs/rehearsal
fi

exec python scripts/mega_loop.py --max-hours "${CHIP_SESSION_HOURS:-8}"
