#!/bin/bash
# Full chip session: probes the tunneled TPU until it answers, then runs
# the complete on-hardware evidence pass, HIGHEST-VALUE FIRST so a short
# window still lands the headline (r3 lesson: 90 usable minutes produced
# one headline and zero scoreboard rows because the long jobs ran first):
#   1. headline    -> repo-root bench.py (dedup self-selection, stream SEPS;
#                     every TPU record also lands in docs/tpu_ledger.jsonl)
#   2. scoreboard  -> docs/TPU_RESULTS.md platform=tpu rows (jobs are
#                     themselves evidence-ordered; per-job budget below)
#   3. acceptance  -> planted-SBM training on-device
#   4. sweep       -> dedup x batch stream SEPS grid (longest; last)
#
# Kill discipline (docs/TPU_MEASUREMENTS_R3.md): a SIGKILLed TPU process
# wedges the chip ~10+ minutes. Budgets are IN-PROCESS where the harness
# has them (bench.py / scoreboard supervise their own children); the two
# bare jobs get `timeout -s INT` + a 60s grace so python unwinds instead
# of dying mid-grant — and even that SIGINT can wedge; budgets are sized
# so they fire only when the tunnel is already gone.
#
# Rehearsal (VERDICT r3 item 7): CHIP_SESSION_REHEARSE=1 skips the probe
# loop and runs the whole pass forced-CPU at smoke scale — proves the
# runner end-to-end so chip minutes are spent measuring, not debugging.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
ROUND="${ROUND:-r04}"
log(){ echo "[chip-session] $(date -u +%H:%M:%S) $*"; }

run_pass(){
  local smoke="$1"
  local sb_out="$2"
  log "=== 1. headline (bench.py) ==="
  QUIVER_BENCH_TIMEOUT="${QUIVER_BENCH_TIMEOUT:-1800}" \
    python bench.py $smoke > "docs/headline_${ROUND}.log" 2>&1
  log "headline rc=$? (docs/headline_${ROUND}.log)"
  grep -h '^{' "docs/headline_${ROUND}.log" | head -2

  log "=== 2. scoreboard ==="
  QUIVER_BENCH_TIMEOUT="${QUIVER_BENCH_TIMEOUT:-2400}" \
    python -m benchmarks.scoreboard $smoke $sb_out
  log "scoreboard rc=$? (${sb_out:-docs}/TPU_RESULTS.md)"

  log "=== 3. acceptance training (planted SBM) ==="
  timeout -s INT -k 60 2400 python -m examples.train_sage \
    --dataset "planted:${ACCEPT_NODES:-50000}" --epochs 3 \
    > "docs/acceptance_tpu_${ROUND}.log" 2>&1
  log "acceptance rc=$? (docs/acceptance_tpu_${ROUND}.log)"

  log "=== 4. sweep ==="
  QUIVER_BENCH_SUPERVISED=1 timeout -s INT -k 60 3600 \
    python -m benchmarks.sweep_sampler --stream "${SWEEP_STREAM:-64}" $smoke \
    > "docs/sweep_${ROUND}.log" 2>&1
  log "sweep rc=$? (docs/sweep_${ROUND}.log)"
  log "pass done"
}

if [ "${CHIP_SESSION_REHEARSE:-0}" = "1" ]; then
  log "REHEARSAL: forced-CPU smoke pass (no probe loop)"
  export JAX_PLATFORMS=cpu
  export QUIVER_BENCH_TIMEOUT="${QUIVER_BENCH_TIMEOUT:-600}"
  export ACCEPT_NODES="${ACCEPT_NODES:-20000}"
  export SWEEP_STREAM=8
  ROUND="${ROUND}-rehearsal"
  # --out keeps rehearsal CPU rows from clobbering the real TPU scoreboard
  run_pass "--smoke" "--out docs/rehearsal"
  exit 0
fi

for i in $(seq 1 "${CHIP_SESSION_PROBES:-400}"); do
  if timeout 240 python -c "
import jax, jax.numpy as jnp
jnp.zeros(8).block_until_ready()
assert jax.devices()[0].platform == 'tpu'" >/dev/null 2>&1; then
    log "chip answered on probe $i"
    sleep 10
    run_pass "" ""
    exit 0
  fi
  log "probe $i failed; sleeping 150s"
  sleep 150
done
log "gave up"
exit 1
