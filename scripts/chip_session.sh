#!/bin/bash
# Full chip session: probes the tunneled TPU until it answers, then runs
# the complete on-hardware evidence pass in order of value:
#   1. scoreboard   -> regenerates docs/TPU_RESULTS.md (platform=tpu rows)
#   2. config sweep -> docs/sweep_r3.log (dedup x batch stream SEPS)
#   3. acceptance   -> docs/acceptance_tpu_r3.log (planted-SBM training)
#   4. headline     -> docs/headline_r3.log (repo-root bench.py)
# Never hard-kill a running TPU process (a kill wedges the chip ~10+ min;
# see docs/TPU_MEASUREMENTS_R3.md "Operational notes").
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
log(){ echo "[chip-session] $*"; }
for i in $(seq 1 "${CHIP_SESSION_PROBES:-400}"); do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
jnp.zeros(8).block_until_ready()
assert jax.devices()[0].platform == 'tpu'" >/dev/null 2>&1; then
    log "chip answered on probe $i at $(date -u +%H:%M:%S)"
    sleep 10
    log "=== scoreboard ==="
    QUIVER_BENCH_TIMEOUT="${QUIVER_BENCH_TIMEOUT:-2400}" python -m benchmarks.scoreboard
    log "=== sweep ==="
    QUIVER_BENCH_SUPERVISED=1 timeout 3600 python -m benchmarks.sweep_sampler --stream 64 > docs/sweep_r3.log 2>&1
    log "sweep rc=$? (docs/sweep_r3.log)"
    log "=== acceptance training (planted SBM) ==="
    timeout 2400 python -m examples.train_sage --dataset planted:50000 --epochs 3 > docs/acceptance_tpu_r3.log 2>&1
    log "acceptance rc=$? (docs/acceptance_tpu_r3.log)"
    log "=== headline bench.py ==="
    timeout 2400 python bench.py > docs/headline_r3.log 2>&1
    log "headline rc=$? (docs/headline_r3.log)"
    log "done at $(date -u +%H:%M:%S)"
    exit 0
  fi
  log "probe $i failed at $(date -u +%H:%M:%S); sleeping 150s"
  sleep 150
done
log "gave up"
exit 1
