"""Outer watchdog for scripts/mega_session.py.

The session holds one device grant and is not interruptible in-process when
a device RPC wedges, so wall-budget enforcement lives here:

* launch the session appending to a log;
* if ``INIT_OK`` does not appear within ``--init-timeout``, the plugin is
  blocked waiting for a grant — kill it (safe: no grant held) and retry
  after a backoff;
* once initialized, watch the ``START <key> budget=<s>`` / ``DONE <key>``
  lines: a job over budget+grace means a wedged RPC — SIGINT, grace,
  SIGTERM, then a longer backoff (the chip may need ~10 min to recover);
* the session skips done jobs and abandons twice-wedged ones via its state
  file, so restarts converge; exit when a session reports ALL DONE /
  PASS COMPLETE, or when the total wall budget runs out.
"""

import argparse
import os
import re
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(f"[mega-loop] {time.strftime('%H:%M:%S')} {msg}", flush=True)


def tail_lines(path, pos):
    """New complete lines since byte offset pos -> (lines, new_pos)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(pos)
            chunk = fh.read()
    except OSError:
        return [], pos
    if not chunk:
        return [], pos
    keep = chunk.rfind(b"\n")
    if keep < 0:
        return [], pos
    lines = chunk[: keep + 1].decode("utf-8", "replace").splitlines()
    return lines, pos + keep + 1


_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "jnp.zeros(8).block_until_ready();"
    "print(jax.devices()[0].platform, flush=True)"
)


def start_probe():
    """Launch a fresh-process grant probe, non-blocking.

    Used only to disambiguate patient-mode stalls: grants flowing while
    the session stays blocked at init means the session's pending request
    was dropped server-side and a relaunch will succeed immediately.
    """
    return subprocess.Popen([sys.executable, "-c", _PROBE_SRC],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)


def finish_probe(proc):
    """(ok, detail) for an EXITED probe. ok requires a real TPU platform —
    a CPU-fallback init is not a grant (mega_session rejects it too)."""
    try:
        out, err = proc.communicate(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        return False, "probe unreapable"
    if proc.returncode == 0 and "tpu" in (out or ""):
        return True, out.strip()
    return False, ((err or out or "").strip()[-200:]
                   or f"rc={proc.returncode}")


def kill_tree(proc, grace=45):
    try:
        proc.send_signal(signal.SIGINT)
    except OSError:
        return
    try:
        proc.wait(grace)
        return
    except subprocess.TimeoutExpired:
        pass
    try:
        proc.terminate()
        proc.wait(30)
    except (OSError, subprocess.TimeoutExpired):
        try:
            proc.kill()
        except OSError:
            pass


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--log", default=os.path.join(REPO, "docs",
                                                 "mega_session_r04.log"))
    # patient defaults (r4 window postmortem): a session blocked at init
    # holds no grant but DOES hold a place in the tunnel's queue; killing
    # waiting clients correlates with perpetual starvation, so the init
    # window is hours, with side probes to catch dead pending requests
    p.add_argument("--init-timeout", type=float, default=7200)
    p.add_argument("--grace", type=float, default=300,
                   help="wall grace on top of each job's in-process budget")
    p.add_argument("--retry-sleep", type=float, default=600)
    p.add_argument("--wedge-sleep", type=float, default=300)
    p.add_argument("--max-hours", type=float, default=9)
    p.add_argument("--probe-after", type=float, default=900,
                   help="side-probe the tunnel once the session has been "
                        "stuck at init this long (0 disables)")
    p.add_argument("--probe-interval", type=float, default=600)
    p.add_argument("--probe-timeout", type=float, default=120)
    p.add_argument("--probe-confirm", type=float, default=180,
                   help="after a SUCCESSFUL side probe, give the stuck "
                        "session this long to initialize before declaring "
                        "its pending grant request dead and relaunching")
    p.add_argument("--session-args", nargs=argparse.REMAINDER, default=[])
    args = p.parse_args()

    if args.probe_after and args.probe_after >= args.init_timeout:
        log(f"note: --probe-after {args.probe_after:.0f} >= --init-timeout "
            f"{args.init_timeout:.0f}; side probes will never fire")

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        log(f"attempt {attempt}: launching mega_session")
        logfh = open(args.log, "ab")
        pos = logfh.seek(0, 2)
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "scripts", "mega_session.py")]
            + args.session_args,
            stdout=logfh, stderr=subprocess.STDOUT, cwd=REPO,
        )
        t_start = time.time()
        inited = False
        job = None  # (key, budget, started_at)
        outcome = None
        last_probe = probe_t0 = 0.0
        probe_ok_at = None
        probe = None
        while True:
            rc = proc.poll()
            lines, pos = tail_lines(args.log, pos)
            for ln in lines:
                if "INIT_OK" in ln:
                    inited = True
                    log(f"session initialized: {ln.strip()[-80:]}")
                m = re.search(r"START (\S+) budget=(\d+)", ln)
                if m:
                    job = (m.group(1), float(m.group(2)), time.time())
                if re.search(r"DONE \S+", ln):
                    job = None
                if "INIT_FAILED" in ln:
                    outcome = "init-failed"
                if "ALL DONE" in ln or "PASS COMPLETE" in ln:
                    outcome = "complete"
            if rc is not None:
                if outcome is None:
                    outcome = f"exited rc={rc}"
                break
            if not inited and time.time() - t_start > args.init_timeout:
                log("no INIT_OK within budget — grant starved; killing "
                    "(safe: no grant held)")
                kill_tree(proc)
                outcome = "init-timeout"
                break
            if (not inited and args.probe_after
                    and time.time() - t_start > args.probe_after):
                if probe_ok_at and time.time() - probe_ok_at > args.probe_confirm:
                    log("grants flow (side probe ok) but the session is "
                        "still blocked — its pending request is dead; "
                        "relaunching now")
                    kill_tree(proc)
                    outcome = "stale-pending"
                    break
                if probe is not None:
                    # reap or time out the in-flight probe WITHOUT blocking
                    # the monitor; never SIGKILL a grant-waiting client
                    # (the r3/r4 wedge pattern) — kill_tree INTs first
                    if probe.poll() is not None:
                        ok, detail = finish_probe(probe)
                        log(f"side probe: {'ok ' + detail if ok else detail}")
                        if ok:
                            probe_ok_at = time.time()
                        probe = None
                    elif time.time() - probe_t0 > args.probe_timeout:
                        log(f"side probe starved > {args.probe_timeout:.0f}s")
                        kill_tree(probe, grace=15)
                        probe = None
                elif (not probe_ok_at
                        and time.time() - last_probe > args.probe_interval):
                    last_probe = probe_t0 = time.time()
                    probe = start_probe()
            if job and time.time() - job[2] > job[1] + args.grace:
                log(f"job {job[0]} exceeded {job[1]:.0f}s+{args.grace:.0f}s "
                    "wall — wedged RPC; killing session")
                kill_tree(proc)
                outcome = "wedged"
                break
            if time.time() > deadline:
                log("wall budget exhausted mid-session; stopping it")
                kill_tree(proc)
                outcome = "deadline"
                break
            time.sleep(15)
        if probe is not None and probe.poll() is None:
            kill_tree(probe, grace=15)
        logfh.close()
        log(f"attempt {attempt} outcome: {outcome}")
        if outcome == "complete":
            log("pass complete")
            try:
                subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "scripts", "window_digest.py"),
                     "--round", os.environ.get("ROUND", "r04")],
                    timeout=120, cwd=REPO,
                )
            except Exception as e:  # noqa: BLE001 — digest is best-effort
                log(f"digest generation failed: {e}")
            return 0
        if outcome == "deadline":
            break
        sleep = (args.wedge_sleep if outcome == "wedged"
                 else 5 if outcome == "stale-pending"
                 # clean fast-fail init: the plugin already waited out its
                 # internal retry window; relaunch promptly to keep a
                 # pending request in the tunnel's queue at all times
                 else 30 if outcome == "init-failed"
                 else args.retry_sleep)
        log(f"sleeping {sleep:.0f}s before retry")
        time.sleep(sleep)
    log("wall budget exhausted")
    return 1


if __name__ == "__main__":
    sys.exit(main())
