"""Single-chip GraphSAGE training — the framework's acceptance example.

Parity with the reference's canonical example (torch-quiver
examples/pyg/reddit_quiver.py): build topology, a [25,10] neighbor sampler,
a 20%-cached feature store, a 2-layer SAGE model, and train with the
"Epoch xx, Loss ..., Approx. Train Acc ..." progress line (README.md:76-78
success criterion). Runs on a synthetic Reddit-scale power-law graph so no
dataset download is needed; point --nodes/--avg-degree at your own scale or
load a real graph with CSRTopo(edge_index=...).

    python -m examples.train_sage                  # Reddit scale (~20s/epoch compile+run)
    python -m examples.train_sage --nodes 20000 --avg-degree 12 --epochs 2   # smoke
"""

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, Feature, GraphSageSampler
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.train import make_eval_step, make_train_step
from quiver_tpu.utils.graphgen import generate_pareto_graph


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=232_965)  # Reddit scale
    p.add_argument("--avg-degree", type=float, default=100.0)
    p.add_argument("--feature-dim", type=int, default=602)  # Reddit: 602
    p.add_argument("--classes", type=int, default=41)  # Reddit: 41
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--fanout", type=int, nargs="+", default=[25, 10])
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--cache-ratio", type=float, default=0.2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    rng = np.random.default_rng(args.seed)
    print(f"building synthetic graph ({args.nodes} nodes)...")
    topo = CSRTopo(edge_index=generate_pareto_graph(args.nodes, args.avg_degree,
                                                    seed=args.seed))
    n = topo.node_count

    # quiver.Feature equivalent: degree-ordered 20% HBM cache, cold rows on host
    feat = rng.normal(size=(n, args.feature_dim)).astype(np.float32)
    budget = int(args.cache_ratio * n) * args.feature_dim * 4
    feature = Feature(device_cache_size=budget, csr_topo=topo).from_cpu_tensor(feat)
    del feat
    labels_all = jnp.asarray(rng.integers(0, args.classes, n).astype(np.int32))
    train_idx = rng.permutation(n)[: max(args.batch, n // 10)]

    sampler = GraphSageSampler(topo, args.fanout, seed_capacity=args.batch,
                               seed=args.seed, frontier_caps="auto")
    model = GraphSAGE(hidden=args.hidden, num_classes=args.classes,
                      num_layers=len(args.fanout))
    tx = optax.adam(args.lr)
    train_step = jax.jit(make_train_step(model, tx))
    eval_step = jax.jit(make_eval_step(model))

    out = sampler.sample(train_idx[: args.batch])
    x = feature[out.n_id]
    params = model.init({"params": jax.random.PRNGKey(args.seed)}, x, out.adjs)[
        "params"]
    opt_state = tx.init(params)

    step_i = 0
    for epoch in range(1, args.epochs + 1):
        t0 = time.time()
        order = np.random.default_rng(epoch).permutation(train_idx)
        losses, correct, total = [], 0, 0
        for lo in range(0, len(order) - args.batch + 1, args.batch):
            seeds = order[lo : lo + args.batch]
            out = sampler.sample(seeds)
            x = feature[out.n_id]
            seed_ids = out.n_id[: args.batch]
            labels = labels_all[jnp.clip(seed_ids, 0)]
            mask = seed_ids >= 0
            params, opt_state, loss = train_step(
                params, opt_state, x, out.adjs, labels, mask,
                jax.random.PRNGKey(step_i))
            losses.append(float(loss))
            c, t = eval_step(params, x, out.adjs, labels, mask)
            correct += int(c)
            total += int(t)
            step_i += 1
        print(
            f"Epoch {epoch:02d}, Loss: {np.mean(losses):.4f}, "
            f"Approx. Train Acc: {correct / max(total, 1):.4f} "
            f"({time.time() - t0:.1f}s)"
        )


if __name__ == "__main__":
    main()
