"""Single-chip GraphSAGE training — the framework's acceptance example.

Parity with the reference's canonical example (torch-quiver
examples/pyg/reddit_quiver.py): build topology, a [25,10] neighbor sampler,
a 20%-cached feature store, a 2-layer SAGE model, train with the
"Epoch xx, Loss ..., Approx. Train Acc ..." progress line (README.md:76-78
success criterion), then report held-out test accuracy.

Datasets (quiver_tpu.datasets):
    --dataset synthetic            random power-law graph, random labels
                                   (throughput exercise; accuracy ~1/C)
    --dataset planted[:n[:C]]      stochastic-block-model acceptance graph —
                                   test accuracy must clear feature-only
                                   Bayes by a wide margin
    --dataset reddit --root DIR    PyG Reddit npz layout (reference's
                                   reddit_quiver.py workload; expect ~0.93+)
    --dataset ogbn-products --root DIR   OGB raw CSV layout

    python -m examples.train_sage --dataset planted:20000 --epochs 4
    python -m examples.train_sage --dataset reddit --root /data/Reddit/raw
"""

import argparse
import time

import numpy as np

from quiver_tpu.utils.backend import honor_forced_platform

honor_forced_platform()  # an explicit JAX_PLATFORMS=cpu must win over sitecustomize

import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, Feature, GraphSageSampler
from quiver_tpu.datasets import GraphDataset, load_dataset
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.train import make_eval_step, make_train_step
from quiver_tpu.utils.graphgen import generate_pareto_graph


def synthetic_dataset(args) -> GraphDataset:
    rng = np.random.default_rng(args.seed)
    topo = CSRTopo(
        edge_index=generate_pareto_graph(args.nodes, args.avg_degree, seed=args.seed)
    )
    n = topo.node_count
    labels = rng.integers(0, args.classes, n).astype(np.int32)
    feat = rng.normal(size=(n, args.feature_dim)).astype(np.float32)
    perm = rng.permutation(n)
    return GraphDataset(
        name="synthetic", topo=topo, features=feat, labels=labels,
        train_idx=perm[: n // 10], val_idx=perm[n // 10 : n // 5],
        test_idx=perm[n // 5 : n // 2], num_classes=args.classes,
    )


def evaluate_layerwise(model, params, topo, feature, labels_all, idx):
    """Full-neighbor layer-wise inference over the whole graph — the
    reference's ``model.inference`` evaluation path (reddit_quiver.py:68-92),
    rebuilt as chunked segment aggregation (models/inference.py). Features
    are streamed back out of the tiered store in blocks, so the cold tier is
    exercised too."""
    from quiver_tpu.models.inference import sage_layerwise_inference

    n, _ = feature.shape
    block = 65536
    # one concatenate = one full copy at a transient 2x footprint; eager
    # .at[].set would copy the whole array once per block (O(N^2) traffic)
    x_all = jnp.concatenate([
        feature[jnp.arange(lo, min(lo + block, n))]
        for lo in range(0, n, block)
    ])
    logp = sage_layerwise_inference(model, params, topo, x_all)
    idx = jnp.asarray(idx)
    pred = jnp.argmax(logp[idx], axis=-1)
    return float((pred == labels_all[idx]).mean())


def evaluate(sampler, feature, eval_step, params, labels_all, idx, batch):
    """Batched accuracy over a node-id split (reference test() loop parity)."""
    correct = total = 0
    for lo in range(0, len(idx), batch):
        seeds = idx[lo : lo + batch]
        out = sampler.sample(seeds)
        x = feature[out.n_id]
        # logits span the padded seed capacity; lanes past batch_size hold
        # frontier nodes (not -1), so mask by the true batch size
        cap = out.adjs[-1].size[1]
        seed_ids = out.n_id[:cap]
        labels = labels_all[jnp.clip(seed_ids, 0)]
        mask = (jnp.arange(cap) < out.batch_size) & (seed_ids >= 0)
        c, t = eval_step(params, x, out.adjs, labels, mask)
        correct += int(c)
        total += int(t)
    return correct / max(total, 1)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dataset", default="synthetic",
                   help="synthetic | planted[:n[:C]] | reddit | ogbn-* ")
    p.add_argument("--root", default=None, help="on-disk dataset directory")
    p.add_argument("--nodes", type=int, default=232_965)  # Reddit scale
    p.add_argument("--avg-degree", type=float, default=100.0)
    p.add_argument("--feature-dim", type=int, default=602)  # Reddit: 602
    p.add_argument("--classes", type=int, default=41)  # Reddit: 41
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--fanout", type=int, nargs="+", default=[25, 10])
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--cache-ratio", type=float, default=0.2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--bf16", action="store_true",
        help="bfloat16 feature storage + mixed-precision model compute",
    )
    p.add_argument(
        "--save-dir", default=None,
        help="checkpoint directory (atomic manifest Checkpointer): "
        "training resumes "
        "from the latest checkpoint there and saves each epoch — the "
        "checkpoint/resume capability the reference has none of",
    )
    p.add_argument(
        "--eval", default="sampled", choices=["sampled", "layerwise"],
        help="test-time evaluation: batched sampled fanout (fast) or "
        "full-neighbor layer-wise inference over all edges (the "
        "reference's model.inference path)",
    )
    args = p.parse_args(argv)

    if args.dataset == "synthetic":
        ds = synthetic_dataset(args)
    else:
        ds = load_dataset(args.dataset, root=args.root)
    topo, n = ds.topo, ds.node_count
    print(f"{ds.name}: {n} nodes, {topo.edge_count} edges, "
          f"{ds.feature_dim} features, {ds.num_classes} classes, "
          f"{len(ds.train_idx)} train / {len(ds.test_idx)} test")

    # quiver.Feature equivalent: degree-ordered 20% HBM cache, cold rows on host
    budget = int(args.cache_ratio * n) * ds.feature_dim * 4
    feature = Feature(
        device_cache_size=budget, csr_topo=topo,
        dtype="bfloat16" if args.bf16 else None,
    ).from_cpu_tensor(ds.features)
    # drop the source array: the tiered store holds the only copy now
    # (for Reddit/products scale this halves peak host memory)
    ds = ds._replace(features=None)
    labels_all = jnp.asarray(ds.labels)
    train_idx = np.asarray(ds.train_idx)

    sampler = GraphSageSampler(topo, args.fanout, seed_capacity=args.batch,
                               seed=args.seed, frontier_caps="auto")
    model = GraphSAGE(hidden=args.hidden, num_classes=ds.num_classes,
                      num_layers=len(args.fanout),
                      dtype="bfloat16" if args.bf16 else None)
    tx = optax.adam(args.lr)
    train_step = jax.jit(make_train_step(model, tx))
    eval_step = jax.jit(make_eval_step(model))

    out = sampler.sample(train_idx[: args.batch])
    x = feature[out.n_id]
    params = model.init({"params": jax.random.PRNGKey(args.seed)}, x, out.adjs)[
        "params"]
    opt_state = tx.init(params)

    ckpt = start_epoch = None
    if args.save_dir:
        from quiver_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(args.save_dir)
        start_epoch = ckpt.latest_step()
        if start_epoch is not None:
            state = ckpt.restore(template={
                "params": params, "opt_state": opt_state,
            })
            params, opt_state = state["params"], state["opt_state"]
            print(f"resumed from {args.save_dir} at epoch {start_epoch}")

    step_i = 0
    for epoch in range(1, args.epochs + 1):
        if start_epoch is not None and epoch <= start_epoch:
            continue  # already trained in a previous run
        t0 = time.time()
        order = np.random.default_rng(epoch).permutation(train_idx)
        losses, correct, total = [], 0, 0
        for lo in range(0, len(order) - args.batch + 1, args.batch):
            seeds = order[lo : lo + args.batch]
            out = sampler.sample(seeds)
            x = feature[out.n_id]
            seed_ids = out.n_id[: args.batch]
            labels = labels_all[jnp.clip(seed_ids, 0)]
            mask = seed_ids >= 0
            params, opt_state, loss = train_step(
                params, opt_state, x, out.adjs, labels, mask,
                jax.random.PRNGKey(step_i))
            losses.append(float(loss))
            c, t = eval_step(params, x, out.adjs, labels, mask)
            correct += int(c)
            total += int(t)
            step_i += 1
        print(
            f"Epoch {epoch:02d}, Loss: {np.mean(losses):.4f}, "
            f"Approx. Train Acc: {correct / max(total, 1):.4f} "
            f"({time.time() - t0:.1f}s)"
        )
        if ckpt is not None:
            ckpt.save(epoch, {"params": params, "opt_state": opt_state})

    if ckpt is not None:
        ckpt.wait_until_finished()

    if args.eval == "layerwise":
        test_acc = evaluate_layerwise(
            model, params, topo, feature, labels_all, np.asarray(ds.test_idx)
        )
    else:
        test_acc = evaluate(
            sampler, feature, eval_step, params, labels_all,
            np.asarray(ds.test_idx), args.batch,
        )
    line = f"Test Acc: {test_acc:.4f}"
    if "feature_bayes_acc" in ds.meta:
        line += f" (feature-only Bayes: {ds.meta['feature_bayes_acc']:.4f})"
    print(line)
    return test_acc, ds


if __name__ == "__main__":
    main()
