"""GraphSAINT subgraph training — end-to-end.

The reference *planned* GraphSAINT (``qv.saint_subgraph`` survives only as a
commented-out test block, SURVEY §2.5); quiver-tpu ships it trainable: a
SAINT sampler draws one induced subgraph per step (ONE compiled program —
draw, dedup, induction all on device, sampling/saint.py), a GraphSAGE model
runs full message passing over the subgraph (the same padded-Adj layers the
neighbor-sampling path uses — a square (C, C) Adj applied at every layer),
and the GraphSAINT loss normalization (``estimate_saint_norm``) unbiases the
node-sampling distribution per Zeng et al. eq. 2.

Acceptance: on the planted-SBM dataset the SAINT-trained model must clear
feature-only Bayes, like the neighbor-sampling path (tests/test_datasets.py).

    python -m examples.train_saint --dataset planted:8000:6 --steps 300
    python -m examples.train_saint --sampler rw --roots 256 --walk-length 3
"""

import argparse
import time

import numpy as np

from quiver_tpu.utils.backend import honor_forced_platform

honor_forced_platform()  # an explicit JAX_PLATFORMS=cpu must win over sitecustomize

import jax
import jax.numpy as jnp
import optax

from quiver_tpu import (
    Adj,
    SAINTEdgeSampler,
    SAINTNodeSampler,
    SAINTRandomWalkSampler,
)
from quiver_tpu.datasets import load_dataset
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.sampling.saint import estimate_saint_norm


def subgraph_adjs(sub, num_layers: int):
    """Full subgraph message passing: the same square (C, C) Adj at every
    layer (every layer sees all induced edges — GraphSAINT's GCN-style
    regime, vs the neighbor sampler's shrinking bipartite frontiers)."""
    C = sub.node_id.shape[0]
    # edge_index rows are (src_local, dst_local); layers' models expect
    # [source, target] with -1 invalid lanes — already the case
    adj = Adj(sub.edge_index, None, (C, C))
    return [adj] * num_layers


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dataset", default="planted:8000:6")
    p.add_argument("--root", default=None)
    p.add_argument("--sampler", default="node", choices=["node", "edge", "rw"])
    p.add_argument("--budget", type=int, default=1024)
    p.add_argument("--roots", type=int, default=256)
    p.add_argument("--walk-length", type=int, default=3)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--norm-iters", type=int, default=30,
                   help="pre-sampling draws for the loss-normalization "
                   "estimate (0 disables normalization)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    ds = load_dataset(args.dataset, root=args.root)
    topo, n = ds.topo, ds.node_count
    print(f"{ds.name}: {n} nodes, {topo.edge_count} edges, "
          f"{ds.num_classes} classes")

    if args.sampler == "node":
        sampler = SAINTNodeSampler(topo, budget=args.budget, seed=args.seed)
    elif args.sampler == "edge":
        sampler = SAINTEdgeSampler(topo, budget=args.budget, seed=args.seed)
    else:
        sampler = SAINTRandomWalkSampler(
            topo, roots=args.roots, walk_length=args.walk_length,
            seed=args.seed,
        )

    # GraphSAINT loss normalization: node_norm[v] ~ 1 / P(v in subgraph)
    if args.norm_iters > 0:
        norm, _ = estimate_saint_norm(sampler, num_iters=args.norm_iters)
        # nodes unseen in the pre-sampling draws report norm 0 — default
        # them to 1 so they still train when they DO appear in a subgraph
        norm = np.where(norm > 0, norm, 1.0).astype(np.float32)
        node_norm = jnp.asarray(norm)
    else:
        node_norm = jnp.ones(n, jnp.float32)

    feats_all = jnp.asarray(ds.features)
    labels_all = jnp.asarray(ds.labels)
    train_mask_all = jnp.zeros(n, bool).at[jnp.asarray(ds.train_idx)].set(True)

    model = GraphSAGE(hidden=args.hidden, num_classes=ds.num_classes,
                      num_layers=args.layers)
    tx = optax.adam(args.lr)

    sub0 = sampler.sample()
    adjs0 = subgraph_adjs(sub0, args.layers)
    x0 = feats_all[jnp.clip(sub0.node_id, 0)]
    params = model.init({"params": jax.random.PRNGKey(args.seed)}, x0, adjs0)[
        "params"]
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, node_id, edge_index, key):
        ids = jnp.clip(node_id, 0)
        x = feats_all[ids]
        labels = labels_all[ids]
        C = node_id.shape[0]
        adjs = [Adj(edge_index, None, (C, C))] * args.layers
        # loss over TRAIN subgraph nodes, weighted by the SAINT node norm
        w = (
            (node_id >= 0)
            & train_mask_all[ids]
        ).astype(jnp.float32) * node_norm[ids]

        def loss_fn(p):
            logp = model.apply({"params": p}, x, adjs, train=True,
                               rngs={"dropout": key})
            ll = jnp.take_along_axis(
                logp, labels[:, None].astype(jnp.int32), axis=1
            )[:, 0]
            return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    t0 = time.time()
    for i in range(args.steps):
        sub = sampler.sample()
        params, opt_state, loss = step(
            params, opt_state, sub.node_id, sub.edge_index,
            jax.random.PRNGKey(1000 + i),
        )
        if (i + 1) % 50 == 0:
            print(f"Step {i + 1:4d}, Loss: {float(loss):.4f} "
                  f"({time.time() - t0:.1f}s)")

    # test accuracy via full-neighbor layer-wise inference over all nodes
    from quiver_tpu.models.inference import sage_layerwise_inference

    logp = sage_layerwise_inference(model, params, topo, ds.features)
    test_idx = jnp.asarray(ds.test_idx)
    pred = jnp.argmax(logp[test_idx], axis=-1)
    acc = float((pred == labels_all[test_idx]).mean())
    line = f"Test Acc: {acc:.4f}"
    if "feature_bayes_acc" in ds.meta:
        line += f" (feature-only Bayes: {ds.meta['feature_bayes_acc']:.4f})"
    print(line)
    return acc, ds


if __name__ == "__main__":
    main()
