"""Heterogeneous R-GCN training on a MAG-style schema.

Capability the reference only gestures at (its GraphSAINT/hetero tests are
rotted stubs, SURVEY §2.5): typed nodes and relations, per-relation neighbor
sampling, relational message passing. Schema mirrors OGB-MAG:
paper-cites-paper, author-writes-paper, inst-employs-author; the task is
paper venue classification.

    python -m examples.train_rgcn_hetero                 # small synthetic MAG
    python -m examples.train_rgcn_hetero --papers 2000   # smoke
"""

import argparse
import time

import numpy as np

from quiver_tpu.utils.backend import honor_forced_platform

honor_forced_platform()  # an explicit JAX_PLATFORMS=cpu must win over sitecustomize

import jax
import jax.numpy as jnp
import optax

from quiver_tpu import HeteroCSRTopo, HeteroFeature, HeteroGraphSampler
from quiver_tpu.models.rgcn import RGCN


def synthetic_mag(rng, n_paper, n_author, n_inst, deg=12):
    edges = {
        ("paper", "cites", "paper"): np.stack([
            rng.integers(0, n_paper, n_paper * deg),
            rng.integers(0, n_paper, n_paper * deg),
        ]),
        ("author", "writes", "paper"): np.stack([
            rng.integers(0, n_author, n_paper * 3),
            rng.integers(0, n_paper, n_paper * 3),
        ]),
        ("inst", "employs", "author"): np.stack([
            rng.integers(0, n_inst, n_author * 2),
            rng.integers(0, n_author, n_author * 2),
        ]),
    }
    num_nodes = {"paper": n_paper, "author": n_author, "inst": n_inst}
    return HeteroCSRTopo(num_nodes, edges), num_nodes


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--papers", type=int, default=20_000)
    p.add_argument("--feature-dim", type=int, default=128)
    p.add_argument("--classes", type=int, default=16)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--fanout", type=int, nargs="+", default=[8, 4])
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    rng = np.random.default_rng(args.seed)
    topo, num_nodes = synthetic_mag(
        rng, args.papers, args.papers // 2, max(args.papers // 40, 4))
    feats = {
        t: rng.normal(size=(c, args.feature_dim)).astype(np.float32)
        for t, c in num_nodes.items()
    }
    feature = HeteroFeature.from_cpu_tensors(feats, device_cache_size="2G")
    labels_all = jnp.asarray(
        rng.integers(0, args.classes, num_nodes["paper"]).astype(np.int32))

    sampler = HeteroGraphSampler(topo, args.fanout, input_type="paper",
                                 seed_capacity=args.batch, seed=args.seed)
    model = RGCN(hidden=args.hidden, num_classes=args.classes,
                 target_type="paper", num_layers=len(args.fanout))

    out = sampler.sample(np.arange(args.batch) % num_nodes["paper"])
    params = model.init({"params": jax.random.PRNGKey(0)}, feature[out.n_id],
                        out.adjs)["params"]
    tx = optax.adam(5e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x_dict, layers, labels, mask, key):
        def loss_fn(p):
            logp = model.apply({"params": p}, x_dict, layers, train=True,
                               rngs={"dropout": key})
            ll = jnp.take_along_axis(logp, jnp.clip(labels, 0)[:, None], axis=1)[:, 0]
            w = mask.astype(logp.dtype)
            return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    t0 = time.time()
    for i in range(args.steps):
        seeds = rng.integers(0, num_nodes["paper"], args.batch)
        out = sampler.sample(seeds)
        seed_ids = out.n_id["paper"][: args.batch]
        labels = labels_all[jnp.clip(seed_ids, 0)]
        mask = seed_ids >= 0
        params, opt_state, loss = step(
            params, opt_state, feature[out.n_id], out.adjs, labels, mask,
            jax.random.PRNGKey(i))
        if i == 0:
            jax.block_until_ready(loss)
            print(f"step 0 (compile): {time.time()-t0:.1f}s")
        elif i % 20 == 0:
            print(f"step {i}: loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
