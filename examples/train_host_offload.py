"""Beyond-HBM training: host-resident topology + cold-tier features.

The papers100M-scale configuration (reference benchmarks/ogbn-papers100M):
graphs and feature tables too large for device memory. The reference's answer
is UVA — GPU kernels dereference pinned host memory over PCIe. The TPU
answer here:

* ``mode="HOST"`` sampler — the big ``indices`` array stays in pinned host
  memory; sampling gathers stage through host compute (only index blocks and
  results cross the PCIe/DMA boundary).
* A small HBM hot tier + pinned-host cold tier for features
  (``device_cache_size`` budget), degree-ordered so the power-law head hits
  HBM.
* ``Prefetcher`` double-buffering so batch i+1's host-side staging overlaps
  batch i's device compute — the latency-hiding role UVA's in-kernel loads
  played.

    python -m examples.train_host_offload                    # ~1M-node demo
    python -m examples.train_host_offload --nodes 50000 --steps 20   # smoke
"""

import argparse
import os
import time

import numpy as np

from quiver_tpu.utils.backend import honor_forced_platform

honor_forced_platform()  # an explicit JAX_PLATFORMS=cpu must win over sitecustomize

import jax

if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
    # sitecustomize pins the TPU plugin before env vars are read; honoring
    # the request via config still works (same as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import optax

from quiver_tpu import Batch, CSRTopo, Feature, GraphSageSampler, Prefetcher
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.train import make_train_step
from quiver_tpu.utils.graphgen import generate_pareto_graph


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=1_000_000)
    p.add_argument("--avg-degree", type=float, default=15.0)
    p.add_argument("--feature-dim", type=int, default=128)
    p.add_argument("--classes", type=int, default=172)  # papers100M: 172
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--fanout", type=int, nargs="+", default=[12, 8])
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--cache-ratio", type=float, default=0.1)
    p.add_argument("--prefetch-depth", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    rng = np.random.default_rng(args.seed)
    print(f"building synthetic graph ({args.nodes} nodes)...")
    topo = CSRTopo(edge_index=generate_pareto_graph(args.nodes, args.avg_degree,
                                                    seed=args.seed))
    n = topo.node_count

    # HOST mode: topology beyond HBM (reference UVA, sage_sampler.py:25-27)
    sampler = GraphSageSampler(topo, args.fanout, mode="HOST",
                               seed_capacity=args.batch, seed=args.seed,
                               frontier_caps="auto")
    feat = rng.normal(size=(n, args.feature_dim)).astype(np.float32)
    budget = int(args.cache_ratio * n) * args.feature_dim * 4
    feature = Feature(device_cache_size=budget, csr_topo=topo).from_cpu_tensor(feat)
    del feat
    labels_all = jnp.asarray(rng.integers(0, args.classes, n).astype(np.int32))

    model = GraphSAGE(hidden=args.hidden, num_classes=args.classes,
                      num_layers=len(args.fanout))
    tx = optax.adam(1e-3)
    step = jax.jit(make_train_step(model, tx))

    out0 = sampler.sample(rng.integers(0, n, args.batch))
    x0 = feature[out0.n_id]
    params = model.init({"params": jax.random.PRNGKey(0)}, x0, out0.adjs)["params"]
    opt_state = tx.init(params)

    def with_labels(seeds, out, x):
        sid = out.n_id[: args.batch]
        return Batch(seeds, out, (x, labels_all[jnp.clip(sid, 0)], sid >= 0))

    stream = (rng.integers(0, n, args.batch) for _ in range(args.steps))
    prefetcher = Prefetcher(sampler, feature, depth=args.prefetch_depth,
                            transform=with_labels)

    t0 = time.time()
    loss = None
    for i, b in enumerate(prefetcher.run(stream)):
        x, labels, mask = b.x
        params, opt_state, loss = step(params, opt_state, x, b.out.adjs,
                                       labels, mask, jax.random.PRNGKey(i))
        if i == 0:
            jax.block_until_ready(loss)
            print(f"step 0 (compile): {time.time()-t0:.1f}s")
            t0 = time.time()
        elif i % 20 == 0:
            print(f"step {i}: loss {float(loss):.4f}")
    jax.block_until_ready(loss)
    per_step = (time.time() - t0) / max(args.steps - 1, 1)
    print(
        f"done: {args.steps} steps at {per_step*1e3:.1f} ms/step "
        f"(cache {feature.cache_ratio:.0%} hot, topology host-resident)"
    )


if __name__ == "__main__":
    main()
