"""Multi-chip SPMD training — the reference's multi-GPU DDP example, TPU-way.

Parity with torch-quiver examples/multi_gpu/pyg/ogb-products/
dist_sampling_ogb_products_quiver.py, which spawns one process per GPU,
splits train_idx per rank, and allreduces gradients over NCCL. Here the
whole thing is ONE fused XLA program over a (data, feature) mesh
(quiver_tpu.parallel.trainer.DistributedTrainer): per-device seed blocks on
the data axis, the hot feature table sharded on the feature axis (the
NVLink-clique role, served by ICI collectives), gradients pmean'd in-program.

On a single-chip machine, simulate a mesh with virtual CPU devices:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m examples.train_multichip --data-axis 4 --feature-axis 2

On a real slice it uses the chips as-is (e.g. --data-axis 2 --feature-axis 2
on a v5e-4).
"""

import argparse
import os
import time

import numpy as np

from quiver_tpu.utils.backend import honor_forced_platform

honor_forced_platform()  # an explicit JAX_PLATFORMS=cpu must win over sitecustomize

import jax

# the image's sitecustomize pins jax to the TPU plugin at startup, which
# defeats a plain JAX_PLATFORMS=cpu env request; honoring it via config
# still works because backend init is lazy (same workaround as tests/conftest.py)
if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, GraphSageSampler, ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer
from quiver_tpu.utils.graphgen import generate_pareto_graph


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=100_000)
    p.add_argument("--avg-degree", type=float, default=25.0)
    p.add_argument("--feature-dim", type=int, default=128)
    p.add_argument("--classes", type=int, default=47)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--local-batch", type=int, default=256)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--data-axis", type=int, default=None)
    p.add_argument("--feature-axis", type=int, default=1)
    p.add_argument("--seed-sharding", default="data", choices=["data", "all"],
                   help="'all': every device a data worker; the sharded "
                   "gather owner-routes via all_to_all (recommended when "
                   "feature-axis > 1 — removes the redundant-sampling cost)")
    p.add_argument("--routed-alpha", type=float, default=2.0,
                   help="capped-bucket factor for the routed gather "
                   "(seed-sharding=all): each all_to_all hop moves "
                   "~alpha*L lanes instead of F*L; overflow is "
                   "fallback-served and reported. 0 = uncapped")
    p.add_argument("--replicate-budget", default="0", metavar="BYTES",
                   help="per-chip byte budget ('4M', '0.5G') for the L0 "
                   "replicated super-hot tier: the top-degree rows live "
                   "in every chip's HBM and are gathered with zero "
                   "interconnect lanes; per-tier hit counts are reported "
                   "after training. 0 = two-tier store")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    n_dev = len(jax.devices())
    mesh = make_mesh(data=args.data_axis, feature=args.feature_axis)
    print(f"mesh over {n_dev} devices: {dict(mesh.shape)}")

    rng = np.random.default_rng(args.seed)
    topo = CSRTopo(edge_index=generate_pareto_graph(args.nodes, args.avg_degree,
                                                    seed=args.seed))
    n = topo.node_count
    feat = rng.normal(size=(n, args.feature_dim)).astype(np.float32)
    # fused trainer needs the table fully device-resident: budget = all rows,
    # sharded over the feature axis (the clique-partitioned hot cache)
    feature = ShardedFeature(
        mesh, device_cache_size=n * args.feature_dim * 4, csr_topo=topo,
        replicate_budget=args.replicate_budget,
    ).from_cpu_tensor(feat)
    del feat
    labels = jnp.asarray(rng.integers(0, args.classes, n).astype(np.int32))

    sampler = GraphSageSampler(topo, args.fanout, seed=args.seed)
    model = GraphSAGE(hidden=args.hidden, num_classes=args.classes,
                      num_layers=len(args.fanout))
    trainer = DistributedTrainer(mesh, sampler, feature, model,
                                 optax.adam(1e-3), local_batch=args.local_batch,
                                 seed_sharding=args.seed_sharding,
                                 routed_alpha=args.routed_alpha or None)
    params, opt_state = trainer.init(jax.random.PRNGKey(args.seed))

    # global batch split over the data axis = train_idx.split(world)[rank]
    global_batch = trainer.global_batch
    t0 = time.time()
    for i in range(args.steps):
        seeds = rng.integers(0, n, global_batch)
        params, opt_state, loss = trainer.step(
            params, opt_state, seeds, labels, jax.random.PRNGKey(1000 + i))
        if i == 0:
            jax.block_until_ready(loss)
            print(f"step 0 (compile): {time.time()-t0:.1f}s")
            t0 = time.time()
        elif i % 10 == 0:
            print(f"step {i}: loss {float(loss):.4f}")
    jax.block_until_ready(loss)
    per_step = (time.time() - t0) / max(args.steps - 1, 1)
    print(
        f"done: {args.steps} steps, global batch {global_batch} "
        f"({per_step*1e3:.1f} ms/step, {global_batch/per_step:,.0f} seeds/s)"
    )
    if args.seed_sharding == "all" and trainer.last_routed_overflow is not None:
        print(f"routed overflow (last step): "
              f"{int(trainer.last_routed_overflow)} lanes fallback-served "
              f"(grow --routed-alpha if persistent)")
    if trainer.last_tier_hits is not None:
        h = np.asarray(trainer.last_tier_hits)
        tot = max(int(h.sum()), 1)
        print(f"feature tier hits (last step): L0 replicated {h[0]} "
              f"({100 * h[0] / tot:.1f}%, zero-comm), sharded {h[1]} "
              f"({100 * h[1] / tot:.1f}%), cold {h[2]} "
              f"({100 * h[2] / tot:.1f}%)")


if __name__ == "__main__":
    main()
