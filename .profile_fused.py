import time, numpy as np, jax, jax.numpy as jnp
from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.sampling.sampler import multilayer_sample
from quiver_tpu.utils.graphgen import generate_pareto_graph

ei = generate_pareto_graph(2_450_000, 50.5, seed=0)
topo_h = CSRTopo(edge_index=ei); del ei
s = GraphSageSampler(topo_h, [15,10,5], seed_capacity=2048, seed=0)
run, caps = s._compiled(2048)
rng = np.random.default_rng(0)
key = jax.random.PRNGKey(0)

seeds = jnp.asarray(rng.integers(0, topo_h.node_count, 2048).astype(np.int32))
ns = jnp.int32(2048)

# warm
out = run(s.topo, seeds, ns, key); jax.block_until_ready(out)
t0=time.time(); iters=10
for i in range(iters):
    out = run(s.topo, seeds, ns, jax.random.fold_in(key, i))
jax.block_until_ready(out)
print(f"fused multilayer, block at end: {(time.time()-t0)/iters*1e3:.1f} ms/iter")

t0=time.time()
for i in range(iters):
    out = run(s.topo, seeds, ns, jax.random.fold_in(key, i))
    jax.block_until_ready(out)
print(f"fused multilayer, block each iter: {(time.time()-t0)/iters*1e3:.1f} ms/iter")

# same but via .sample() host path
t0=time.time()
for i in range(iters):
    o = s.sample(np.asarray(rng.integers(0, topo_h.node_count, 2048)))
    jax.block_until_ready(o.n_id)
print(f".sample() host path, block each: {(time.time()-t0)/iters*1e3:.1f} ms/iter")

# unfused: layer-by-layer in separate jits
from quiver_tpu.ops.sample import sample_layer
from quiver_tpu.ops.reindex import reindex_layer
sl = jax.jit(sample_layer, static_argnums=(3,))
rl = jax.jit(reindex_layer, static_argnums=(3,))
def unfused(topo, seeds, ns, key):
    cur, cn = seeds, ns
    for l,k in enumerate((15,10,5)):
        key, sub = jax.random.split(key)
        nbr, _ = sl(topo, cur, cn, k, sub)
        cur, cn, col, ov = rl(cur, cn, nbr, caps[l])
    return cur, cn
out = unfused(s.topo, seeds, ns, key); jax.block_until_ready(out)
t0=time.time()
for i in range(iters):
    out = unfused(s.topo, seeds, ns, jax.random.fold_in(key, i))
jax.block_until_ready(out)
print(f"unfused per-layer jits: {(time.time()-t0)/iters*1e3:.1f} ms/iter")
