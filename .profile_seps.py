import time, numpy as np, jax, jax.numpy as jnp
from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.utils.graphgen import generate_pareto_graph

t0=time.time()
ei = generate_pareto_graph(2_450_000, 50.5, seed=0)
topo = CSRTopo(edge_index=ei); del ei
print(f"build {time.time()-t0:.1f}s nodes={topo.node_count} edges={topo.edge_count}")
rng = np.random.default_rng(0)

for sizes in ([15], [15,10], [15,10,5]):
    s = GraphSageSampler(topo, sizes, seed_capacity=2048, seed=0)
    run, caps = s._compiled(2048)
    print("sizes", sizes, "caps", caps)
    out = s.sample(rng.integers(0, topo.node_count, 2048))
    jax.block_until_ready(out.n_id)
    t0=time.time(); iters=8
    for _ in range(iters):
        out = s.sample(rng.integers(0, topo.node_count, 2048))
        jax.block_until_ready(out.n_id)
    dt=(time.time()-t0)/iters
    print(f"  {dt*1e3:.1f} ms/iter, n_count={int(out.n_count)}, overflow={int(out.overflow)}")
    for a in out.adjs:
        print("   adj", a.edge_index.shape, "valid", int(jnp.sum(a.edge_index[0]>=0)))
