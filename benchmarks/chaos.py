"""Chaos lane: FaultPlan drills over a tiny epoch — the resilience layer's
evidence job (mega_session ``chaos`` stage, log-only).

Three deterministic drills, each asserting the property the resilience
layer guarantees (quiver_tpu/resilience/):

* **guard**: a NaN-poisoned batch inside the fused step leaves params
  bit-unchanged and the skip counter reads exactly 1;
* **retry**: seeded transient sampler faults are absorbed by the
  Prefetcher's bounded backoff and the delivered stream is bit-identical
  to a fault-free run;
* **preempt/resume**: a simulated kill mid-epoch, then resume() — the
  remaining loss trajectory is bit-identical to the uninterrupted run.

Any drill failure raises (the session marks the job failed); success
prints one ``CHAOS <drill> OK`` line per drill.

    python -m benchmarks.chaos --smoke
"""

import argparse
import tempfile

import numpy as np

from benchmarks import common


def _build_graph(nodes: int, feature_dim: int, seed: int):
    from quiver_tpu import CSRTopo

    rng = np.random.default_rng(seed)
    topo = CSRTopo(
        edge_index=rng.integers(0, nodes, size=(2, 10 * nodes)).astype(
            np.int64
        )
    )
    feat = rng.normal(size=(nodes, feature_dim)).astype(np.float32)
    labels = rng.integers(0, 4, nodes).astype(np.int32)
    return topo, feat, labels


def _build_trainer(topo, feat, local_batch, plan=None, guard=False,
                   checkpoint_dir=None, checkpoint_every=0):
    import optax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.mesh import make_mesh
    from quiver_tpu.parallel.trainer import DistributedTrainer

    mesh = make_mesh()  # data = all devices, feature = 1
    sampler = GraphSageSampler(
        topo, [5, 5], seed=3, seed_capacity=local_batch
    )
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    kw = {}
    if checkpoint_dir is not None:
        kw = dict(checkpoint_dir=checkpoint_dir,
                  checkpoint_every=checkpoint_every)
    return DistributedTrainer(
        mesh, sampler, feature, model, optax.sgd(1e-2),
        local_batch=local_batch, nonfinite_guard=guard, fault_plan=plan,
        **kw
    )


def _tree_equal(a, b) -> bool:
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def drill_guard(topo, feat, labels, local_batch, seed):
    """NaN batch -> cond-skipped update, params preserved, counter = 1."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu import FaultPlan
    from quiver_tpu.obs.registry import GUARD_SKIPPED

    plan = FaultPlan(nan_feature_steps=(1,), nan_rows=8)
    trainer = _build_trainer(topo, feat, local_batch, plan=plan, guard=True)
    params, opt = trainer.init(jax.random.PRNGKey(0))
    lab = jnp.asarray(labels)
    rng = np.random.default_rng(seed)
    for step in range(3):
        p_before = params
        params, opt, loss = trainer.step(
            params, opt, rng.integers(0, topo.node_count,
                                      trainer.global_batch),
            lab, jax.random.PRNGKey(step),
        )
        if step == 1:
            assert not np.isfinite(float(loss)), "poisoned loss was finite"
            assert _tree_equal(params, p_before), \
                "poisoned step mutated params"
            skipped = int(np.asarray(trainer.metrics.value(GUARD_SKIPPED)))
            assert skipped == 1, f"skip counter {skipped} != 1"
        else:
            assert np.isfinite(float(loss)), f"clean step {step} loss NaN"
    common.write_metrics(trainer, drill="chaos-guard")
    common.log("CHAOS guard OK (poisoned step skipped, params preserved)")


def drill_retry(topo, steps, local_batch, seed):
    """Seeded transient sampler faults -> retried, stream bit-identical."""
    from quiver_tpu import FaultPlan, GraphSageSampler
    from quiver_tpu.obs import StepTimeline
    from quiver_tpu.parallel.pipeline import Prefetcher

    plan = FaultPlan.chaos(
        seed=seed, steps=steps, transient_p=0.4, max_transient=2
    )
    if not plan.sampler_faults:
        # a sparse draw must not turn the drill into a no-op
        import dataclasses

        plan = dataclasses.replace(plan, sampler_faults={1: 2})
    seeds = [
        np.random.default_rng(seed + i).integers(
            0, topo.node_count, local_batch
        )
        for i in range(steps)
    ]
    oracle = GraphSageSampler(topo, [5, 5], seed=3,
                              seed_capacity=local_batch)
    clean = [oracle.sample(s) for s in seeds]
    faulty = plan.wrap_sampler(
        GraphSageSampler(topo, [5, 5], seed=3, seed_capacity=local_batch)
    )
    timeline = StepTimeline()
    pf = Prefetcher(faulty, None, depth=2, retries=3, backoff=1e-3,
                    timeline=timeline)
    batches = list(pf.run(seeds))
    assert len(batches) == steps, f"{len(batches)}/{steps} delivered"
    planned = sum(plan.sampler_faults.values())
    assert pf.retries_total == planned, \
        f"retries {pf.retries_total} != planned {planned}"
    for c, b in zip(clean, batches):
        assert np.array_equal(np.asarray(c.n_id), np.asarray(b.out.n_id)), \
            "recovered stream diverged from the fault-free oracle"
    common.log(
        f"CHAOS retry OK ({planned} transient faults absorbed, stream "
        "bit-identical)"
    )


def drill_preempt_resume(topo, feat, labels, local_batch, seed):
    """Kill at a planned step, resume, compare the trajectory bitwise."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu import FaultPlan, Preemption

    lab = jnp.asarray(labels)
    idx = np.random.default_rng(seed).integers(
        0, topo.node_count, 6 * local_batch * jax.device_count()
    )
    with tempfile.TemporaryDirectory() as tmp:
        trainer_a = _build_trainer(
            topo, feat, local_batch, checkpoint_dir=f"{tmp}/a",
            checkpoint_every=2,
        )
        seed_mat = trainer_a.pack_epoch(idx, seed=0)
        key = jax.random.PRNGKey(7)
        pa, oa = trainer_a.init(jax.random.PRNGKey(0))
        pa, oa, losses_a = trainer_a.epoch_scan(pa, oa, seed_mat, lab, key)
        losses_a = np.asarray(losses_a)

        trainer_b = _build_trainer(
            topo, feat, local_batch, checkpoint_dir=f"{tmp}/b",
            checkpoint_every=2, plan=FaultPlan(preempt_at_step=3),
        )
        p0, o0 = trainer_b.init(jax.random.PRNGKey(0))
        preempted = False
        try:
            trainer_b.epoch_scan(p0, o0, seed_mat, lab, key)
        except Preemption:
            preempted = True
        assert preempted, "FaultPlan preemption never fired"
        pr, orr, key_r, step, epoch = trainer_b.resume(p0, o0)
        assert step == 2, f"resumed at step {step}, expected 2"
        pr, orr, losses_r = trainer_b.epoch_scan(
            pr, orr, seed_mat, lab, key_r, epoch=epoch, start_step=step
        )
        losses_r = np.asarray(losses_r)
        assert np.array_equal(
            losses_r.view(np.uint32), losses_a[step:].view(np.uint32)
        ), "resumed loss trajectory diverged"
        assert _tree_equal(pa, pr), "resumed final params diverged"
        trainer_a.checkpointer.close()
        trainer_b.checkpointer.close()
    common.log(
        f"CHAOS preempt/resume OK (killed at step 3, resumed at {step}, "
        f"{losses_r.shape[0]} remaining steps bit-identical)"
    )


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=2000)
    p.add_argument("--feature-dim", type=int, default=16)
    p.add_argument("--local-batch", type=int, default=16)
    p.add_argument("--retry-steps", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="shrink the drills further (rehearsal mode)")
    args = p.parse_args()
    if args.smoke:
        args.nodes = min(args.nodes, 800)
        args.retry_steps = min(args.retry_steps, 4)

    common.init_backend()
    topo, feat, labels = _build_graph(
        args.nodes, args.feature_dim, args.seed
    )

    def body():
        drill_guard(topo, feat, labels, args.local_batch, args.seed)
        drill_retry(topo, args.retry_steps, args.local_batch, args.seed)
        drill_preempt_resume(
            topo, feat, labels, args.local_batch, args.seed
        )
        common.log("CHAOS all drills passed")
        return 0

    return common.run_guarded(body, args)


if __name__ == "__main__":
    raise SystemExit(main())
